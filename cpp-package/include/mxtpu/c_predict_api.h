/*
 * libmxtpu C predict API (parity: include/mxnet/c_predict_api.h).
 *
 * Inference-only C ABI for non-Python consumers: create a predictor
 * from an exported ONNX artifact (mx.contrib.onnx.export_model), feed
 * float32 input, run forward, copy the float32 output out.
 */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* PredictorHandle;

/* Human-readable message for the last failed call (thread-shared). */
const char* MXTPUGetLastError();

/* Create a predictor from an exported .onnx file. Returns 0 on
 * success; the handle stays valid until MXTPUPredFree. */
int MXTPUPredCreate(const char* model_path, PredictorHandle* out);

/* Bind a float32 input tensor (copied). */
int MXTPUPredSetInput(PredictorHandle h, const float* data,
                      const int64_t* shape, int ndim);

/* Run the forward pass; writes the output shape (up to max_ndim). */
int MXTPUPredForward(PredictorHandle h, int64_t* out_shape,
                     int max_ndim, int* out_ndim);

/* Copy the float32 output into `out` (capacity in floats). */
int MXTPUPredGetOutput(PredictorHandle h, float* out,
                       int64_t capacity_floats);

int MXTPUPredFree(PredictorHandle h);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_PREDICT_API_H_ */

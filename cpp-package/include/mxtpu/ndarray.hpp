// mxtpu C++ user API — RAII NDArray over the libmxtpu_train C ABI
// (parity: cpp-package/include/mxnet-cpp/ndarray.h in the reference;
// the op functions in ops.hpp are GENERATED from the live op table by
// scripts/gen_cpp_ops.py, mirroring the reference's generated
// op-wrapper headers).
#ifndef MXTPU_NDARRAY_HPP_
#define MXTPU_NDARRAY_HPP_

#include <mxtpu/c_train_api.h>

#include <cstdint>
#include <numeric>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

namespace mxtpu {

inline void check(int rc, const char* what) {
  if (rc != 0) {
    throw std::runtime_error(std::string(what) + ": " +
                             MXTPUTrainGetLastError());
  }
}

class NDArray {
 public:
  NDArray() : h_(-1) {}
  NDArray(const float* data, const std::vector<int64_t>& shape)
      : h_(-1) {
    check(MXTPUNDArrayCreate(data, shape.data(),
                             static_cast<int>(shape.size()), &h_),
          "NDArrayCreate");
  }
  explicit NDArray(const std::vector<float>& data,
                   const std::vector<int64_t>& shape)
      : NDArray(data.data(), shape) {}

  static NDArray FromHandle(int h) {
    NDArray a;
    a.h_ = h;
    return a;
  }

  NDArray(NDArray&& o) noexcept : h_(o.h_) { o.h_ = -1; }
  NDArray& operator=(NDArray&& o) noexcept {
    if (this != &o) {
      Release();
      h_ = o.h_;
      o.h_ = -1;
    }
    return *this;
  }
  NDArray(const NDArray&) = delete;
  NDArray& operator=(const NDArray&) = delete;
  ~NDArray() { Release(); }

  int handle() const { return h_; }
  bool valid() const { return h_ >= 0; }

  std::vector<int64_t> Shape() const {
    int64_t dims[16];
    int nd = 0;
    check(MXTPUNDArrayShape(h_, dims, 16, &nd), "NDArrayShape");
    return std::vector<int64_t>(dims, dims + nd);
  }

  int64_t Size() const {
    auto s = Shape();
    return std::accumulate(s.begin(), s.end(), int64_t{1},
                           std::multiplies<int64_t>());
  }

  std::vector<float> CopyTo() const {
    std::vector<float> out(static_cast<size_t>(Size()));
    check(MXTPUNDArrayCopyTo(h_, out.data(),
                             static_cast<int64_t>(out.size())),
          "NDArrayCopyTo");
    return out;
  }

  double Scalar() const {
    double v = 0;
    check(MXTPUNDArrayScalar(h_, &v), "NDArrayScalar");
    return v;
  }

  void AttachGrad() {
    check(MXTPUAutogradMarkVariable(h_), "AttachGrad");
  }

  NDArray Grad() const {
    int g = -1;
    check(MXTPUNDArrayGetGrad(h_, &g), "GetGrad");
    return FromHandle(g);
  }

  void Backward() const {
    check(MXTPUAutogradBackward(h_), "Backward");
  }

 private:
  void Release() {
    if (h_ >= 0) MXTPUNDArrayFree(h_);
    h_ = -1;
  }
  int h_;
};

class AutogradRecord {
 public:
  AutogradRecord() { check(MXTPUAutogradSetIsRecording(1), "record"); }
  ~AutogradRecord() { MXTPUAutogradSetIsRecording(0); }
};

class Optimizer {
 public:
  Optimizer(const std::string& name, const std::string& kwargs_json)
      : h_(-1) {
    check(MXTPUOptimizerCreate(name.c_str(), kwargs_json.c_str(), &h_),
          "OptimizerCreate");
  }
  void Update(int index, const NDArray& weight, const NDArray& grad) {
    check(MXTPUOptimizerUpdate(h_, index, weight.handle(),
                               grad.handle()),
          "OptimizerUpdate");
  }

 private:
  int h_;
};

namespace detail {
inline NDArray Invoke(const char* op, std::initializer_list<int> ins,
                      const std::string& kwargs) {
  std::vector<int> hs(ins);
  int out = -1;
  int n = 0;
  check(MXTPUImperativeInvoke(op, hs.data(),
                              static_cast<int>(hs.size()),
                              kwargs.empty() ? "{}" : kwargs.c_str(),
                              &out, 1, &n),
        op);
  return NDArray::FromHandle(out);
}
}  // namespace detail

}  // namespace mxtpu

#endif  // MXTPU_NDARRAY_HPP_

// libmxtpu_train — training-capable C API (parity: the training
// surface of the reference's include/mxnet/c_api.h: NDArray
// create/copy, imperative op invoke by name, autograd, optimizer
// update). All functions return 0 on success, -1 on failure; fetch
// the error text with MXTPUTrainGetLastError().
#ifndef MXTPU_C_TRAIN_API_H_
#define MXTPU_C_TRAIN_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

const char* MXTPUTrainGetLastError();
int MXTPUTrainInit();

/* NDArray: float32 host buffers in, integer handles out. */
int MXTPUNDArrayCreate(const float* data, const int64_t* shape,
                       int ndim, int* out);
int MXTPUNDArrayFree(int h);
int MXTPUNDArrayCopyTo(int h, float* out, int64_t capacity_floats);
int MXTPUNDArrayShape(int h, int64_t* out_shape, int max_ndim,
                      int* out_ndim);
int MXTPUNDArrayScalar(int h, double* out);

/* Invoke any op from the framework's op table by name ("dot",
 * "add", "relu", "npx:log_softmax", ...). Static attrs ride in as a
 * JSON object string. */
int MXTPUImperativeInvoke(const char* op_name, const int* in_handles,
                          int n_in, const char* kwargs_json,
                          int* out_handles, int max_out, int* n_out);

/* Autograd. */
int MXTPUAutogradMarkVariable(int h);
int MXTPUAutogradSetIsRecording(int flag);
int MXTPUAutogradBackward(int loss_handle);
int MXTPUNDArrayGetGrad(int h, int* out_grad);

/* Optimizer: name + JSON hyperparameters -> updater handle;
 * update applies grad to weight in place (per-weight `index` keys the
 * optimizer state, like the reference's kvstore updater). */
int MXTPUOptimizerCreate(const char* name, const char* kwargs_json,
                         int* out);
int MXTPUOptimizerUpdate(int opt, int index, int weight_h, int grad_h);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_TRAIN_API_H_ */

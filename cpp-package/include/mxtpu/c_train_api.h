// libmxtpu_train — training-capable C API (parity: the training
// surface of the reference's include/mxnet/c_api.h: NDArray
// create/copy, imperative op invoke by name, autograd, optimizer
// update). All functions return 0 on success, -1 on failure; fetch
// the error text with MXTPUTrainGetLastError().
#ifndef MXTPU_C_TRAIN_API_H_
#define MXTPU_C_TRAIN_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

const char* MXTPUTrainGetLastError();
int MXTPUTrainInit();

/* NDArray: float32 host buffers in, integer handles out. */
int MXTPUNDArrayCreate(const float* data, const int64_t* shape,
                       int ndim, int* out);
int MXTPUNDArrayFree(int h);
int MXTPUNDArrayCopyTo(int h, float* out, int64_t capacity_floats);
int MXTPUNDArrayShape(int h, int64_t* out_shape, int max_ndim,
                      int* out_ndim);
int MXTPUNDArrayScalar(int h, double* out);

/* Invoke any op from the framework's op table by name ("dot",
 * "add", "relu", "npx:log_softmax", ...). Static attrs ride in as a
 * JSON object string. */
int MXTPUImperativeInvoke(const char* op_name, const int* in_handles,
                          int n_in, const char* kwargs_json,
                          int* out_handles, int max_out, int* n_out);

/* Autograd. */
int MXTPUAutogradMarkVariable(int h);
int MXTPUAutogradSetIsRecording(int flag);
int MXTPUAutogradBackward(int loss_handle);
int MXTPUNDArrayGetGrad(int h, int* out_grad);

/* Optimizer: name + JSON hyperparameters -> updater handle;
 * update applies grad to weight in place (per-weight `index` keys the
 * optimizer state, like the reference's kvstore updater). */
int MXTPUOptimizerCreate(const char* name, const char* kwargs_json,
                         int* out);
int MXTPUOptimizerUpdate(int opt, int index, int weight_h, int grad_h);

/* NDArray save/load in the reference's legacy binary format
 * (parity: MXNDArraySave / MXNDArrayLoad, c_api.cc:1913,1961).
 * names_json: JSON array of names ("[]" saves a nameless list).
 * After Load, MXTPUNDArrayLoadNames yields the names as JSON. */
int MXTPUNDArraySave(const char* fname, const int* handles, int n,
                     const char* names_json);
int MXTPUNDArrayLoad(const char* fname, int* out_handles, int max_out,
                     int* n_out);
int MXTPUNDArrayLoadNames(char* buf, int buflen);

/* CachedOp: run an exported hybridized graph (-symbol.json [+
 * -NNNN.params]) from C (parity: MXCreateCachedOp / MXInvokeCachedOp,
 * src/imperative/cached_op.cc:776). Invoke records on the autograd
 * tape while MXTPUAutogradSetIsRecording(1) is active, so a C host
 * can also TRAIN the graph: get param handles, backward the loss,
 * apply MXTPUOptimizerUpdate per param. */
int MXTPUCachedOpCreate(const char* symbol_file,
                        const char* input_names_json,
                        const char* param_file, int* out);
int MXTPUCachedOpInvoke(int op, const int* in_handles, int n_in,
                        int* out_handles, int max_out, int* n_out);
int MXTPUCachedOpParamNames(int op, char* buf, int buflen);
int MXTPUCachedOpParamGet(int op, const char* name, int* out);
int MXTPUCachedOpParamSet(int op, const char* name, int nd);
int MXTPUCachedOpFree(int op);

/* KVStore (parity: MXKVStoreCreate/Init/Push/Pull/SetOptimizer,
 * c_api.cc:2971). Pull fills a caller-preallocated NDArray. With a
 * set optimizer, push applies the update server-side (update-on-
 * kvstore), and pull returns the updated weights. */
int MXTPUKVStoreCreate(const char* kind, int* out);
int MXTPUKVStoreInit(int kv, int key, int nd);
int MXTPUKVStorePush(int kv, int key, int nd);
int MXTPUKVStorePull(int kv, int key, int out_nd);
int MXTPUKVStoreSetOptimizer(int kv, const char* name,
                             const char* kwargs_json);
int MXTPUKVStoreFree(int kv);

/* DataIter (parity: MXDataIterCreateIter family): NDArrayIter batch
 * feeder. Next returns 1 while batches remain (handles out), 0 at
 * epoch end. */
int MXTPUDataIterCreate(int data_nd, int label_nd, int batch_size,
                        int shuffle, int* out);
int MXTPUDataIterNext(int it, int* out_data, int* out_label);
int MXTPUDataIterReset(int it);
int MXTPUDataIterFree(int it);

/* ---- profiler (parity: c_api_profile.cc family) ---- */
int MXTPUSetProfilerConfig(const char* filename);
int MXTPUSetProfilerState(int state);  /* 0=stop, 1=run */
int MXTPUDumpProfile();

/* ---- sync (parity: MXNDArrayWaitToRead / MXNDArrayWaitAll) ---- */
int MXTPUNDArrayWaitToRead(int h);
int MXTPUNDArrayWaitAll();

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_TRAIN_API_H_ */

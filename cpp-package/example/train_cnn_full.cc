// Full C++ training lifecycle over the libmxtpu_train C API — no
// Python in the host program (round-4 VERDICT task #4):
//
//   synthesize images -> DataIter batches -> CNN forward (convolution/
//   pooling/fully_connected ops) -> autograd backward -> KVStore
//   update-on-push (server-side SGD) -> CHECKPOINT (reference legacy
//   binary via MXTPUNDArraySave) -> free everything -> RELOAD
//   (MXTPUNDArrayLoad) -> evaluate accuracy.
//
// Parity model: the reference cpp-package lenet example
// (cpp-package/example/lenet.cpp) + MXNDArraySave/Load
// (src/c_api/c_api.cc:1913,1961) + MXKVStore* (c_api.cc:2971) +
// MXDataIter* — exercised here through the mxtpu equivalents.
//
// Build (see tests/test_c_train_api.py):
//   g++ -O2 train_cnn_full.cc -I../include -L. -lmxtpu_train
#include <mxtpu/c_train_api.h>

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#define CHECK(call)                                            \
  do {                                                         \
    if ((call) != 0) {                                         \
      std::fprintf(stderr, "FAIL %s: %s\n", #call,             \
                   MXTPUTrainGetLastError());                  \
      return 1;                                                \
    }                                                          \
  } while (0)

namespace {

float frand() { return static_cast<float>(std::rand()) / RAND_MAX; }

// class 0: vertical stripes; class 1: horizontal stripes (+noise) —
// only a conv filter can tell them apart reliably.
void make_dataset(int n, int hw, std::vector<float>* x,
                  std::vector<float>* y) {
  x->assign(static_cast<size_t>(n) * hw * hw, 0.0f);
  y->assign(n, 0.0f);
  for (int i = 0; i < n; ++i) {
    int cls = i % 2;
    (*y)[i] = static_cast<float>(cls);
    for (int r = 0; r < hw; ++r)
      for (int c = 0; c < hw; ++c) {
        int stripe = (cls == 0 ? c : r) % 2;
        (*x)[(static_cast<size_t>(i) * hw + r) * hw + c] =
            stripe ? 1.0f : 0.0f;
      }
  }
}

int make_param(const int64_t* shape, int ndim, float scale, int* out) {
  int64_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  std::vector<float> host(n);
  for (auto& v : host) v = (frand() - 0.5f) * 2.0f * scale;
  return MXTPUNDArrayCreate(host.data(), shape, ndim, out);
}

constexpr int kHW = 8, kFilters = 4, kClasses = 2;
constexpr int kFcIn = kFilters * (kHW / 2) * (kHW / 2);

// forward: conv(3x3 pad 1) -> relu -> maxpool(2x2 s2) -> fc
// params: [conv_w (F,1,3,3), conv_b (F), fc_w (C, F*4*4), fc_b (C)]
// returns logits handle; records temps for the caller to free
int forward(const int* params, int xh, int* out,
            std::vector<int>* temps) {
  int h, n;
  int c_in[3] = {xh, params[0], params[1]};
  if (MXTPUImperativeInvoke(
          "npx:convolution", c_in, 3,
          "{\"kernel\": [3, 3], \"num_filter\": 4, \"pad\": [1, 1]}",
          &h, 1, &n) != 0)
    return -1;
  temps->push_back(h);
  int r_in[1] = {h};
  if (MXTPUImperativeInvoke("npx:relu", r_in, 1, nullptr, &h, 1, &n)
      != 0)
    return -1;
  temps->push_back(h);
  int p_in[1] = {h};
  if (MXTPUImperativeInvoke(
          "npx:pooling", p_in, 1,
          "{\"kernel\": [2, 2], \"stride\": [2, 2],"
          " \"pool_type\": \"max\"}", &h, 1, &n) != 0)
    return -1;
  temps->push_back(h);
  int f_in[3] = {h, params[2], params[3]};
  if (MXTPUImperativeInvoke("npx:fully_connected", f_in, 3,
                            "{\"num_hidden\": 2}", &h, 1, &n) != 0)
    return -1;
  *out = h;
  return 0;
}

}  // namespace

int main() {
  std::srand(11);
  CHECK(MXTPUTrainInit());

  // ---- params ----
  int conv_w, conv_b, fc_w, fc_b;
  {
    int64_t s1[4] = {kFilters, 1, 3, 3};
    CHECK(make_param(s1, 4, 0.3f, &conv_w));
    int64_t s2[1] = {kFilters};
    CHECK(make_param(s2, 1, 0.0f, &conv_b));
    int64_t s3[2] = {kClasses, kFcIn};
    CHECK(make_param(s3, 2, 0.1f, &fc_w));
    int64_t s4[1] = {kClasses};
    CHECK(make_param(s4, 1, 0.0f, &fc_b));
  }
  int params[4] = {conv_w, conv_b, fc_w, fc_b};
  for (int p : params) CHECK(MXTPUAutogradMarkVariable(p));

  // ---- data: one big tensor, batched by the DataIter ----
  const int kN = 64, kBatch = 16;
  std::vector<float> xs, ys;
  make_dataset(kN, kHW, &xs, &ys);
  int data_nd, label_nd;
  {
    int64_t ds[4] = {kN, 1, kHW, kHW};
    CHECK(MXTPUNDArrayCreate(xs.data(), ds, 4, &data_nd));
    int64_t ls[1] = {kN};
    CHECK(MXTPUNDArrayCreate(ys.data(), ls, 1, &label_nd));
  }
  int it;
  CHECK(MXTPUDataIterCreate(data_nd, label_nd, kBatch, /*shuffle=*/0,
                            &it));

  // ---- kvstore with server-side SGD (update-on-push) ----
  int kv;
  CHECK(MXTPUKVStoreCreate("local", &kv));
  CHECK(MXTPUKVStoreSetOptimizer(kv, "sgd",
                                 "{\"learning_rate\": 0.25}"));
  for (int i = 0; i < 4; ++i) CHECK(MXTPUKVStoreInit(kv, i, params[i]));

  // ---- training loop ----
  double first_loss = -1.0, last_loss = -1.0;
  for (int epoch = 0; epoch < 12; ++epoch) {
    CHECK(MXTPUDataIterReset(it));
    int bx, by, more;
    while ((more = MXTPUDataIterNext(it, &bx, &by)) == 1) {
      std::vector<int> temps;
      CHECK(MXTPUAutogradSetIsRecording(1));
      int logits;
      if (forward(params, bx, &logits, &temps) != 0) {
        std::fprintf(stderr, "forward FAIL: %s\n",
                     MXTPUTrainGetLastError());
        return 1;
      }
      temps.push_back(logits);
      int h, n;
      int ls_in[1] = {logits};
      CHECK(MXTPUImperativeInvoke("npx:log_softmax", ls_in, 1,
                                  "{\"axis\": -1}", &h, 1, &n));
      temps.push_back(h);
      int pk_in[2] = {h, by};
      CHECK(MXTPUImperativeInvoke("npx:pick", pk_in, 2,
                                  "{\"axis\": -1}", &h, 1, &n));
      temps.push_back(h);
      int mn_in[1] = {h};
      CHECK(MXTPUImperativeInvoke("mean", mn_in, 1, nullptr, &h, 1,
                                  &n));
      temps.push_back(h);
      int ng_in[1] = {h};
      int loss;
      CHECK(MXTPUImperativeInvoke("negative", ng_in, 1, nullptr, &loss,
                                  1, &n));
      CHECK(MXTPUAutogradSetIsRecording(0));
      CHECK(MXTPUAutogradBackward(loss));

      // push grads; server applies SGD; pull refreshed weights
      for (int i = 0; i < 4; ++i) {
        int g;
        CHECK(MXTPUNDArrayGetGrad(params[i], &g));
        CHECK(MXTPUKVStorePush(kv, i, g));
        CHECK(MXTPUKVStorePull(kv, i, params[i]));
        CHECK(MXTPUNDArrayFree(g));
      }

      double lv;
      CHECK(MXTPUNDArrayScalar(loss, &lv));
      if (first_loss < 0) first_loss = lv;
      last_loss = lv;
      for (int t : temps) CHECK(MXTPUNDArrayFree(t));
      CHECK(MXTPUNDArrayFree(loss));
      CHECK(MXTPUNDArrayFree(bx));
      CHECK(MXTPUNDArrayFree(by));
    }
    if (more < 0) return 1;
    if (epoch % 4 == 0)
      std::printf("epoch %d loss %.4f\n", epoch, last_loss);
  }
  std::printf("first %.4f final %.4f\n", first_loss, last_loss);
  if (!(last_loss < first_loss * 0.3) || !std::isfinite(last_loss)) {
    std::fprintf(stderr, "TRAINING DID NOT CONVERGE\n");
    return 2;
  }

  // ---- checkpoint (reference legacy binary) ----
  const char* ckpt = "cnn_checkpoint.params";
  CHECK(MXTPUNDArraySave(
      ckpt, params, 4,
      "[\"conv_w\", \"conv_b\", \"fc_w\", \"fc_b\"]"));
  for (int p : params) CHECK(MXTPUNDArrayFree(p));

  // ---- reload ----
  int loaded[8], n_loaded = 0;
  CHECK(MXTPUNDArrayLoad(ckpt, loaded, 8, &n_loaded));
  if (n_loaded != 4) {
    std::fprintf(stderr, "expected 4 arrays, got %d\n", n_loaded);
    return 2;
  }
  char names[256];
  CHECK(MXTPUNDArrayLoadNames(names, sizeof(names)));
  // order params by saved name (dict order is load order here, but
  // re-derive from the names JSON to be explicit)
  const char* want[4] = {"conv_w", "conv_b", "fc_w", "fc_b"};
  int reparams[4] = {-1, -1, -1, -1};
  std::string nj(names);
  for (int i = 0; i < 4; ++i) {
    size_t pos = 0;
    int idx = 0;
    // walk the JSON array items in order
    while ((pos = nj.find('"', pos)) != std::string::npos) {
      size_t end = nj.find('"', pos + 1);
      std::string name = nj.substr(pos + 1, end - pos - 1);
      if (name == want[i]) reparams[i] = loaded[idx];
      ++idx;
      pos = end + 1;
    }
  }
  for (int i = 0; i < 4; ++i)
    if (reparams[i] < 0) {
      std::fprintf(stderr, "name %s missing in %s\n", want[i], names);
      return 2;
    }

  // ---- evaluate on fresh data with the RELOADED weights ----
  std::vector<float> ex, ey;
  std::srand(99);
  make_dataset(32, kHW, &ex, &ey);
  int exh;
  {
    int64_t ds[4] = {32, 1, kHW, kHW};
    CHECK(MXTPUNDArrayCreate(ex.data(), ds, 4, &exh));
  }
  std::vector<int> temps;
  int logits;
  if (forward(reparams, exh, &logits, &temps) != 0) {
    std::fprintf(stderr, "eval forward FAIL: %s\n",
                 MXTPUTrainGetLastError());
    return 1;
  }
  std::vector<float> out(32 * kClasses);
  CHECK(MXTPUNDArrayCopyTo(logits, out.data(), out.size()));
  int correct = 0;
  for (int i = 0; i < 32; ++i) {
    int pred = out[i * 2] > out[i * 2 + 1] ? 0 : 1;
    if (pred == static_cast<int>(ey[i])) ++correct;
  }
  std::printf("reloaded accuracy %d/32\n", correct);
  if (correct < 29) {
    std::fprintf(stderr, "RELOADED MODEL INACCURATE\n");
    return 2;
  }
  std::remove(ckpt);
  std::printf("CNN_FULL_OK\n");
  return 0;
}

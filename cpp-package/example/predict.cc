// Minimal C++ inference consumer over the libmxtpu C ABI (parity:
// cpp-package/example + the reference's c_predict_api users).
//
// Usage: predict <model.onnx> <n> <c> [h w]
// Feeds an all-0.5 input of the given shape, prints the output values.
//
// Build:
//   g++ -O2 predict.cc -o predict -I../include -L. -lmxtpu \
//       -Wl,-rpath,'$ORIGIN'
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "mxtpu/c_predict_api.h"

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr, "usage: %s model.onnx n c [h w]\n", argv[0]);
    return 2;
  }
  std::vector<int64_t> shape;
  for (int i = 2; i < argc; ++i) shape.push_back(std::atoll(argv[i]));
  int64_t numel = 1;
  for (int64_t s : shape) numel *= s;

  PredictorHandle h;
  if (MXTPUPredCreate(argv[1], &h) != 0) {
    std::fprintf(stderr, "create failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  std::vector<float> input(numel, 0.5f);
  if (MXTPUPredSetInput(h, input.data(), shape.data(),
                        static_cast<int>(shape.size())) != 0) {
    std::fprintf(stderr, "set_input failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  int64_t out_shape[8];
  int out_ndim = 0;
  if (MXTPUPredForward(h, out_shape, 8, &out_ndim) != 0) {
    std::fprintf(stderr, "forward failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  int64_t out_n = 1;
  std::printf("output shape:");
  for (int i = 0; i < out_ndim; ++i) {
    std::printf(" %lld", static_cast<long long>(out_shape[i]));
    out_n *= out_shape[i];
  }
  std::printf("\n");
  std::vector<float> out(out_n);
  if (MXTPUPredGetOutput(h, out.data(), out_n) != 0) {
    std::fprintf(stderr, "get_output failed: %s\n", MXTPUGetLastError());
    return 1;
  }
  std::printf("output:");
  for (int64_t i = 0; i < out_n && i < 16; ++i)
    std::printf(" %.6f", out[i]);
  std::printf("\n");
  MXTPUPredFree(h);
  return 0;
}

// The same MLP training loop as train_mlp.cc, written against the
// typed C++ API (RAII NDArray + generated op wrappers) instead of raw
// C handles — parity with the reference's cpp-package/example/mlp.cpp
// over its generated op.h.
//
// Build (see tests/test_c_train_api.py):
//   g++ -O2 train_mlp_api.cc -I../include -L. -lmxtpu_train -o mlp_api
#include <mxtpu/ndarray.hpp>
#include <mxtpu/ops.hpp>

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

using mxtpu::AutogradRecord;
using mxtpu::NDArray;
using mxtpu::Optimizer;
namespace ops = mxtpu::ops;

namespace {
float frand() { return static_cast<float>(std::rand()) / RAND_MAX; }

NDArray randn(int64_t r, int64_t c, float scale) {
  std::vector<float> host(static_cast<size_t>(r * c));
  for (auto& v : host) v = (frand() - 0.5f) * 2.0f * scale;
  return NDArray(host, {r, c});
}
}  // namespace

int main() {
  std::srand(11);
  mxtpu::check(MXTPUTrainInit(), "init");

  const int kIn = 64, kHidden = 32, kClasses = 4, kBatch = 32;
  NDArray w1 = randn(kIn, kHidden, 0.1f);
  NDArray b1 = randn(1, kHidden, 0.0f);
  NDArray w2 = randn(kHidden, kClasses, 0.1f);
  NDArray b2 = randn(1, kClasses, 0.0f);
  NDArray* params[4] = {&w1, &b1, &w2, &b2};
  for (auto* p : params) p->AttachGrad();

  Optimizer sgd("sgd", "{\"learning_rate\": 0.5}");

  double first = -1, last = -1;
  for (int step = 0; step < 60; ++step) {
    std::vector<float> xv(kBatch * kIn), yv(kBatch);
    for (int i = 0; i < kBatch; ++i) {
      int k = i % kClasses;
      yv[i] = static_cast<float>(k);
      for (int j = 0; j < kIn; ++j)
        xv[i * kIn + j] = (j % kClasses == k ? 1.0f : 0.0f) +
                          0.2f * (frand() - 0.5f);
    }
    NDArray x(xv, {kBatch, kIn});
    NDArray y(yv, {kBatch});

    NDArray loss;
    {
      AutogradRecord rec;
      NDArray h = ops::relu(ops::add(ops::dot(x, w1), b1));
      NDArray logits = ops::add(ops::dot(h, w2), b2);
      NDArray lp = ops::log_softmax(logits, "{\"axis\": -1}");
      NDArray picked = ops::pick(lp, y, "{\"axis\": -1}");
      loss = ops::negative(ops::mean(picked));
    }
    loss.Backward();
    for (int i = 0; i < 4; ++i) {
      NDArray g = params[i]->Grad();
      sgd.Update(i, *params[i], g);
    }
    double lv = loss.Scalar();
    if (step == 0) first = lv;
    last = lv;
    if (step % 20 == 0) std::printf("step %d loss %.4f\n", step, lv);
  }
  std::printf("first %.4f final %.4f\n", first, last);
  if (!(last < first * 0.2) || !std::isfinite(last)) {
    std::fprintf(stderr, "TRAINING DID NOT CONVERGE\n");
    return 2;
  }
  std::printf("TRAIN_OK\n");
  return 0;
}

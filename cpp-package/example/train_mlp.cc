// Train a 2-layer MLP classifier from C++ through the libmxtpu_train
// C API — no Python in the host program (parity: the reference's
// cpp-package training examples, e.g. cpp-package/example/mlp.cpp,
// over its generated op wrappers + C API).
//
// The "dataset" is synthetic MNIST-shaped blobs: class k's pixels are
// drawn around k-dependent means, so a linear-ish model must reach
// near-zero loss if forward, backward, and the optimizer all work.
//
// Build (see tests/test_c_train_api.py):
//   g++ -O2 train_mlp.cc -I../include -L. -lmxtpu_train -o train_mlp
#include <mxtpu/c_train_api.h>

#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <vector>

#define CHECK(call)                                            \
  do {                                                         \
    if ((call) != 0) {                                         \
      std::fprintf(stderr, "FAIL %s: %s\n", #call,             \
                   MXTPUTrainGetLastError());                  \
      return 1;                                                \
    }                                                          \
  } while (0)

namespace {

float frand() { return static_cast<float>(std::rand()) / RAND_MAX; }

int make_param(int rows, int cols, float scale, int* out) {
  std::vector<float> host(static_cast<size_t>(rows) * cols);
  for (auto& v : host) v = (frand() - 0.5f) * 2.0f * scale;
  int64_t shape[2] = {rows, cols};
  return MXTPUNDArrayCreate(host.data(), shape, 2, out);
}

}  // namespace

int main() {
  std::srand(7);
  CHECK(MXTPUTrainInit());

  const int kIn = 64, kHidden = 32, kClasses = 4, kBatch = 32;

  int w1, b1, w2, b2;
  CHECK(make_param(kIn, kHidden, 0.1f, &w1));
  CHECK(make_param(1, kHidden, 0.0f, &b1));
  CHECK(make_param(kHidden, kClasses, 0.1f, &w2));
  CHECK(make_param(1, kClasses, 0.0f, &b2));
  const int params[4] = {w1, b1, w2, b2};
  for (int p : params) CHECK(MXTPUAutogradMarkVariable(p));

  int opt;
  CHECK(MXTPUOptimizerCreate("sgd", "{\"learning_rate\": 0.5}", &opt));

  double first_loss = -1.0, last_loss = -1.0;
  for (int step = 0; step < 60; ++step) {
    // synthetic batch: class k lights up feature group j%K == k
    std::vector<float> x(kBatch * kIn);
    std::vector<float> y(kBatch);
    for (int i = 0; i < kBatch; ++i) {
      int k = i % kClasses;
      y[i] = static_cast<float>(k);
      for (int j = 0; j < kIn; ++j)
        x[i * kIn + j] = (j % kClasses == k ? 1.0f : 0.0f) +
                         0.2f * (frand() - 0.5f);
    }
    int64_t xs[2] = {kBatch, kIn};
    int64_t ys[1] = {kBatch};
    int xh, yh;
    CHECK(MXTPUNDArrayCreate(x.data(), xs, 2, &xh));
    CHECK(MXTPUNDArrayCreate(y.data(), ys, 1, &yh));

    CHECK(MXTPUAutogradSetIsRecording(1));
    int h, n;
    std::vector<int> temps;  // free after backward or they leak
    int t1[2] = {xh, w1};
    CHECK(MXTPUImperativeInvoke("dot", t1, 2, nullptr, &h, 1, &n));
    temps.push_back(h);
    int t2[2] = {h, b1};
    CHECK(MXTPUImperativeInvoke("add", t2, 2, nullptr, &h, 1, &n));
    temps.push_back(h);
    int t3[1] = {h};
    CHECK(MXTPUImperativeInvoke("npx:relu", t3, 1, nullptr, &h, 1, &n));
    temps.push_back(h);
    int t4[2] = {h, w2};
    CHECK(MXTPUImperativeInvoke("dot", t4, 2, nullptr, &h, 1, &n));
    temps.push_back(h);
    int t5[2] = {h, b2};
    CHECK(MXTPUImperativeInvoke("add", t5, 2, nullptr, &h, 1, &n));
    temps.push_back(h);
    int t6[1] = {h};
    CHECK(MXTPUImperativeInvoke("npx:log_softmax", t6, 1,
                                "{\"axis\": -1}", &h, 1, &n));
    temps.push_back(h);
    int t7[2] = {h, yh};
    CHECK(MXTPUImperativeInvoke("npx:pick", t7, 2, "{\"axis\": -1}",
                                &h, 1, &n));
    temps.push_back(h);
    int t8[1] = {h};
    CHECK(MXTPUImperativeInvoke("mean", t8, 1, nullptr, &h, 1, &n));
    temps.push_back(h);
    int t9[1] = {h};
    int loss;
    CHECK(MXTPUImperativeInvoke("negative", t9, 1, nullptr, &loss, 1,
                                &n));
    CHECK(MXTPUAutogradSetIsRecording(0));
    CHECK(MXTPUAutogradBackward(loss));
    for (int t : temps) CHECK(MXTPUNDArrayFree(t));

    for (int i = 0; i < 4; ++i) {
      int g;
      CHECK(MXTPUNDArrayGetGrad(params[i], &g));
      CHECK(MXTPUOptimizerUpdate(opt, i, params[i], g));
      CHECK(MXTPUNDArrayFree(g));
    }

    double lv;
    CHECK(MXTPUNDArrayScalar(loss, &lv));
    if (step == 0) first_loss = lv;
    last_loss = lv;
    if (step % 20 == 0)
      std::printf("step %d loss %.4f\n", step, lv);
    CHECK(MXTPUNDArrayFree(xh));
    CHECK(MXTPUNDArrayFree(yh));
    CHECK(MXTPUNDArrayFree(loss));
  }

  std::printf("first %.4f final %.4f\n", first_loss, last_loss);
  if (!(last_loss < first_loss * 0.2) || !std::isfinite(last_loss)) {
    std::fprintf(stderr, "TRAINING DID NOT CONVERGE\n");
    return 2;
  }
  std::printf("TRAIN_OK\n");
  return 0;
}

// Deploy + fine-tune an EXPORTED hybridized graph from C++ through
// the CachedOp C API (parity: MXCreateCachedOp / MXInvokeCachedOp,
// src/imperative/cached_op.cc:776 — the reference's deployment path
// where a model trained in any frontend runs from C).
//
// argv: <symbol.json> <params-file>
// Prints the first logits row (the pytest compares against the Python
// forward), then runs one SGD step through the cached graph and
// verifies the loss drops.
#include <mxtpu/c_train_api.h>

#include <cmath>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#define CHECK(call)                                            \
  do {                                                         \
    if ((call) != 0) {                                         \
      std::fprintf(stderr, "FAIL %s: %s\n", #call,             \
                   MXTPUTrainGetLastError());                  \
      return 1;                                                \
    }                                                          \
  } while (0)

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s symbol.json params\n", argv[0]);
    return 1;
  }
  CHECK(MXTPUTrainInit());

  int op;
  CHECK(MXTPUCachedOpCreate(argv[1], "[\"data\"]", argv[2], &op));

  // deterministic input: ascending ramp over (4, 3)
  std::vector<float> host(12);
  for (int i = 0; i < 12; ++i) host[i] = 0.1f * i;
  int64_t shape[2] = {4, 3};
  int x;
  CHECK(MXTPUNDArrayCreate(host.data(), shape, 2, &x));

  int y, n;
  CHECK(MXTPUCachedOpInvoke(op, &x, 1, &y, 1, &n));
  int64_t yshape[8];
  int yndim;
  CHECK(MXTPUNDArrayShape(y, yshape, 8, &yndim));
  std::vector<float> out(static_cast<size_t>(yshape[0]) * yshape[1]);
  CHECK(MXTPUNDArrayCopyTo(y, out.data(), out.size()));
  std::printf("logits0");
  for (int64_t j = 0; j < yshape[1]; ++j)
    std::printf(" %.6f", out[j]);
  std::printf("\n");

  // params are live handles: one training step through the graph
  char names[512];
  CHECK(MXTPUCachedOpParamNames(op, names, sizeof(names)));
  std::printf("params %s\n", names);

  int opt;
  CHECK(MXTPUOptimizerCreate("sgd", "{\"learning_rate\": 0.05}", &opt));

  double losses[2] = {0, 0};
  for (int step = 0; step < 2; ++step) {
    CHECK(MXTPUAutogradSetIsRecording(1));
    int logits;
    CHECK(MXTPUCachedOpInvoke(op, &x, 1, &logits, 1, &n));
    // loss = mean(logits^2) — drives outputs toward zero
    int sq, h;
    int sq_in[2] = {logits, logits};
    CHECK(MXTPUImperativeInvoke("multiply", sq_in, 2, nullptr, &sq, 1,
                                &n));
    int mn_in[1] = {sq};
    CHECK(MXTPUImperativeInvoke("mean", mn_in, 1, nullptr, &h, 1, &n));
    CHECK(MXTPUAutogradSetIsRecording(0));
    CHECK(MXTPUAutogradBackward(h));
    CHECK(MXTPUNDArrayScalar(h, &losses[step]));

    // apply SGD to every graph parameter via its live handle
    std::string nj(names);
    size_t pos = 0;
    int idx = 0;
    while ((pos = nj.find('"', pos)) != std::string::npos) {
      size_t end = nj.find('"', pos + 1);
      std::string pname = nj.substr(pos + 1, end - pos - 1);
      int ph, g;
      CHECK(MXTPUCachedOpParamGet(op, pname.c_str(), &ph));
      if (MXTPUNDArrayGetGrad(ph, &g) == 0) {
        CHECK(MXTPUOptimizerUpdate(opt, idx, ph, g));
        CHECK(MXTPUNDArrayFree(g));
      }
      CHECK(MXTPUNDArrayFree(ph));
      ++idx;
      pos = end + 1;
    }
    CHECK(MXTPUNDArrayFree(logits));
    CHECK(MXTPUNDArrayFree(sq));
    CHECK(MXTPUNDArrayFree(h));
  }
  std::printf("step losses %.6f -> %.6f\n", losses[0], losses[1]);
  if (!(losses[1] < losses[0]) || !std::isfinite(losses[1])) {
    std::fprintf(stderr, "CACHEDOP TRAIN STEP DID NOT IMPROVE\n");
    return 2;
  }
  CHECK(MXTPUCachedOpFree(op));
  std::printf("CACHEDOP_OK\n");
  return 0;
}

"""Model-zoo ResNet on CIFAR-shaped data with the fused TrainStep
(parity: example/gluon/image_classification.py, the reference's
multi-GPU training example — here the dp axis is a jax.sharding mesh).

Shows the TPU-first throughput path: hybridized whole-graph step,
bf16 params, optional bulk mode (N steps per XLA program)."""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon, np, parallel


def synthetic_cifar(n=2048):
    rng = onp.random.RandomState(0)
    protos = rng.rand(10, 32, 32, 3).astype("float32")
    y = rng.randint(0, 10, n)
    x = protos[y] + 0.05 * rng.rand(n, 32, 32, 3).astype("float32")
    return x, y.astype("int32")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--bf16", action="store_true")
    ap.add_argument("--bulk", type=int, default=0,
                    help="steps per XLA program (0 = stepwise)")
    args = ap.parse_args()

    import jax
    n_dev = jax.local_device_count()
    mesh = parallel.make_mesh((n_dev,), ("dp",))
    parallel.set_mesh(mesh)

    net = getattr(gluon.model_zoo.vision, args.model)(
        classes=10, layout="NHWC")
    net.initialize(mx.init.Xavier())
    if args.bf16:
        net.cast("bfloat16")

    step = parallel.TrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                          "multi_precision": args.bf16},
        mesh=mesh, batch_axis="dp")

    x, y = synthetic_cifar()
    bs = args.batch_size
    dtype = "bfloat16" if args.bf16 else "float32"
    steps = len(x) // bs
    for epoch in range(args.epochs):
        losses = []
        if args.bulk > 1:
            k = args.bulk
            for s in range(0, steps - k + 1, k):
                d = np.array(x[s * bs:(s + k) * bs].reshape(
                    k, bs, 32, 32, 3), dtype=dtype)
                l = np.array(y[s * bs:(s + k) * bs].reshape(k, bs))
                losses.extend(step.run_chain(d, l).asnumpy().tolist())
        else:
            for s in range(steps):
                d = np.array(x[s * bs:(s + 1) * bs], dtype=dtype)
                l = np.array(y[s * bs:(s + 1) * bs])
                losses.append(float(step(d, l).asnumpy()))
        print(f"epoch {epoch}: first loss {losses[0]:.4f} "
              f"last loss {losses[-1]:.4f} ({len(losses)} steps)")
    return losses[-1]


if __name__ == "__main__":
    main()

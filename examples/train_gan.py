"""Tiny GAN on a 2-D Gaussian-mixture (reference example/gluon/dc_gan
training pattern, shrunk to run on CPU in seconds).

Pins the adversarial idioms a switching user needs: two Trainers over
disjoint parameter sets, `detach()` cutting the generator out of the
discriminator's backward, and label flipping for the generator step.
The quantitative check: generated samples must cover most mixture
modes (mode coverage >= threshold), not just fool the discriminator.

Run (CPU smoke):
    JAX_PLATFORMS=cpu python examples/train_gan.py
"""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.gluon import nn

MODES = onp.array([[2.0, 0.0], [-2.0, 0.0], [0.0, 2.0], [0.0, -2.0],
                   [1.5, 1.5], [-1.5, 1.5], [1.5, -1.5], [-1.5, -1.5]],
                  "f4")


def real_batch(rng, n):
    idx = rng.randint(0, len(MODES), n)
    return (MODES[idx] + 0.1 * rng.randn(n, 2)).astype("f4")


def mlp(out_units, hidden, act_last=None):
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu"),
            nn.Dense(hidden, activation="relu"),
            nn.Dense(out_units, activation=act_last))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--latent", type=int, default=8)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--min-modes", type=int, default=5)
    args = ap.parse_args()

    gen = mlp(2, 64)
    disc = mlp(1, 64)
    gen.initialize(mx.init.Xavier())
    disc.initialize(mx.init.Xavier())
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    rng = onp.random.RandomState(0)
    ones = np.ones((args.batch,))
    zeros = np.zeros((args.batch,))
    for step in range(args.steps):
        real = np.array(real_batch(rng, args.batch))
        noise = np.array(rng.randn(args.batch, args.latent)
                         .astype("f4"))
        # --- discriminator step: real -> 1, fake(detached) -> 0 ---
        with autograd.record():
            fake = gen(noise)
            d_loss = (bce(disc(real), ones)
                      + bce(disc(fake.detach()), zeros)).mean()
        d_loss.backward()
        d_tr.step(args.batch)
        # --- generator step: make disc call fakes real ---
        with autograd.record():
            g_loss = bce(disc(gen(noise)), ones).mean()
        g_loss.backward()
        g_tr.step(args.batch)
        if step % 150 == 0 or step == args.steps - 1:
            print(f"step {step}  d_loss {float(d_loss.asnumpy()):.3f}"
                  f"  g_loss {float(g_loss.asnumpy()):.3f}")

    # ---- mode coverage: fraction of mixture modes with a nearby
    # generated sample ----
    noise = np.array(rng.randn(1024, args.latent).astype("f4"))
    samples = gen(noise).asnumpy()
    d2 = ((samples[:, None, :] - MODES[None]) ** 2).sum(-1)
    nearest = d2.argmin(1)
    covered = len({int(m) for m, dist in
                   zip(nearest, d2.min(1)) if dist < 1.0})
    print(f"modes_covered {covered}/8")
    assert covered >= args.min_modes, \
        f"mode collapse: only {covered} modes covered"


if __name__ == "__main__":
    main()

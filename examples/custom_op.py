"""Python custom operators three ways (parity:
example/extensions/lib_custom_op and python/mxnet/operator.py):

1. `mx.operator.CustomOp` — registered op with prop, shape/type
   inference, imperative forward/backward over NDArrays.
2. `autograd.Function` — inline custom-VJP callable.
3. `mx.rtc` — a user Pallas kernel (the NVRTC/CUDA-string analogue),
   jit-compiled for the accelerator.
"""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, np, operator


@operator.register("softsign_x")
class SoftsignProp(operator.CustomOpProp):
    def list_arguments(self):
        return ["data"]

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return Softsign()


class Softsign(operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0]
        self.assign(out_data[0], req[0], x / (1 + abs(x)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        x = in_data[0]
        g = out_grad[0] / (1 + abs(x)) ** 2
        self.assign(in_grad[0], req[0], g)


class ClipGrad(autograd.Function):
    """Identity forward, clipped gradient backward."""

    def forward(self, x):
        return x

    def backward(self, dy):
        return np.clip(dy, -0.1, 0.1)


def main():
    x = np.array(onp.linspace(-3, 3, 8, dtype="float32"))
    x.attach_grad()
    with autograd.record():
        y = mx.npx.custom(x, op_type="softsign_x")
        z = ClipGrad()(y * 4.0)
        loss = z.sum()
    loss.backward()
    print("softsign:", y.asnumpy().round(3))
    print("clipped grads:", x.grad.asnumpy().round(3))

    # Pallas path: runtime-compiled vector kernel through mx.rtc
    src = (
        "def scale2(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] * 2.0\n")
    mod = mx.rtc.PallasModule(src)
    kernel = mod.get_kernel("scale2")
    out = kernel(np.array([1.0, 2.0, 3.0]))
    print("pallas scale2:", out.asnumpy())


if __name__ == "__main__":
    main()

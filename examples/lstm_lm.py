"""Word-level LSTM language model (BASELINE.json config 3).

The reference's headline RNN workload is example/rnn's PTB LSTM LM on
the cuDNN fused path (src/operator/rnn-inl.h). Here the same model
shape runs on the fused scan LSTM (gluon.rnn.LSTM lowers to ONE
lax.scan over the sequence — the TPU-native equivalent of the cuDNN
multi-layer kernel), trained with truncated BPTT, optional hybridized
bulk steps, and perplexity reporting.

Data: a deterministic synthetic corpus with PTB-like statistics
(Zipfian unigrams + a short-range bigram structure the model can
learn), so the example is runnable offline; point --text at any
whitespace-tokenized file (e.g. real PTB) to train on it instead.

Run (CPU smoke):
    JAX_PLATFORMS=cpu python examples/lstm_lm.py --steps 8
"""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse
import math
import time

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.gluon import nn, rnn


class LSTMLanguageModel(nn.HybridBlock):
    """Embedding -> multi-layer fused LSTM -> tied-capacity decoder
    (reference shape: example/rnn/word_lm model.py)."""

    def __init__(self, vocab, embed=200, hidden=200, layers=2,
                 dropout=0.2):
        super().__init__()
        self.embed = nn.Embedding(vocab, embed)
        self.drop = nn.Dropout(dropout)
        self.lstm = rnn.LSTM(hidden, num_layers=layers,
                             dropout=dropout, layout="NTC",
                             input_size=embed)
        self.decoder = nn.Dense(vocab, flatten=False)
        self._hidden, self._layers = hidden, layers

    def begin_state(self, batch_size, ctx=None):
        return self.lstm.begin_state(batch_size=batch_size, ctx=ctx)

    def forward(self, tokens, state):
        x = self.drop(self.embed(tokens))
        out, new_state = self.lstm(x, state)
        return self.decoder(self.drop(out)), new_state


def synthetic_corpus(n_tokens, vocab, seed=0):
    """Zipf unigrams + deterministic bigram successor structure:
    token t is followed by (t*7+3)%vocab 60% of the time, so a
    learning model's perplexity drops well below the unigram floor."""
    rng = onp.random.RandomState(seed)
    ranks = onp.arange(1, vocab + 1, dtype="f8")
    p = (1.0 / ranks) / (1.0 / ranks).sum()
    toks = onp.empty(n_tokens, "i4")
    toks[0] = 0
    zipf = rng.choice(vocab, size=n_tokens, p=p)
    follow = rng.uniform(size=n_tokens) < 0.6
    for i in range(1, n_tokens):
        toks[i] = (toks[i - 1] * 7 + 3) % vocab if follow[i] \
            else zipf[i]
    return toks


def batchify(tokens, batch):
    n = len(tokens) // batch
    return tokens[:n * batch].reshape(batch, n)


def detach(state):
    return [s.detach() for s in state]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--text", help="whitespace-tokenized corpus file")
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--bptt", type=int, default=32)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--lr", type=float, default=3.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--hybridize", action="store_true")
    args = ap.parse_args()

    if args.text:
        words = open(args.text).read().split()
        uniq = sorted(set(words))[:args.vocab - 1]
        idx = {w: i + 1 for i, w in enumerate(uniq)}
        toks = onp.array([idx.get(w, 0) for w in words], "i4")
    else:
        toks = synthetic_corpus(50_000, args.vocab)

    data = batchify(toks, args.batch)
    net = LSTMLanguageModel(args.vocab, embed=args.hidden,
                            hidden=args.hidden, layers=args.layers)
    net.initialize(mx.init.Xavier())
    if args.hybridize:
        net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    state = net.begin_state(args.batch)

    n_batches = (data.shape[1] - 1) // args.bptt
    if n_batches < 1:
        raise SystemExit(
            f"corpus too small: need at least batch*(bptt+1) = "
            f"{args.batch * (args.bptt + 1)} tokens for "
            f"--batch {args.batch} --bptt {args.bptt}")
    t0 = time.time()
    tokens_seen = 0
    ppl = None
    for step in range(args.steps):
        off = (step % n_batches) * args.bptt
        x = np.array(data[:, off:off + args.bptt])
        y = np.array(data[:, off + 1:off + args.bptt + 1]
                     .astype("i4"))
        state = detach(state)  # truncated BPTT boundary
        with autograd.record():
            logits, state = net(x, state)
            loss = loss_fn(logits, y).mean()
        loss.backward()
        grads = [p.grad() for p in net.collect_params().values()
                 if p.grad_req != "null"]
        gluon.utils.clip_global_norm(grads, args.clip)
        trainer.step(1)
        tokens_seen += args.batch * args.bptt
        ppl = math.exp(min(float(loss.asnumpy()), 20.0))
        if step % 5 == 0 or step == args.steps - 1:
            print(f"step {step}  ppl {ppl:.1f}")
    wps = tokens_seen / (time.time() - t0)
    print(f"final_ppl {ppl:.2f}  tokens_per_sec {wps:.0f}")
    # the bigram structure is learnable: perplexity must end below
    # the vocab-size random floor
    assert ppl < args.vocab, "no learning signal"


if __name__ == "__main__":
    main()

"""INT8 post-training quantization with calibration (parity:
example/quantization/*: quantize a trained fp32 model, calibrate
activation ranges, compare accuracy)."""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon, np
from mxnet_tpu.contrib.quantization import quantize_net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--calib-mode", default="entropy",
                    choices=["none", "naive", "entropy"])
    ap.add_argument("--batches", type=int, default=4)
    args = ap.parse_args()

    rng = onp.random.RandomState(0)
    protos = rng.rand(10, 32, 32, 3).astype("float32")
    y = rng.randint(0, 10, 512)
    x = protos[y] + 0.05 * rng.rand(512, 32, 32, 3).astype("float32")

    net = getattr(gluon.model_zoo.vision, args.model)(
        classes=10, layout="NHWC")
    net.initialize(mx.init.Xavier())
    net.hybridize()

    data = np.array(x)
    labels = np.array(y.astype("int32"))
    fp32_out = net(data[:128]).asnumpy()

    calib = [(data[i * 32:(i + 1) * 32],) for i in range(args.batches)]
    qnet = quantize_net(net, quantized_dtype="int8",
                        calib_mode=args.calib_mode, calib_data=calib)
    qnet.hybridize()
    int8_out = qnet(data[:128]).asnumpy()

    agree = (fp32_out.argmax(1) == int8_out.argmax(1)).mean()
    print(f"{args.model} int8 ({args.calib_mode} calibration): "
          f"top-1 agreement with fp32 on synthetic eval = {agree:.3f}")
    metric = gluon.metric.Accuracy()
    metric.update(labels[:128], np.array(int8_out))
    print("int8 accuracy vs labels:", metric.get()[1])


if __name__ == "__main__":
    main()

"""BERT sequence-classification fine-tuning (BASELINE.json config 4;
parity: the reference ecosystem's GluonNLP finetune_classifier.py).

Synthetic sentence-pair task: class = whether the two segments share a
majority token. Uses the fused TrainStep (one XLA program per step)
with pad masking via valid_length, the config-4 training shape.
"""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import gluon, np, parallel
from mxnet_tpu.gluon.model_zoo.bert import BERTClassifier, bert_small


def synthetic_pairs(n, seq_len, vocab, rng):
    """Token pairs with a learnable signal: positive examples repeat a
    marker token in both segments."""
    toks = rng.randint(4, vocab, (n, seq_len))
    seg = onp.zeros((n, seq_len), "int32")
    seg[:, seq_len // 2:] = 1
    labels = rng.randint(0, 2, n)
    marker = 2
    for i in range(n):
        if labels[i]:
            toks[i, 1] = marker
            toks[i, seq_len // 2 + 1] = marker
    valid = rng.randint(seq_len // 2 + 2, seq_len + 1, n)
    return (toks.astype("int32"), seg, valid.astype("int32"),
            labels.astype("int32"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=32)
    ap.add_argument("--lr", type=float, default=5e-4)
    args = ap.parse_args()

    import jax
    n_dev = jax.local_device_count()
    mesh = parallel.make_mesh((n_dev,), ("dp",))
    parallel.set_mesh(mesh)

    vocab = 200
    net = BERTClassifier(bert_small(vocab_size=vocab,
                                    max_length=args.seq_len),
                         num_classes=2)
    net.initialize(mx.init.TruncNorm(stdev=0.02)
                   if hasattr(mx.init, "TruncNorm") else mx.init.Xavier())

    step = parallel.TrainStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(), "adamw"
        if "adamw" in dir(mx.optimizer) else "adam",
        optimizer_params={"learning_rate": args.lr}, mesh=mesh,
        batch_axis="dp")

    rng = onp.random.RandomState(0)
    bs = args.batch_size * n_dev
    losses = []
    for s in range(args.steps):
        toks, seg, valid, y = synthetic_pairs(bs, args.seq_len, vocab,
                                              rng)
        loss = step((np.array(toks), np.array(seg), np.array(valid)),
                    np.array(y))
        losses.append(float(loss.asnumpy()))
    print(f"bert finetune: loss {losses[0]:.4f} -> {losses[-1]:.4f}")

    # eval accuracy on fresh data; hybridize so eval is one jitted
    # program (eager ops can't mix mesh params with fresh host arrays)
    net.hybridize()
    toks, seg, valid, y = synthetic_pairs(256, args.seq_len, vocab, rng)
    ins = [parallel.replicate(np.array(a), mesh)
           for a in (toks, seg, valid)]
    out = net(*ins)
    acc = (out.asnumpy().argmax(1) == y).mean()
    print(f"eval accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()

"""Multi-process data-parallel training via kvstore `dist_sync`
(parity: example/distributed_training/cifar10_dist.py). Launch with:

    python tools/launch.py -n 2 --launcher local \
        python examples/train_dist.py --epochs 1
"""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np, parallel
from mxnet_tpu.gluon import nn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    parallel.initialize_distributed()
    kv = mx.kvstore.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers
    print(f"worker {rank}/{nworker} up")

    rng = onp.random.RandomState(7)  # same model/data seed per worker
    protos = rng.rand(4, 16).astype("float32")
    y_all = rng.randint(0, 4, 512)
    x_all = protos[y_all] + 0.1 * rng.rand(512, 16).astype("float32")
    # shard the dataset by rank (parity: SplitSampler in the reference)
    x, y = x_all[rank::nworker], y_all[rank::nworker]

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 5e-3}, kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    bs = args.batch_size
    for epoch in range(args.epochs):
        losses = []
        for s in range(len(x) // bs):
            d = np.array(x[s * bs:(s + 1) * bs])
            l = np.array(y[s * bs:(s + 1) * bs].astype("int32"))
            with autograd.record():
                loss = loss_fn(net(d), l).mean()
            loss.backward()
            trainer.step(bs)
            losses.append(float(loss.asnumpy()))
        print(f"worker {rank} epoch {epoch}: loss "
              f"{losses[0]:.4f} -> {losses[-1]:.4f}")


if __name__ == "__main__":
    main()

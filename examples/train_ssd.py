"""Toy single-shot detector (SSD) on synthetic shapes.

Exercises the full detection op stack end to end, the workload of the
reference's `example/ssd`: anchors from `npx.multibox_prior`, training
targets from `npx.multibox_target` (IoU matching + hard negative
mining), offset regression (SmoothL1) + class scores (softmax CE),
and `npx.multibox_detection` (decode + per-class NMS) at eval — all on
a tiny conv backbone so it runs on CPU in seconds.

Task: each image contains ONE axis-aligned bright rectangle on a dark
noisy background; class = rectangle orientation (wide vs tall). The
detector must localize it (IoU vs ground truth) and classify it.

Run (CPU smoke):
    JAX_PLATFORMS=cpu python examples/train_ssd.py
"""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np, npx
from mxnet_tpu.gluon import nn

HW = 32
N_CLASSES = 2  # wide vs tall (background is id 0 inside the op stack)


def synth_batch(rng, batch):
    """Images (B,3,HW,HW) + labels (B,1,5) [cls, xmin,ymin,xmax,ymax]
    in normalized corner coords."""
    imgs = rng.uniform(0.0, 0.2, (batch, 3, HW, HW)).astype("f4")
    labels = onp.zeros((batch, 1, 5), "f4")
    for i in range(batch):
        wide = rng.randint(0, 2)
        w, h = (rng.randint(12, 18), rng.randint(5, 8)) if wide \
            else (rng.randint(5, 8), rng.randint(12, 18))
        x0 = rng.randint(1, HW - w - 1)
        y0 = rng.randint(1, HW - h - 1)
        chan = rng.randint(0, 3)
        imgs[i, chan, y0:y0 + h, x0:x0 + w] = 1.0
        labels[i, 0] = [wide, x0 / HW, y0 / HW,
                        (x0 + w) / HW, (y0 + h) / HW]
    return imgs, labels


class TinySSD(nn.HybridBlock):
    """Conv backbone -> one 8x8 feature map -> per-anchor heads."""

    def __init__(self, n_anchor_shapes):
        super().__init__()
        self.backbone = nn.HybridSequential()
        for ch in (16, 32):
            self.backbone.add(
                nn.Conv2D(ch, 3, padding=1, strides=2),
                nn.BatchNorm(), nn.Activation("relu"))
        k = n_anchor_shapes
        # class head: (background + classes) per anchor shape
        self.cls_head = nn.Conv2D(k * (N_CLASSES + 1), 3, padding=1)
        self.box_head = nn.Conv2D(k * 4, 3, padding=1)

    def forward(self, x):
        f = self.backbone(x)                       # (B, C, 8, 8)
        B = f.shape[0]
        cls = self.cls_head(f)                     # (B, k*(C+1), 8, 8)
        box = self.box_head(f)                     # (B, k*4, 8, 8)
        cls = cls.transpose(0, 2, 3, 1).reshape(B, -1, N_CLASSES + 1)
        box = box.transpose(0, 2, 3, 1).reshape(B, -1)
        return cls, box, f


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--eval-iou", type=float, default=0.4)
    args = ap.parse_args()

    sizes, ratios = (0.35, 0.5), (1.0, 2.0, 0.5)
    k = len(sizes) + len(ratios) - 1
    net = TinySSD(k)
    net.initialize(mx.init.Xavier())

    rng = onp.random.RandomState(0)
    box_loss = gluon.loss.HuberLoss(rho=1.0)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    # anchors depend only on the feature-map geometry: compute once,
    # outside any autograd tape
    imgs0, _ = synth_batch(rng, 1)
    _, _, feat0 = net(np.array(imgs0))
    anchors = npx.multibox_prior(feat0, sizes=sizes, ratios=ratios)

    for step in range(args.steps):
        imgs_np, labels_np = synth_batch(rng, args.batch)
        imgs = np.array(imgs_np)
        labels = np.array(labels_np)
        with autograd.record():
            cls_pred, box_pred, feat = net(imgs)
            box_t, box_m, cls_t = npx.multibox_target(
                anchors, labels, cls_pred.transpose(0, 2, 1),
                negative_mining_ratio=3.0)
            # cls_t: -1 = ignored by hard-negative mining — mask it
            # out of the class loss (the reference SSD recipe)
            keep = (cls_t >= 0).astype("float32")
            logp = npx.log_softmax(cls_pred, axis=-1)
            picked = npx.pick(logp, np.maximum(cls_t, 0), axis=-1)
            l_cls = -(picked * keep).sum() / np.maximum(
                keep.sum(), 1.0)
            l_box = box_loss(box_pred * box_m, box_t)  # box_t pre-masked
            loss = l_cls + l_box.mean() * 10.0
        loss.backward()
        trainer.step(args.batch)
        if step % 20 == 0 or step == args.steps - 1:
            print(f"step {step}  loss {float(loss.asnumpy()):.4f}")

    # ---- eval: decode + NMS, check localization on fresh images ----
    imgs_np, labels_np = synth_batch(rng, 32)
    cls_pred, box_pred, _ = net(np.array(imgs_np))
    cls_prob = npx.softmax(cls_pred, axis=-1).transpose(0, 2, 1)
    out = npx.multibox_detection(cls_prob, box_pred, anchors,
                                 nms_threshold=0.45)
    out_np = out.asnumpy()
    # one batched IoU call for all best-detection/gt pairs
    bests = onp.full((len(imgs_np), 6), -1.0, "f4")
    for i in range(len(imgs_np)):
        dets = out_np[i]
        dets = dets[dets[:, 0] >= 0]
        if len(dets):
            bests[i] = dets[dets[:, 1].argmax()]
    ious = npx.box_iou(np.array(bests[:, None, 2:6]),
                       np.array(labels_np[:, :, 1:5])).asnumpy()
    hits = sum(1 for i in range(len(imgs_np))
               if ious[i, 0, 0] >= args.eval_iou
               and int(bests[i, 0]) == int(labels_np[i, 0, 0]))
    acc = hits / len(imgs_np)
    print(f"detection_accuracy {acc:.2f} (IoU>={args.eval_iou} + "
          "correct class)")
    assert acc >= 0.5, "detector failed to learn the toy task"


if __name__ == "__main__":
    main()

"""Causal transformer language model with flash attention and optional
ring-attention sequence parallelism.

Beyond-reference long-context showcase: the reference's sequence story
tops out at fused RNNs (src/operator/rnn-inl.h); here attention runs as
a Pallas flash kernel and, over a dp×sp mesh, as ring attention
(shard_map + ppermute over 'sp') so sequence length scales across
chips. Run on the 8-device virtual CPU mesh:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        JAX_PLATFORMS=cpu python examples/lm_transformer.py --sp 4
"""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np, npx, parallel
from mxnet_tpu.gluon import nn


class CausalSelfAttention(nn.HybridBlock):
    def __init__(self, dim, heads, sp_axis=None):
        super().__init__()
        self.heads = heads
        self.sp_axis = sp_axis
        self.qkv = nn.Dense(3 * dim, use_bias=False, flatten=False)
        self.proj = nn.Dense(dim, use_bias=False, flatten=False)

    def forward(self, x):
        B, S, D = x.shape
        H = self.heads
        qkv = self.qkv(x).reshape(B, S, 3, H, D // H)
        q = qkv[:, :, 0].transpose(0, 2, 1, 3)
        k = qkv[:, :, 1].transpose(0, 2, 1, 3)
        v = qkv[:, :, 2].transpose(0, 2, 1, 3)
        if self.sp_axis:
            out = npx.ring_attention(q, k, v, causal=True,
                                     axis_name=self.sp_axis)
        else:
            out = npx.flash_attention(q, k, v, causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(B, S, D)
        return self.proj(out)


class Block(nn.HybridBlock):
    def __init__(self, dim, heads, sp_axis=None):
        super().__init__()
        self.ln1 = nn.LayerNorm()
        self.attn = CausalSelfAttention(dim, heads, sp_axis)
        self.ln2 = nn.LayerNorm()
        self.mlp1 = nn.Dense(4 * dim, activation="relu", flatten=False)
        self.mlp2 = nn.Dense(dim, flatten=False)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        return x + self.mlp2(self.mlp1(self.ln2(x)))


class TinyLM(nn.HybridBlock):
    def __init__(self, vocab, dim=64, heads=4, depth=2, sp_axis=None):
        super().__init__()
        self.emb = nn.Embedding(vocab, dim)
        self.blocks = nn.HybridSequential()
        for _ in range(depth):
            self.blocks.add(Block(dim, heads, sp_axis))
        self.head = nn.Dense(vocab, flatten=False)

    def forward(self, tokens):
        return self.head(self.blocks(self.emb(tokens)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--sp", type=int, default=0,
                    help="sequence-parallel degree (0 = single chip "
                         "flash attention)")
    args = ap.parse_args()

    import jax
    vocab, batch = 64, 4
    sp_axis = None
    mesh = None
    if args.sp > 1:
        n_dev = jax.local_device_count()
        dp = max(1, n_dev // args.sp)
        mesh = parallel.make_mesh((dp, args.sp), ("dp", "sp"))
        parallel.set_mesh(mesh)
        sp_axis = "sp"

    net = TinyLM(vocab, sp_axis=sp_axis)
    net.initialize(mx.init.Xavier())

    rng = onp.random.RandomState(0)
    toks = rng.randint(0, vocab, (batch, args.seq_len + 1))

    if sp_axis:
        from jax.sharding import PartitionSpec as P
        step = parallel.TrainStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "adam",
            optimizer_params={"learning_rate": 1e-3}, mesh=mesh,
            batch_axis="dp")
        data = np.array(toks[:, :-1])
        label = np.array(toks[:, 1:].astype("int32"))
        # materialize deferred params BEFORE sharding the tokens:
        # deferred init runs eagerly on first use, and eager ops
        # cannot mix mesh-sharded and single-device operands
        net.infer_shape(data)
        # shard sequence over 'sp' by hand: (B, S) -> P('dp', 'sp')
        import jax as _jax
        from jax.sharding import NamedSharding
        sh = NamedSharding(mesh, P("dp", "sp"))
        data._install(_jax.device_put(data._data, sh))
        label._install(_jax.device_put(label._data, sh))
        losses = [float(step(data, label).asnumpy())
                  for _ in range(args.steps)]
    else:
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-3})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        data = np.array(toks[:, :-1])
        label = np.array(toks[:, 1:].astype("int32"))
        losses = []
        for _ in range(args.steps):
            with autograd.record():
                out = net(data)
                loss = loss_fn(out.reshape(-1, vocab),
                               label.reshape(-1)).mean()
            loss.backward()
            trainer.step(1)
            losses.append(float(loss.asnumpy()))

    print(f"seq_len={args.seq_len} sp={args.sp or 1}: "
          f"loss {losses[0]:.4f} -> {losses[-1]:.4f}")


if __name__ == "__main__":
    main()

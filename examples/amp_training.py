"""Automatic mixed precision (parity: the reference's AMP tutorial,
example/automatic-mixed-precision): `amp.init()` turns on cast-list
autocast at op dispatch; fp16 adds dynamic loss scaling through
`amp.init_trainer` + `amp.scale_loss`."""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon, np
from mxnet_tpu.gluon import nn


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dtype", default="bfloat16",
                    choices=["bfloat16", "float16"])
    ap.add_argument("--steps", type=int, default=40)
    args = ap.parse_args()

    amp.init(target_dtype=args.dtype)

    rng = onp.random.RandomState(0)
    protos = rng.rand(4, 32).astype("float32")
    y = rng.randint(0, 4, 256)
    x = protos[y] + 0.1 * rng.rand(256, 32).astype("float32")

    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-2})
    if args.dtype == "float16":
        amp.init_trainer(trainer)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    losses = []
    for s in range(args.steps):
        i = (s * 32) % 224
        d, l = np.array(x[i:i + 32]), np.array(y[i:i + 32].astype("int32"))
        with autograd.record():
            loss = loss_fn(net(d), l).mean()
            if args.dtype == "float16":
                with amp.scale_loss(loss, trainer) as scaled:
                    scaled.backward()
            else:
                loss.backward()
        trainer.step(1)
        losses.append(float(loss.asnumpy()))
    print(f"{args.dtype}: loss {losses[0]:.4f} -> {losses[-1]:.4f}")


if __name__ == "__main__":
    main()

"""Gluon MLP on MNIST — the reference's hello-world training loop
(parity: example/gluon/mnist/mnist.py) on the imperative autograd path.

Falls back to a synthetic MNIST-shaped dataset when the real download
is unavailable (offline CI)."""
from __future__ import annotations

import os as _os, sys as _sys
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))  # run from anywhere
if _os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    import jax as _jax  # the axon plugin hook ignores the env var alone
    _jax.config.update("jax_platforms", "cpu")

import argparse

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.gluon import nn


def _flatten_dataset(ds, limit=None):
    """Pre-transform once on host (batched), not per-sample on device:
    the per-sample path costs one dispatch per example."""
    xs, ys = [], []
    n = len(ds) if limit is None else min(limit, len(ds))
    for i in range(n):
        data, label = ds[i]
        a = onp.asarray(getattr(data, "asnumpy", lambda: data)())
        xs.append(a.reshape(-1))
        ys.append(int(label))
    x = onp.stack(xs).astype("float32")
    if x.max() > 1.5:  # uint8 pixel range
        x /= 255.0
    return gluon.data.ArrayDataset(
        np.array(x), np.array(onp.asarray(ys, dtype="int32")))


def load_data(batch_size, limit=2048):
    try:
        train = _flatten_dataset(gluon.data.vision.MNIST(train=True),
                                 limit)
        val = _flatten_dataset(gluon.data.vision.MNIST(train=False),
                               limit // 4)
    except Exception:
        print("MNIST unavailable; using synthetic digits")
        rng = onp.random.RandomState(0)
        protos = rng.rand(10, 28 * 28).astype("float32")
        y = rng.randint(0, 10, limit + limit // 4)
        x = (protos[y] + 0.1 * rng.rand(len(y), 28 * 28)) \
            .astype("float32")
        train = gluon.data.ArrayDataset(
            np.array(x[:limit]), np.array(y[:limit].astype("int32")))
        val = gluon.data.ArrayDataset(
            np.array(x[limit:]), np.array(y[limit:].astype("int32")))
    return (gluon.data.DataLoader(train, batch_size, shuffle=True),
            gluon.data.DataLoader(val, batch_size))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--checkpoint-prefix", default=None)
    args = ap.parse_args()

    train_iter, val_iter = load_data(args.batch_size)

    net = nn.HybridSequential()
    net.add(nn.Dense(128, activation="relu"),
            nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = gluon.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        for data, label in train_iter:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label).mean()
            loss.backward()
            trainer.step(1)
            metric.update(label, out)
        name, acc = metric.get()
        print(f"epoch {epoch}: train-{name}={acc:.4f}")

        metric.reset()
        for data, label in val_iter:
            metric.update(label, net(data))
        name, acc = metric.get()
        print(f"epoch {epoch}: val-{name}={acc:.4f}")

    if args.checkpoint_prefix:
        net.save_parameters(args.checkpoint_prefix + ".params")
        print("saved", args.checkpoint_prefix + ".params")
    return acc


if __name__ == "__main__":
    main()

"""Sparse NDArray storage types: row_sparse and CSR.

Capability parity with the reference's sparse arrays
(include/mxnet/ndarray.h:63-65 storage-type enum;
python/mxnet/ndarray/sparse.py RowSparseNDArray/CSRNDArray) with a
TPU-first execution strategy (SURVEY.md §7 "hard parts"): sparse
layouts live as (values, indices[, indptr]) device arrays, and sparse
kernels lower to gather / segment-sum / scatter-add — the XLA-friendly
forms — rather than CUDA-style per-row kernels. Ops without a sparse
implementation fall back to dense, mirroring the reference's
storage-fallback dispatch (DispatchMode::kFComputeFallback,
src/imperative/imperative_utils.h).

Sparse autograd: like the reference, sparse arrays are leaf inputs of
dense compute (a CSR/RSP input is densified by the fallback before a
differentiable op); row_sparse *gradients* arise from
Embedding(sparse_grad=True) and are handled by the optimizer's lazy
update path.
"""
from __future__ import annotations

import numpy as onp
import jax
import jax.numpy as jnp

from .. import engine
from ..base import resolve_dtype
from ..context import current_context
from .ndarray import NDArray

# ---------------------------------------------------------------------------
# Index dtype policy (reference: src/libinfo.cc:39-157 INT64_TENSOR_SIZE
# build flag). XLA's native index width is int32, so index arrays are
# int32 by design unless jax x64 mode is on — the shared 64-bit policy
# in base.narrow_dtype, which bounds-checks host values instead of
# silently wrapping. Enabling x64 switches index arrays to true int64,
# the reference's large-tensor build.
# ---------------------------------------------------------------------------
def index_dtype():
    """The dtype used for sparse index/indptr arrays (int32 unless jax
    x64 mode is enabled)."""
    from ..base import narrow_dtype
    return onp.dtype(narrow_dtype(None, onp.int64))


def _as_index_array(vals):
    """Convert host/device values to the index dtype, bounds-checked
    via base.narrow_dtype (device arrays skip the value check — they
    are already within the active policy, and re-checking would force
    a host sync)."""
    from ..base import narrow_dtype
    raw = getattr(vals, "_data", vals)
    host_vals = None if isinstance(raw, jax.Array) else raw
    return jnp.asarray(raw, narrow_dtype(host_vals, onp.int64))


class BaseSparseNDArray(NDArray):
    __slots__ = ("_aux", "_shape")

    # dense-materializing NumPy-API methods go through tostype
    def asnumpy(self):
        return self.todense().asnumpy()

    @property
    def shape(self):
        return self._shape

    @property
    def dtype(self):
        return onp.dtype(self._data.dtype)

    def todense(self) -> NDArray:
        raise NotImplementedError

    def tostype(self, stype):
        if stype == self.stype:
            return self
        if stype == "default":
            return self.todense()
        return cast_storage(self.todense(), stype)

    def astype(self, dtype, copy=True):
        return self._replace_data(jnp.asarray(self._data,
                                              resolve_dtype(dtype)))

    def copy(self):
        return self._replace_data(self._data)

    def wait_to_read(self):
        jax.block_until_ready(self._data)
        return self

    def __repr__(self):
        return (f"<{type(self).__name__} {self.shape} "
                f"dtype={self.dtype.name}>")

    # arithmetic: scalar ops keep sparsity; array ops fall back dense
    def __mul__(self, other):
        if onp.isscalar(other):
            return self._replace_data(self._data * other)
        return self.todense() * other

    __rmul__ = __mul__

    def __truediv__(self, other):
        if onp.isscalar(other):
            return self._replace_data(self._data / other)
        return self.todense() / other

    def __neg__(self):
        return self._replace_data(-self._data)

    def __add__(self, other):
        if isinstance(other, type(self)):
            return add(self, other)
        return self.todense() + other

    def __radd__(self, other):
        return self.todense() + other

    def __sub__(self, other):
        if isinstance(other, type(self)):
            return add(self, other._replace_data(-other._data))
        return self.todense() - other

    def sum(self, *a, **k):
        return self.todense().sum(*a, **k)

    def mean(self, *a, **k):
        return self.todense().mean(*a, **k)


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at `indices` hold `data`; all other rows are zero
    (parity: python/mxnet/ndarray/sparse.py RowSparseNDArray)."""

    @property
    def stype(self):
        return "row_sparse"

    @property
    def data(self):
        return NDArray(self._data, ctx=self._ctx)

    @property
    def indices(self):
        return NDArray(self._aux[0], ctx=self._ctx)

    def _replace_data(self, new_data):
        out = RowSparseNDArray.__new__(RowSparseNDArray)
        NDArray.__init__(out, new_data, ctx=self._ctx)
        out._aux = self._aux
        out._shape = self._shape
        return out

    def todense(self) -> NDArray:
        idx = self._aux[0]
        dense = jnp.zeros(self._shape, self._data.dtype)
        dense = dense.at[idx].set(self._data)
        return NDArray(engine.track(dense), ctx=self._ctx)

    def retain(self, row_ids):
        return retain(self, row_ids)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (parity: CSRNDArray)."""

    @property
    def stype(self):
        return "csr"

    @property
    def data(self):
        return NDArray(self._data, ctx=self._ctx)

    @property
    def indices(self):
        return NDArray(self._aux[0], ctx=self._ctx)

    @property
    def indptr(self):
        return NDArray(self._aux[1], ctx=self._ctx)

    def _replace_data(self, new_data):
        out = CSRNDArray.__new__(CSRNDArray)
        NDArray.__init__(out, new_data, ctx=self._ctx)
        out._aux = self._aux
        out._shape = self._shape
        return out

    def _row_ids(self):
        """Per-nnz row id, computed as a gather-free searchsorted —
        static nnz keeps this jittable."""
        nnz = self._data.shape[0]
        return jnp.searchsorted(self._aux[1],
                                jnp.arange(nnz, dtype=jnp.int32),
                                side="right") - 1

    def todense(self) -> NDArray:
        rows = self._row_ids()
        cols = self._aux[0]
        dense = jnp.zeros(self._shape, self._data.dtype)
        dense = dense.at[rows, cols].add(self._data)
        return NDArray(engine.track(dense), ctx=self._ctx)

    def __getitem__(self, key):
        if isinstance(key, int):
            key = slice(key, key + 1)
        if isinstance(key, slice):
            dense = self.todense()[key]
            return cast_storage(dense, "csr")
        raise TypeError("CSRNDArray supports row slicing only")


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------
def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Build a RowSparseNDArray from (data, indices) or a dense source."""
    ctx = ctx or current_context()
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2:
        data, indices = arg1
        data = jnp.asarray(getattr(data, "_data", data),
                           resolve_dtype(dtype) if dtype else None)
        indices = _as_index_array(indices)
        order = jnp.argsort(indices)
        data, indices = data[order], indices[order]
        if shape is None:
            raise ValueError("shape required for (data, indices) input")
        out = RowSparseNDArray.__new__(RowSparseNDArray)
        NDArray.__init__(out, engine.track(data), ctx=ctx)
        out._aux = [engine.track(indices)]
        out._shape = tuple(shape)
        return out
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(
        jnp.asarray(arg1, resolve_dtype(dtype) if dtype else None), ctx=ctx)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Build a CSRNDArray from (data, indices, indptr) or dense."""
    ctx = ctx or current_context()
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = jnp.asarray(getattr(data, "_data", data),
                           resolve_dtype(dtype) if dtype else None)
        indices = _as_index_array(indices)
        indptr = _as_index_array(indptr)
        if shape is None:
            raise ValueError("shape required for (data, indices, indptr)")
        out = CSRNDArray.__new__(CSRNDArray)
        NDArray.__init__(out, engine.track(data), ctx=ctx)
        out._aux = [engine.track(indices), engine.track(indptr)]
        out._shape = tuple(shape)
        return out
    dense = arg1 if isinstance(arg1, NDArray) else NDArray(
        jnp.asarray(arg1, resolve_dtype(dtype) if dtype else None), ctx=ctx)
    return cast_storage(dense, "csr")


def zeros(stype, shape, ctx=None, dtype=None):
    dtype = resolve_dtype(dtype) if dtype else onp.float32
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    if stype == "row_sparse":
        return row_sparse_array(
            (jnp.zeros((0,) + shape[1:], dtype),
             jnp.zeros((0,), index_dtype())), shape=shape, ctx=ctx)
    if stype == "csr":
        return csr_matrix(
            (jnp.zeros((0,), dtype), jnp.zeros((0,), index_dtype()),
             jnp.zeros((shape[0] + 1,), index_dtype())), shape=shape, ctx=ctx)
    if stype == "default":
        from .. import numpy as np_mod
        return np_mod.zeros(shape, dtype=dtype, ctx=ctx)
    raise ValueError(f"unknown stype {stype!r}")


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


# ---------------------------------------------------------------------------
# storage casts (parity: cast_storage op, src/operator/tensor/cast_storage*)
# ---------------------------------------------------------------------------
def cast_storage(arr, stype):
    if isinstance(arr, BaseSparseNDArray):
        if stype == arr.stype:
            return arr
        arr = arr.todense()
    if stype == "default":
        return arr
    host = onp.asarray(arr.asnumpy())
    if stype == "row_sparse":
        nz_rows = onp.nonzero(host.reshape(host.shape[0], -1).any(axis=1))[0]
        return row_sparse_array((host[nz_rows], nz_rows.astype(onp.int64)),
                                shape=host.shape, ctx=arr.ctx,
                                dtype=host.dtype)
    if stype == "csr":
        if host.ndim != 2:
            raise ValueError("csr requires a 2-D array")
        rows, cols = onp.nonzero(host)
        data = host[rows, cols]
        indptr = onp.zeros(host.shape[0] + 1, onp.int64)
        onp.add.at(indptr, rows + 1, 1)
        indptr = onp.cumsum(indptr)
        return csr_matrix((data, cols.astype(onp.int64), indptr),
                          shape=host.shape, ctx=arr.ctx, dtype=host.dtype)
    raise ValueError(f"unknown stype {stype!r}")


# ---------------------------------------------------------------------------
# sparse ops
# ---------------------------------------------------------------------------
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware matmul.

    csr × dense lowers to gather + segment-sum (the reference's
    dot(csr, dense) kernel, src/operator/tensor/dot-inl.h);
    csr.T × dense lowers to scatter-add. row_sparse × dense gathers
    the stored rows then scatter-adds into the output.
    """
    from ..ops import apply_op
    # the gather/segment-sum kernels are written for a 2-D rhs; a
    # 1-D vector is the (n, 1) column promoted back down afterwards
    if isinstance(rhs, NDArray) and not isinstance(
            rhs, BaseSparseNDArray) and rhs.ndim == 1 and \
            isinstance(lhs, (CSRNDArray, RowSparseNDArray)):
        return dot(lhs, rhs.reshape(-1, 1), transpose_a=transpose_a,
                   transpose_b=False).reshape(-1)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, NDArray) and \
            not isinstance(rhs, BaseSparseNDArray):
        cols = lhs._aux[0]
        rows = lhs._row_ids()
        n_rows, n_cols = lhs.shape

        def csr_dot(data, r):
            if transpose_b:
                r = r.T
            if not transpose_a:
                # out[i,:] = sum_k data[k] * r[cols[k],:] for rows[k]==i
                contrib = data[:, None] * r[cols]
                return jax.ops.segment_sum(contrib, rows,
                                           num_segments=n_rows)
            contrib = data[:, None] * r[rows]
            out = jnp.zeros((n_cols, r.shape[1]), data.dtype)
            return out.at[cols].add(contrib)

        return apply_op(csr_dot, lhs.data, rhs, name="sparse_dot_csr")
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, NDArray) and \
            not isinstance(rhs, BaseSparseNDArray):
        idx = lhs._aux[0]
        n_rows = lhs.shape[0]

        def rsp_dot(data, r):
            if transpose_b:
                r = r.T
            if not transpose_a:
                out = jnp.zeros((n_rows, r.shape[1]), data.dtype)
                return out.at[idx].set(data @ r)
            return data.T @ r[idx]

        return apply_op(rsp_dot, lhs.data, rhs, name="sparse_dot_rsp")
    # dense fallback
    from .. import numpy as np_mod
    ldense = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    rdense = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    if transpose_a:
        ldense = ldense.T
    if transpose_b:
        rdense = rdense.T
    return np_mod.dot(ldense, rdense)


def add(lhs, rhs):
    """Sparse + sparse of matching stype stays sparse."""
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        assert lhs.shape == rhs.shape
        idx = jnp.concatenate([lhs._aux[0], rhs._aux[0]])
        dat = jnp.concatenate([lhs._data, rhs._data])
        # unique pads with fill_value=shape[0], which sorts after every
        # real row id, so the first n entries are the real rows
        uniq, inv = jnp.unique(idx, return_inverse=True,
                               size=idx.shape[0], fill_value=lhs.shape[0])
        summed = jax.ops.segment_sum(dat, inv, num_segments=idx.shape[0])
        n = int((uniq < lhs.shape[0]).sum())
        return row_sparse_array((summed[:n], uniq[:n]),
                                shape=lhs.shape, ctx=lhs.ctx)
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return cast_storage(lhs.todense() + rhs.todense(), "csr")
    return (lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs) + \
        (rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs)


elemwise_add = add


def retain(rsp, row_ids):
    """Keep only `row_ids` rows of a RowSparseNDArray (parity:
    sparse_retain, used by the kvstore row_sparse_pull path)."""
    if not isinstance(rsp, RowSparseNDArray):
        raise TypeError("retain expects a RowSparseNDArray")
    want = _as_index_array(row_ids)
    have = rsp._aux[0]
    # membership via sorted search (have is sorted by construction)
    pos = jnp.searchsorted(have, want)
    pos = jnp.clip(pos, 0, have.shape[0] - 1) if have.shape[0] else pos
    hit = (have.shape[0] > 0) & (have[pos] == want) \
        if have.shape[0] else jnp.zeros(want.shape, bool)
    data = rsp._data[pos] * hit[:, None].astype(rsp._data.dtype) \
        if rsp._data.ndim > 1 else rsp._data[pos] * hit
    return row_sparse_array((data, want), shape=rsp.shape, ctx=rsp.ctx)


def norm(arr, ord=2):
    return NDArray(engine.track(jnp.linalg.norm(arr._data.ravel(),
                                                ord=ord)), ctx=arr.ctx)


def array(source, ctx=None, dtype=None):
    """Sparse-aware array constructor (parity: mx.nd.sparse.array)."""
    if isinstance(source, BaseSparseNDArray):
        return source
    try:
        import scipy.sparse as sps
        if sps.issparse(source):
            csr = source.tocsr()
            return csr_matrix((csr.data, csr.indices.astype(onp.int64),
                               csr.indptr.astype(onp.int64)),
                              shape=csr.shape, ctx=ctx, dtype=dtype)
    except ImportError:
        pass
    raise ValueError("use mx.np.array for dense sources")

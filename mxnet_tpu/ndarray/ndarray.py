"""NDArray: the imperative, async array type.

Capability parity with the reference's NDArray
(include/mxnet/ndarray.h:82; python/mxnet/numpy/multiarray.py), mapped
onto JAX:

- The payload is a ``jax.Array`` — an asynchronous future on device.
  Creating/operating returns immediately (the reference's engine-push
  contract); ``wait_to_read``/``asnumpy`` are the sync points where
  deferred device errors also surface.
- Immutability + functional updates replace the engine's write-var
  discipline: an "in-place" op installs a new buffer and bumps
  ``_version`` (the reference bumps its engine var instead).
- ``_grad``/``_grad_req``/``_node`` are the autograd attachment points
  (parity: AGInfo, include/mxnet/imperative.h:54).
- Views/slices are functional copies, not aliases (XLA arrays cannot
  alias); ``x[i:j] = v`` still works because it rewrites the base.
- Storage types: dense only on device. The stype slot is kept so
  sparse (row_sparse/CSR) can land later without API churn
  (SURVEY.md §7 stage 2).
"""
from __future__ import annotations

import numpy as onp

import jax
import jax.numpy as jnp

from .. import engine
from ..base import resolve_dtype
from ..context import Context, current_context


def _coerce_index_dtype(arr):
    """Float index arrays truncate to int (reference parity: the
    mx.np default dtype is float32, so `a[np.array([0, 2])]` arrives
    float and the reference accepts it — for reads AND writes)."""
    if jnp.issubdtype(arr.dtype, jnp.inexact):
        return arr.astype(jnp.int64 if jax.config.jax_enable_x64
                          else jnp.int32)
    return arr


def _to_jax_index(key):
    """Convert an index expression possibly containing NDArrays."""
    if isinstance(key, NDArray):
        return _coerce_index_dtype(key._data)
    if isinstance(key, tuple):
        return tuple(_to_jax_index(k) for k in key)
    if isinstance(key, list):
        return [_to_jax_index(k) for k in key]
    return key


class NDArray:
    """An async, device-resident n-dimensional array."""

    __slots__ = ("_data", "_ctx", "_grad", "_grad_req", "_node",
                 "_fresh_grad", "_version", "_bucket_pad", "__weakref__")

    # Make `ndarray op numpy_array` hit our reflected ops, not numpy's.
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Context = None, _track: bool = False):
        if _track:
            data = engine.track(data)
        self._data = data
        self._ctx = ctx if ctx is not None else current_context()
        self._grad = None
        self._grad_req = "null"
        self._node = None
        self._fresh_grad = False
        self._version = 0

    # ------------------------------------------------------------------
    # basic properties
    # ------------------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return onp.dtype(self._data.dtype)

    @property
    def size(self):
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def ctx(self) -> Context:
        return self._ctx

    context = ctx

    @property
    def device(self) -> Context:
        return self._ctx

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self):
        return self.transpose()

    @property
    def grad(self):
        return self._grad

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        try:
            arr = self.asnumpy()
        except Exception as e:  # async error surfaced at print time
            return f"NDArray<error: {e}>"
        return f"array({arr}, ctx={self._ctx})"

    def __str__(self):
        return str(self.asnumpy())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if self.size != 1:
            raise ValueError(
                "The truth value of an array with more than one element is "
                "ambiguous.")
        return bool(self.item())

    def __int__(self):
        return int(self.item())

    def __float__(self):
        return float(self.item())

    def __index__(self):
        if self.ndim == 0 and onp.issubdtype(self.dtype, onp.integer):
            return int(self.item())
        raise TypeError("only integer scalar arrays can be converted to an index")

    def __hash__(self):
        return id(self)

    def __format__(self, fmt):
        if self.size == 1:
            return format(self.item(), fmt)
        return repr(self)

    # ------------------------------------------------------------------
    # sync / conversion
    # ------------------------------------------------------------------
    def wait_to_read(self):
        """Block until computed; re-raise deferred device errors."""
        engine.wait_to_read(self._data)
        return self

    wait_to_write = wait_to_read

    def asnumpy(self) -> onp.ndarray:
        d = engine.wait_to_read(self._data)
        if str(d.dtype) == "bfloat16":
            return onp.asarray(d.astype(jnp.float32)).astype(onp.float32)
        return onp.asarray(d)

    def item(self):
        return self.asnumpy().item()

    def asscalar(self):
        return self.item()

    def tolist(self):
        return self.asnumpy().tolist()

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __array_function__(self, func, types, args, kwargs):
        """NumPy dispatch protocol: numpy.foo(mx_arr) routes to the
        mx.np implementation when one exists, host fallback otherwise
        (parity: python/mxnet/numpy_dispatch_protocol.py +
        numpy/fallback.py)."""
        from ..numpy import dispatch
        return dispatch.array_function(self, func, types, args, kwargs)

    def __array_ufunc__(self, ufunc, method, *inputs, **kwargs):
        from ..numpy import dispatch
        return dispatch.array_ufunc(self, ufunc, method, *inputs, **kwargs)

    def __dlpack__(self, stream=None):
        return self._data.__dlpack__()

    def __dlpack_device__(self):
        return self._data.__dlpack_device__()

    def astype(self, dtype, copy=True):
        dtype = resolve_dtype(dtype)
        if not copy and self.dtype == dtype:
            return self
        from ..ops import apply_op
        return apply_op(lambda x: jnp.asarray(x, _jdt(dtype)), self,
                        name="astype")

    def as_np_ndarray(self):
        return self

    def as_nd_ndarray(self):
        return self

    # ------------------------------------------------------------------
    # context movement
    # ------------------------------------------------------------------
    def as_in_context(self, ctx: Context):
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    as_in_ctx = as_in_context
    to_device = as_in_context

    def copyto(self, other):
        """Copy to a Context or into another NDArray (parity:
        NDArray::CopyFromTo, src/ndarray/ndarray.cc:1331)."""
        if isinstance(other, Context):
            data = jax.device_put(self._data, other.jax_device)
            return NDArray(engine.track(data), ctx=other)
        if isinstance(other, NDArray):
            data = jax.device_put(self._data, other.ctx.jax_device)
            other._install(jnp.asarray(data, other._data.dtype))
            return other
        raise TypeError(f"copyto expects Context or NDArray, got {type(other)}")

    def copy(self):
        # A genuinely distinct buffer: jax arrays are immutable, so an
        # alias would normally do — but fused-step buffer donation
        # (parallel/train_step.py) can invalidate donated buffers, and a
        # copy() result must survive that.
        return NDArray(engine.track(jnp.array(self._data, copy=True)),
                       ctx=self._ctx)

    # ------------------------------------------------------------------
    # autograd attachment
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype=None):
        """Allocate a gradient buffer and mark this array as a variable."""
        self._grad = NDArray(engine.track(jnp.zeros(self.shape, self._data.dtype)),
                             ctx=self._ctx)
        self._grad_req = grad_req
        self._node = None

    def drop_grad(self):
        self._grad = None
        self._grad_req = "null"

    def zero_grad(self):
        if self._grad is not None:
            self._grad._install(jnp.zeros_like(self._grad._data))

    def detach(self):
        out = NDArray(self._data, ctx=self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd
        autograd.backward([self], head_grads=[out_grad] if out_grad is not None
                          else None, retain_graph=retain_graph,
                          train_mode=train_mode)

    # ------------------------------------------------------------------
    # mutation (functional under the hood)
    # ------------------------------------------------------------------
    def _install(self, new_data):
        """Install a new buffer (the write-var version bump)."""
        self._data = engine.track(new_data)
        self._version += 1
        return self

    def _stateful_update(self, fn, new):
        """Apply ``fn(old_raw, new_raw)`` as a state update.

        Used for auxiliary (non-differentiable) state like BatchNorm
        running statistics. Eagerly this installs the new buffer; inside
        a hybridize trace the update is registered with the tracer so
        the compiled graph threads it as an extra output and writes it
        back after each call (the reference mutates aux NDArrays from
        inside the kernel instead).
        """
        import jax as _jax
        newd = fn(self._data, new._data if isinstance(new, NDArray) else new)
        # aux state must keep its dtype: stats math may upcast (e.g.
        # bf16 nets accumulate in f32) and a dtype flip would retrace
        # every compiled step that threads this buffer through.
        if newd.dtype != self._data.dtype:
            newd = jnp.asarray(newd, self._data.dtype)
        if isinstance(newd, _jax.core.Tracer):
            from ..gluon import _deferred
            _deferred.register_state_update(self, newd)
        else:
            self._install(newd)
        return self

    def __setitem__(self, key, value):
        if isinstance(value, NDArray):
            value = value._data
        idx = _to_jax_index(key)
        if idx is Ellipsis or (isinstance(idx, slice) and idx == slice(None)):
            new = jnp.broadcast_to(jnp.asarray(value, self._data.dtype),
                                   self.shape)
        else:
            new = self._data.at[idx].set(jnp.asarray(value).astype(self._data.dtype)
                                         if not onp.isscalar(value) else value)
        if new.shape != self.shape:
            raise ValueError("setitem cannot change shape")
        self._install(jnp.asarray(new, self._data.dtype))

    def __getitem__(self, key):
        from ..ops import apply_op
        nd_keys = []
        if isinstance(key, NDArray):
            nd_keys = [key]
        elif isinstance(key, tuple):
            nd_keys = [k for k in key if isinstance(k, NDArray)]

        def do_index(x, *keys):
            kit = iter(keys)
            if isinstance(key, NDArray):
                k = _coerce_index_dtype(next(kit))
            elif isinstance(key, tuple):
                k = tuple(_coerce_index_dtype(next(kit))
                          if isinstance(kk, NDArray) else kk
                          for kk in key)
            else:
                k = key
            return x[k]

        return apply_op(do_index, self, *nd_keys, name="getitem")

    # ------------------------------------------------------------------
    # arithmetic — delegate to the mx.np namespace (single source of truth)
    # ------------------------------------------------------------------
    def _np(self):
        from .. import numpy as _mnp
        return _mnp

    def __add__(self, o): return self._np().add(self, o)
    def __radd__(self, o): return self._np().add(o, self)
    def __sub__(self, o): return self._np().subtract(self, o)
    def __rsub__(self, o): return self._np().subtract(o, self)
    def __mul__(self, o): return self._np().multiply(self, o)
    def __rmul__(self, o): return self._np().multiply(o, self)
    def __truediv__(self, o): return self._np().true_divide(self, o)
    def __rtruediv__(self, o): return self._np().true_divide(o, self)
    def __floordiv__(self, o): return self._np().floor_divide(self, o)
    def __rfloordiv__(self, o): return self._np().floor_divide(o, self)
    def __mod__(self, o): return self._np().mod(self, o)
    def __rmod__(self, o): return self._np().mod(o, self)
    def __divmod__(self, o): return (self // o, self % o)
    def __pow__(self, o): return self._np().power(self, o)
    def __rpow__(self, o): return self._np().power(o, self)
    def __matmul__(self, o): return self._np().matmul(self, o)
    def __rmatmul__(self, o): return self._np().matmul(o, self)
    def __neg__(self): return self._np().negative(self)
    def __pos__(self): return self
    def __abs__(self): return self._np().abs(self)
    def __invert__(self): return self._np().invert(self)
    def __and__(self, o): return self._np().bitwise_and(self, o)
    def __rand__(self, o): return self._np().bitwise_and(o, self)
    def __or__(self, o): return self._np().bitwise_or(self, o)
    def __ror__(self, o): return self._np().bitwise_or(o, self)
    def __xor__(self, o): return self._np().bitwise_xor(self, o)
    def __rxor__(self, o): return self._np().bitwise_xor(o, self)
    def __lshift__(self, o): return self._np().left_shift(self, o)
    def __rshift__(self, o): return self._np().right_shift(self, o)

    def __eq__(self, o): return self._np().equal(self, o)
    def __ne__(self, o): return self._np().not_equal(self, o)
    def __lt__(self, o): return self._np().less(self, o)
    def __le__(self, o): return self._np().less_equal(self, o)
    def __gt__(self, o): return self._np().greater(self, o)
    def __ge__(self, o): return self._np().greater_equal(self, o)

    # in-place: functional rebind (new buffer, version bump)
    def __iadd__(self, o): return self._inplace(self._np().add(self, o))
    def __isub__(self, o): return self._inplace(self._np().subtract(self, o))
    def __imul__(self, o): return self._inplace(self._np().multiply(self, o))
    def __itruediv__(self, o): return self._inplace(self._np().true_divide(self, o))
    def __ifloordiv__(self, o): return self._inplace(self._np().floor_divide(self, o))
    def __imod__(self, o): return self._inplace(self._np().mod(self, o))
    def __ipow__(self, o): return self._inplace(self._np().power(self, o))

    def _inplace(self, result):
        self._data = result._data
        self._node = result._node
        self._version += 1
        return self

    # ------------------------------------------------------------------
    # shape / reduction methods (delegate to mx.np)
    # ------------------------------------------------------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self._np().reshape(self, shape)

    def reshape_like(self, other):
        return self._np().reshape(self, other.shape)

    def transpose(self, *axes):
        if len(axes) == 0:
            axes = None
        elif len(axes) == 1 and (axes[0] is None or isinstance(axes[0], (tuple, list))):
            axes = axes[0]
        return self._np().transpose(self, axes)

    def swapaxes(self, a1, a2): return self._np().swapaxes(self, a1, a2)
    def flatten(self): return self.reshape(-1)
    def ravel(self): return self.reshape(-1)
    def squeeze(self, axis=None): return self._np().squeeze(self, axis)
    def expand_dims(self, axis): return self._np().expand_dims(self, axis)
    def broadcast_to(self, shape): return self._np().broadcast_to(self, shape)
    def broadcast_like(self, other): return self._np().broadcast_to(self, other.shape)
    def repeat(self, repeats, axis=None): return self._np().repeat(self, repeats, axis)
    def tile(self, reps): return self._np().tile(self, reps)
    def flip(self, axis=None): return self._np().flip(self, axis)
    def split(self, indices_or_sections, axis=0):
        return self._np().split(self, indices_or_sections, axis)
    def take(self, indices, axis=None, mode="clip"):
        return self._np().take(self, indices, axis=axis, mode=mode)
    def pad(self, pad_width, mode="constant", **kw):
        return self._np().pad(self, pad_width, mode=mode, **kw)
    def clip(self, a_min=None, a_max=None): return self._np().clip(self, a_min, a_max)
    def round(self, decimals=0): return self._np().round(self, decimals)

    def sum(self, axis=None, dtype=None, out=None, keepdims=False):
        return self._np().sum(self, axis=axis, dtype=dtype, out=out, keepdims=keepdims)
    def mean(self, axis=None, dtype=None, out=None, keepdims=False):
        return self._np().mean(self, axis=axis, dtype=dtype, out=out, keepdims=keepdims)
    def prod(self, axis=None, dtype=None, out=None, keepdims=False):
        return self._np().prod(self, axis=axis, dtype=dtype, out=out, keepdims=keepdims)
    def max(self, axis=None, out=None, keepdims=False):
        return self._np().max(self, axis=axis, out=out, keepdims=keepdims)
    def min(self, axis=None, out=None, keepdims=False):
        return self._np().min(self, axis=axis, out=out, keepdims=keepdims)
    def std(self, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
        return self._np().std(self, axis=axis, dtype=dtype, out=out, ddof=ddof, keepdims=keepdims)
    def var(self, axis=None, dtype=None, out=None, ddof=0, keepdims=False):
        return self._np().var(self, axis=axis, dtype=dtype, out=out, ddof=ddof, keepdims=keepdims)
    def cumsum(self, axis=None, dtype=None): return self._np().cumsum(self, axis, dtype)
    def argmax(self, axis=None): return self._np().argmax(self, axis)
    def argmin(self, axis=None): return self._np().argmin(self, axis)
    def argsort(self, axis=-1): return self._np().argsort(self, axis)
    def sort(self, axis=-1):
        return self._inplace(self._np().sort(self, axis))
    def all(self, axis=None, keepdims=False): return self._np().all(self, axis, keepdims=keepdims)
    def any(self, axis=None, keepdims=False): return self._np().any(self, axis, keepdims=keepdims)
    def nonzero(self): return self._np().nonzero(self)
    def dot(self, other): return self._np().dot(self, other)

    def abs(self): return self._np().abs(self)
    def exp(self): return self._np().exp(self)
    def log(self): return self._np().log(self)
    def sqrt(self): return self._np().sqrt(self)
    def square(self): return self._np().square(self)
    def sign(self): return self._np().sign(self)
    def sigmoid(self): return self._np()._npx().sigmoid(self)
    def relu(self): return self._np()._npx().relu(self)
    def tanh(self): return self._np().tanh(self)

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sparse
        return _sparse.cast_storage(self, stype)

    def slice_axis(self, axis, begin, end):
        idx = [slice(None)] * self.ndim
        idx[axis] = slice(begin, end)
        return self[tuple(idx)]


def _jdt(dtype):
    """numpy dtype -> value usable as a jnp dtype (bfloat16-safe)."""
    return dtype


def waitall():
    engine.waitall()

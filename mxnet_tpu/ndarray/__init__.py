"""mx.nd — the NDArray namespace.

In the reference, mx.nd (legacy) and mx.np (NumPy semantics) are
separate op namespaces with different default semantics. This framework
is NumPy-semantics throughout, so mx.nd is the same function set plus
the NDArray type and serialization entry points — kept so reference
scripts using mx.nd.* keep working.
"""
from .ndarray import NDArray, waitall  # noqa: F401
from . import sparse  # noqa: F401
from .sparse import (  # noqa: F401
    BaseSparseNDArray, RowSparseNDArray, CSRNDArray,
)


def __getattr__(name):
    # Delegate op lookups to the numpy namespace (lazy to avoid cycles).
    from .. import numpy as _np
    from .. import utils_io as _io

    if name == "save":
        return _io.save
    if name == "load":
        return _io.load
    return getattr(_np, name)

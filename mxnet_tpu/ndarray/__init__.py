"""mx.nd — the NDArray namespace.

In the reference, mx.nd (legacy) and mx.np (NumPy semantics) are
separate op namespaces with different default semantics. This framework
is NumPy-semantics throughout, so mx.nd is the same function set plus
the NDArray type and serialization entry points — kept so reference
scripts using mx.nd.* keep working.
"""
from .ndarray import NDArray, waitall  # noqa: F401
from . import sparse  # noqa: F401
from .sparse import (  # noqa: F401
    BaseSparseNDArray, RowSparseNDArray, CSRNDArray,
)


def dot(lhs, rhs, transpose_a=False, transpose_b=False, out=None):
    """Sparse-aware mx.nd.dot with the legacy transpose flags
    (parity: src/operator/tensor/dot.cc — dot(csr, dense),
    dot(csr.T, dense), dot(dense, row_sparse) all dispatch to the
    sparse lowering; dense×dense goes through the numpy namespace)."""
    if isinstance(lhs, BaseSparseNDArray) or \
            isinstance(rhs, BaseSparseNDArray):
        r = sparse.dot(lhs, rhs, transpose_a=transpose_a,
                       transpose_b=transpose_b)
        if out is not None:
            out._inplace(r)
            return out
        return r
    from .. import numpy as _np
    a = _np.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = _np.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return _np.dot(a, b, out=out)


def _legacy_sort(data, axis=-1, is_ascend=True, **kwargs):
    """Legacy ordering signature (parity:
    src/operator/tensor/ordering_op.cc Sort — `is_ascend` flag; the
    numpy namespace sorts ascending only)."""
    from .. import numpy as _np
    out = _np.sort(data, axis=axis)
    return out if is_ascend else _np.flip(out, axis=axis)


def _legacy_argsort(data, axis=-1, is_ascend=True, dtype="float32",
                    **kwargs):
    """Parity: ordering_op.cc argsort — float32 index dtype default."""
    from .. import numpy as _np
    import numpy as onp
    if is_ascend:
        idx = _np.argsort(data, axis=axis)
    elif onp.dtype(str(data.dtype)).kind == "f":
        idx = _np.argsort(-data, axis=axis)  # stable tie order
    else:
        # ints/bool: negation wraps unsigned (and INT_MIN); a flipped
        # ascending argsort is a correct descending order (ties
        # reversed — the reference leaves tie order unspecified)
        idx = _np.flip(_np.argsort(data, axis=axis),
                       axis=-1 if axis is None else axis)
    return idx.astype(dtype)


def _legacy_reverse(data, axis=0, **kwargs):
    """Parity: src/operator/tensor/matrix_op.cc reverse = np.flip."""
    from .. import numpy as _np
    return _np.flip(data, axis=axis)


def _legacy_topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False,
                 dtype="float32", **kwargs):
    from .. import numpy_extension as _npx
    return _npx.topk(data, k=k, axis=axis, ret_typ=ret_typ,
                     is_ascend=is_ascend, dtype=dtype)


def _dlpack_fn(name):
    def f(*a, **kw):
        from .. import dlpack as _dl
        return getattr(_dl, name)(*a, **kw)
    f.__name__ = name
    return f


_LEGACY_OPS = {
    "sort": _legacy_sort,
    "argsort": _legacy_argsort,
    "reverse": _legacy_reverse,
    "topk": _legacy_topk,
    # mx.nd.to_dlpack_for_read & co (reference python/mxnet/dlpack.py)
    "to_dlpack_for_read": _dlpack_fn("to_dlpack_for_read"),
    "to_dlpack_for_write": _dlpack_fn("to_dlpack_for_write"),
    "from_dlpack": _dlpack_fn("from_dlpack"),
}

# Legacy CamelCase operator names (the reference's original imperative
# namespace, e.g. mx.nd.Convolution — src/operator/nn/*.cc NNVM
# registrations). Each delegates to the snake_case npx op with the
# same semantics so reference-era scripts run unchanged.
_CAMEL_TO_NPX = {
    "Activation": "activation",
    "BatchNorm": "batch_norm",
    "Convolution": "convolution",
    "Deconvolution": "deconvolution",
    "Dropout": "dropout",
    "Embedding": "embedding",
    "FullyConnected": "fully_connected",
    "LayerNorm": "layer_norm",
    "GroupNorm": "group_norm",
    "InstanceNorm": "instance_norm",
    "LeakyReLU": "leaky_relu",
    "Pooling": "pooling",
    "RNN": "rnn",
    "SequenceMask": "sequence_mask",
    "SequenceLast": "sequence_last",
    "SequenceReverse": "sequence_reverse",
    "L2Normalization": "l2_normalization",
    "LRN": "lrn",
    "Custom": "custom",
    "ROIPooling": "roi_pooling",
    "ROIAlign": "roi_align",
    "BlockGrad": "stop_gradient",
    "UpSampling": "upsampling",
    "SoftmaxOutput": "softmax_output",
    "MakeLoss": "make_loss",
    "LinearRegressionOutput": "linear_regression_output",
    "MAERegressionOutput": "mae_regression_output",
    "LogisticRegressionOutput": "logistic_regression_output",
    "BilinearSampler": "bilinear_sampler",
    "GridGenerator": "grid_generator",
    "SpatialTransformer": "spatial_transformer",
    "Correlation": "correlation",
}


def _camel_wrappers():
    """CamelCase ops whose legacy signatures need adapting rather than
    delegating 1:1 (matrix_op.cc / slice_channel.cc attr names)."""
    from .. import numpy as _np
    from .. import numpy_extension as _npx

    def Concat(*data, dim=1, num_args=None, **kw):
        return _np.concatenate(data, axis=dim)

    def SliceChannel(data, num_outputs=1, axis=1, squeeze_axis=False,
                     **kw):
        outs = _np.split(data, num_outputs, axis=axis)
        if squeeze_axis:
            outs = [o.squeeze(axis) for o in outs]
        return outs

    def SwapAxis(data, dim1=0, dim2=0, **kw):
        return _np.swapaxes(data, dim1, dim2)

    def Cast(data, dtype="float32", **kw):
        return data.astype(dtype)

    def Flatten(data, **kw):
        return data.reshape(data.shape[0], -1)

    def SoftmaxActivation(data, mode="instance", **kw):
        return _npx.softmax(data, axis=1 if mode == "channel" else -1)

    def ElementWiseSum(*data, num_args=None, **kw):
        out = data[0]
        for d in data[1:]:
            out = out + d
        return out

    def Reshape(data, shape=None, reverse=False, target_shape=None,
                keep_highest=False, **kw):
        # legacy special codes 0/-1/-2/-3/-4 (matrix_op-inl.h); the
        # lowercase nd.reshape keeps numpy semantics by design
        from ..base import legacy_reshape_shape
        if shape is not None:
            return data.reshape(legacy_reshape_shape(
                data.shape, shape, reverse=reverse))
        if target_shape is None:
            raise ValueError("Reshape needs shape= (or the deprecated "
                             "target_shape=)")
        # deprecated target_shape path (matrix_op-inl.h:205-223):
        # keep_highest pins dim 0; exactly one 0 entry is inferred
        out = [int(s) for s in target_shape]
        start = 0
        if keep_highest:
            out[0] = data.shape[0]
            start = 1
        zeros = [i for i in range(start, len(out)) if out[i] == 0]
        if len(zeros) == 1:
            known = 1
            for i, d in enumerate(out):
                if i != zeros[0]:
                    known *= d
            out[zeros[0]] = data.size // max(known, 1)
        return data.reshape(tuple(out))

    def Crop(*data, offset=(0, 0), h_w=(0, 0), center_crop=False, **kw):
        # crop.cc: crop data (NCHW) to the size of the second input
        # (or h_w), at `offset` or centered; out-of-range crops error
        # like the reference CHECKs instead of silently clamping
        x = data[0]
        th, tw = (data[1].shape[2:4] if len(data) == 2
                  else (int(h_w[0]), int(h_w[1])))
        if th <= 0 or tw <= 0:
            raise ValueError("Crop needs a reference input or a "
                             f"positive h_w (got h_w=({th}, {tw}))")
        H, W = x.shape[2], x.shape[3]
        if center_crop:
            oy, ox = (H - th) // 2, (W - tw) // 2
        else:
            oy, ox = int(offset[0]), int(offset[1])
        if oy < 0 or ox < 0 or oy + th > H or ox + tw > W:
            raise ValueError(
                f"Crop window ({th}, {tw}) at offset ({oy}, {ox}) "
                f"exceeds input spatial dims ({H}, {W})")
        return x[:, :, oy:oy + th, ox:ox + tw]

    return {k: v for k, v in locals().items() if not k.startswith("_")}


def __getattr__(name):
    # Delegate op lookups to the numpy namespace (lazy to avoid cycles).
    from .. import numpy as _np
    from .. import utils_io as _io

    if name == "save":
        return _io.save
    if name == "load":
        return _io.load
    if name in _LEGACY_OPS:
        return _LEGACY_OPS[name]
    if name in _CAMEL_TO_NPX:
        from .. import numpy_extension as _npx
        fn = getattr(_npx, _CAMEL_TO_NPX[name])
        globals()[name] = fn  # cache: next access skips __getattr__
        return fn
    if name[:1].isupper():
        wrappers = _camel_wrappers()
        if name in wrappers:
            globals().update(wrappers)  # build the closures only once
            return wrappers[name]
    return getattr(_np, name)

"""mx.nd — the NDArray namespace.

In the reference, mx.nd (legacy) and mx.np (NumPy semantics) are
separate op namespaces with different default semantics. This framework
is NumPy-semantics throughout, so mx.nd is the same function set plus
the NDArray type and serialization entry points — kept so reference
scripts using mx.nd.* keep working.
"""
from .ndarray import NDArray, waitall  # noqa: F401
from . import sparse  # noqa: F401
from .sparse import (  # noqa: F401
    BaseSparseNDArray, RowSparseNDArray, CSRNDArray,
)


def _legacy_sort(data, axis=-1, is_ascend=True, **kwargs):
    """Legacy ordering signature (parity:
    src/operator/tensor/ordering_op.cc Sort — `is_ascend` flag; the
    numpy namespace sorts ascending only)."""
    from .. import numpy as _np
    out = _np.sort(data, axis=axis)
    return out if is_ascend else _np.flip(out, axis=axis)


def _legacy_argsort(data, axis=-1, is_ascend=True, dtype="float32",
                    **kwargs):
    """Parity: ordering_op.cc argsort — float32 index dtype default."""
    from .. import numpy as _np
    import numpy as onp
    if is_ascend:
        idx = _np.argsort(data, axis=axis)
    elif onp.dtype(str(data.dtype)).kind == "f":
        idx = _np.argsort(-data, axis=axis)  # stable tie order
    else:
        # ints/bool: negation wraps unsigned (and INT_MIN); a flipped
        # ascending argsort is a correct descending order (ties
        # reversed — the reference leaves tie order unspecified)
        idx = _np.flip(_np.argsort(data, axis=axis),
                       axis=-1 if axis is None else axis)
    return idx.astype(dtype)


def _legacy_reverse(data, axis=0, **kwargs):
    """Parity: src/operator/tensor/matrix_op.cc reverse = np.flip."""
    from .. import numpy as _np
    return _np.flip(data, axis=axis)


def _legacy_topk(data, k=1, axis=-1, ret_typ="indices", is_ascend=False,
                 dtype="float32", **kwargs):
    from .. import numpy_extension as _npx
    return _npx.topk(data, k=k, axis=axis, ret_typ=ret_typ,
                     is_ascend=is_ascend, dtype=dtype)


_LEGACY_OPS = {
    "sort": _legacy_sort,
    "argsort": _legacy_argsort,
    "reverse": _legacy_reverse,
    "topk": _legacy_topk,
}


def __getattr__(name):
    # Delegate op lookups to the numpy namespace (lazy to avoid cycles).
    from .. import numpy as _np
    from .. import utils_io as _io

    if name == "save":
        return _io.save
    if name == "load":
        return _io.load
    if name in _LEGACY_OPS:
        return _LEGACY_OPS[name]
    return getattr(_np, name)

"""mx.init — alias of mx.initializer (parity with the reference)."""
from .initializer import *  # noqa: F401,F403
from .initializer import (  # noqa: F401
    Initializer, InitDesc, Zero, Zeros, One, Ones, Constant, Uniform,
    Normal, Orthogonal, Xavier, MSRAPrelu, Bilinear, LSTMBias, Mixed,
    register, create,
)

"""mx.executor — alias module (parity: python/mxnet/executor.py,
whose 2.x Executor is a CachedOp-delegating shim; ours lives with the
symbol package)."""
from .symbol.executor import Executor  # noqa: F401

"""Optimizers (parity: python/mxnet/optimizer/, 22 classes; fused update
kernels src/operator/optimizer_op.cc, contrib/multi_lamb.cc etc.).

TPU-native design: every optimizer defines a pure functional step
``_step(w, g, state, hyper) -> (new_w, new_state)`` over raw jax arrays.
Steps are jit-compiled once per (optimizer, shape, dtype) — the fused
single-kernel update the reference hand-writes in CUDA falls out of XLA
fusion. Scalar hyperparameters (lr, wd, ...) are passed as traced
scalars so changing the learning rate never triggers recompilation.

Mixed precision (parity: *_mp_* update ops): when a weight is
float16/bfloat16 and multi_precision=True, the state carries an fp32
master copy; math runs in fp32 and the bf16 weight is a cast of the
master.
"""
from __future__ import annotations

import functools

import numpy as onp
import jax
import jax.numpy as jnp

from ..ndarray.ndarray import NDArray
from .. import engine
from ..random_state import next_key

__all__ = ["Optimizer", "create", "register", "SGD", "NAG", "Adam", "AdamW",
           "Adamax", "Nadam", "AdaBelief", "RMSProp", "AdaGrad",
           "GroupAdaGrad", "AdaDelta", "Ftrl", "FTML", "LAMB", "LARS",
           "LANS", "Signum", "SGLD", "DCASGD", "Test", "Updater",
           "get_updater"]

_REGISTRY = {}


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REGISTRY[name.lower()](**kwargs)


@functools.lru_cache(maxsize=None)
def _jitted_step(cls, mp):
    """One compiled update kernel per optimizer class (+mp flag)."""
    fn = cls._step_mp if mp else cls._step
    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jitted_multi_step(cls, mp):
    """One compiled MULTI-tensor update per optimizer class (+mp flag):
    applies ``cls._step`` to every parameter of a group inside one XLA
    program (the reference's multi_sgd_update/multi_lamb kernel family,
    here by construction instead of hand-written CUDA). Optimizer
    states are donated — they are trainer-internal, so the update
    rewrites them in place instead of allocating a second copy."""
    def multi(ws, gs, states, hypers):
        # hypers is one stacked (n,)-array per hyper field (not one
        # scalar per field per param): the host pays a handful of
        # device_puts per group instead of 5-8 per PARAMETER, which is
        # what made a 48-param dispatch slower than the loop it
        # replaced. Static indexing recovers the exact per-param
        # scalar, so the traced math is unchanged.
        new_ws, new_states = [], []
        for i, (w, g, s) in enumerate(zip(ws, gs, states)):
            h = {k: (None if v is None else v[i])
                 for k, v in hypers.items()}
            if mp:
                nw, ns = cls._step_mp(w, g, s, h)
            else:
                nw, ns = cls._step(w, jnp.asarray(g, w.dtype), s, h)
            new_ws.append(nw)
            new_states.append(ns)
        return tuple(new_ws), tuple(new_states)
    # weights are NOT donated: user code may hold live aliases of a
    # weight buffer (detach() snapshots, set_data-shared params) that
    # donation would invalidate, and the per-param path never donated
    # them either — memory profile is unchanged (the loop also
    # allocates fresh weight buffers). States are trainer-internal.
    return jax.jit(multi, donate_argnums=(2,))


class Optimizer:
    """Base optimizer (parity: mxnet.optimizer.Optimizer)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=None, lr_scheduler=None,
                 multi_precision=False, param_dict=None, aggregate_num=0,
                 use_fused_step=True, **kwargs):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate if learning_rate is not None else 0.01
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None and learning_rate is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.aggregate_num = aggregate_num
        self.param_dict = param_dict or {}
        self.idx2name = dict(param_idx2name or {})
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = 0
        self.num_update = 0
        self._index_update_count = {}

    # -- lr/wd plumbing ------------------------------------------------
    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lr(self, index):
        lr = self.learning_rate
        if index in self.param_dict:
            p = self.param_dict[index]
            lr *= getattr(p, "lr_mult", 1.0)
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            p = self.param_dict[index]
            wd *= getattr(p, "wd_mult", 1.0)
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- state ---------------------------------------------------------
    def _use_mp(self, weight):
        return self.multi_precision and (
            weight.dtype == onp.float16 or str(weight.dtype) == "bfloat16")

    def create_state(self, index, weight):
        """Return the optimizer state pytree (raw jax arrays) for weight."""
        return ()

    def create_state_multi_precision(self, index, weight):
        if self._use_mp(weight):
            master = jnp.asarray(weight._data, jnp.float32)
            return (master, self.create_state(index, NDArray(master)))
        return self.create_state(index, weight)

    def _migrate_state(self, state):
        """Hook for adapting serialized states from an older layout
        (Updater.set_states); default: unchanged."""
        return state

    def __setstate__(self, d):
        """Unpickling restores __dict__ without __init__, so instances
        serialized before a hyperparameter existed would lack it. Fill
        missing attributes from the class __init__ defaults — one fix
        for every optimizer and every future added knob."""
        import inspect
        self.__dict__.update(d)
        for klass in type(self).__mro__:
            ctor = klass.__dict__.get("__init__")
            if ctor is None:
                continue
            for name, p in inspect.signature(ctor).parameters.items():
                if p.default is inspect.Parameter.empty:
                    continue
                if name not in self.__dict__ and not name.startswith("_"):
                    self.__dict__.setdefault(name, p.default)

    # -- hypers passed into the jitted step ----------------------------
    def _hyper(self, index):
        t = self._index_update_count.get(index, self.num_update)
        return {
            "lr": onp.float32(self._get_lr(index)),
            "wd": onp.float32(self._get_wd(index)),
            "rescale": onp.float32(self.rescale_grad),
            "clip": (onp.float32(self.clip_gradient)
                     if self.clip_gradient is not None else None),
            "t": onp.int32(t),
        }

    @staticmethod
    def _pre(g, w, hyper, wd_in_grad=True):
        """rescale → clip → (optionally) add L2 wd into the gradient."""
        g = g * hyper["rescale"]
        if hyper["clip"] is not None:
            g = jnp.clip(g, -hyper["clip"], hyper["clip"])
        if wd_in_grad:
            g = g + hyper["wd"] * w
        return g

    # -- update API (parity: update / update_multi_precision) ----------
    def update(self, index, weight, grad, state):
        if not isinstance(index, (list, tuple)):
            index, weight, grad, state = [index], [weight], [grad], [state]
        self._update_count(index)
        cls = type(self)
        for i, w, g, s in zip(index, weight, grad, state):
            hyper = self._hyper(i)
            new_w, new_s = _jitted_step(cls, False)(
                w._data, jnp.asarray(g._data, w._data.dtype), s, hyper)
            w._install(new_w)
            self._set_state(i, s, new_s)

    def update_multi_precision(self, index, weight, grad, state):
        if type(self).update is not Optimizer.update:
            # Optimizer subclasses with a custom update() (e.g. SGLD)
            # must not be silently replaced by the base jitted _step.
            return self.update(index, weight, grad, state)
        if not isinstance(index, (list, tuple)):
            index, weight, grad, state = [index], [weight], [grad], [state]
        self._update_count(index)
        cls = type(self)
        for i, w, g, s in zip(index, weight, grad, state):
            hyper = self._hyper(i)
            if self._use_mp(w) and isinstance(s, tuple) and len(s) == 2 and \
                    isinstance(s[0], jax.Array) and s[0].dtype == jnp.float32:
                new_w, new_s = _jitted_step(cls, True)(
                    w._data, g._data, s, hyper)
            else:
                new_w, new_s = _jitted_step(cls, False)(
                    w._data, jnp.asarray(g._data, w._data.dtype), s, hyper)
            w._install(new_w)
            self._set_state(i, s, new_s)

    def fused_update_multi_precision(self, index, weight, grad, state):
        """Multi-tensor update: ONE jitted, donation-friendly program
        per (dtype, multi-precision) group applies this optimizer's
        ``_step`` to all grouped parameters and their states at once
        (2 host dispatches per group instead of 2 per parameter).

        Bit-identical to calling ``update_multi_precision`` per
        parameter: the per-index hypers (lr_mult/wd_mult/update count)
        are computed the same way and the traced math is the same
        ``_step`` — XLA compiles N independent elementwise chains side
        by side. Optimizers overriding ``update()`` (e.g. SGLD) or
        ``update_multi_precision`` itself fall back to the
        per-parameter path, called exactly the way the non-fused
        Trainer loop calls it.

        Returns True when the multi-tensor path ran, False when it
        fell back (so callers label their timing correctly)."""
        if type(self).update is not Optimizer.update or \
                type(self).update_multi_precision is not \
                Optimizer.update_multi_precision:
            for i, w, g, st in zip(index, weight, grad, state):
                self.update_multi_precision([i], [w], [g], [st])
            return False
        cls = type(self)
        # count + hyper interleaved PER INDEX in list order — exactly
        # the per-param loop's sequence, so scheduler-driven lr reads
        # the same num_update even when per-index counts are unequal
        hyper_dicts = []
        for i in index:
            self._update_count([i])
            hyper_dicts.append(self._hyper(i))
        groups = {}
        for pos, (w, s) in enumerate(zip(weight, state)):
            mp = self._use_mp(w) and isinstance(s, tuple) \
                and len(s) == 2 and isinstance(s[0], jax.Array) \
                and s[0].dtype == jnp.float32
            groups.setdefault((str(w._data.dtype), mp), []).append(pos)
        for (_, mp), poss in groups.items():
            # stack per field ((n,) array or None) — field presence is
            # per-optimizer, so it is uniform across the group
            hypers = {k: (None if v0 is None
                          else onp.stack([hyper_dicts[p][k]
                                          for p in poss]))
                      for k, v0 in hyper_dicts[poss[0]].items()}
            ws = tuple(weight[p]._data for p in poss)
            gs = tuple(grad[p]._data for p in poss)
            ss = tuple(state[p] for p in poss)
            # donated (state) leaves must not alias: XLA rejects
            # donating one buffer twice. State pytrees can share
            # buffers (a user-built state, a loaded checkpoint) —
            # copy repeats once; steady-state steps see distinct
            # buffers and skip this. Weights are NOT donated (see
            # _jitted_multi_step), so weight aliasing is fine.
            seen = set()

            def _dealias(x):
                if isinstance(x, jax.Array):
                    if id(x) in seen:
                        return jnp.array(x, copy=True)
                    seen.add(id(x))
                return x
            ss = jax.tree_util.tree_map(_dealias, ss)
            new_ws, new_ss = _jitted_multi_step(cls, mp)(ws, gs, ss,
                                                         hypers)
            for p, nw, ns in zip(poss, new_ws, new_ss):
                weight[p]._install(nw)
                self._set_state(index[p], state[p], ns)
        return True

    def _set_state(self, index, old, new):
        # states are stored by the caller (Trainer/Updater hold the dict);
        # mutate the container in place when it is a list
        self._last_states = getattr(self, "_last_states", {})
        self._last_states[index] = new

    # The functional step; subclasses override. Default: plain SGD.
    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        return w - hyper["lr"] * g, state

    @classmethod
    def _step_mp(cls, w, g, state, hyper):
        master, inner = state
        g32 = jnp.asarray(g, jnp.float32)
        new_master, new_inner = cls._step(master, g32, inner, hyper)
        return jnp.asarray(new_master, w.dtype), (new_master, new_inner)


@register
class Test(Optimizer):
    """Trivial optimizer used by tests (parity: mx.optimizer.Test)."""

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data),)

    @staticmethod
    def _step(w, g, state, hyper):
        (acc,) = state
        g = Optimizer._pre(g, w, hyper)
        return w - hyper["lr"] * g, (acc + g,)


@register
class SGD(Optimizer):
    """SGD with momentum (parity: optimizer/sgd.py; kernels
    src/operator/optimizer_op.cc sgd_update/sgd_mom_update)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lazy_update=False,
                 **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        h = super()._hyper(index)
        h["momentum"] = onp.float32(self.momentum)
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        if not state:
            return w - hyper["lr"] * g, state
        (mom,) = state
        mom = hyper["momentum"] * mom - hyper["lr"] * g
        return w + mom, (mom,)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (parity: optimizer/nag.py)."""

    def __init__(self, learning_rate=0.1, momentum=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        h = super()._hyper(index)
        h["momentum"] = onp.float32(self.momentum)
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        (mom,) = state
        mom = hyper["momentum"] * mom + g
        return w - hyper["lr"] * (g + hyper["momentum"] * mom), (mom,)


@register
class Adam(Optimizer):
    """Adam (parity: optimizer/adam.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data), jnp.zeros_like(weight._data))

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(beta1=onp.float32(self.beta1), beta2=onp.float32(self.beta2),
                 eps=onp.float32(self.epsilon))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        m, v = state
        b1, b2, t = hyper["beta1"], hyper["beta2"], hyper["t"]
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        coef1 = 1.0 - jnp.power(b1, t.astype(jnp.float32))
        coef2 = 1.0 - jnp.power(b2, t.astype(jnp.float32))
        lr_t = hyper["lr"] * jnp.sqrt(coef2) / coef1
        return w - lr_t * m / (jnp.sqrt(v) + hyper["eps"]), (m, v)


@register
class AdamW(Adam):
    """Adam with decoupled weight decay (parity: optimizer/adamW.py —
    the reference applies the wd term with the SAME bias-corrected lr,
    to the already-updated weight)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, correct_bias=True, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, **kwargs)
        self.correct_bias = correct_bias

    def _hyper(self, index):
        h = super()._hyper(index)
        # None/1.0 keeps the flag a static pytree leaf (AdaBelief trick)
        h["correct"] = 1.0 if self.correct_bias else None
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper, wd_in_grad=False)
        m, v = state
        b1, b2, t = hyper["beta1"], hyper["beta2"], hyper["t"]
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        lr_t = hyper["lr"]
        if hyper.get("correct") is not None:
            coef1 = 1.0 - jnp.power(b1, t.astype(jnp.float32))
            coef2 = 1.0 - jnp.power(b2, t.astype(jnp.float32))
            lr_t = lr_t * jnp.sqrt(coef2) / coef1
        w = w - lr_t * m / (jnp.sqrt(v) + hyper["eps"])
        return w - lr_t * hyper["wd"] * w, (m, v)


@register
class Adamax(Adam):
    """AdaMax (parity: optimizer/adamax.py)."""

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        m, u = state
        b1, b2, t = hyper["beta1"], hyper["beta2"], hyper["t"]
        m = b1 * m + (1 - b1) * g
        u = jnp.maximum(b2 * u, jnp.abs(g))
        lr_t = hyper["lr"] / (1.0 - jnp.power(b1, t.astype(jnp.float32)))
        return w - lr_t * m / (u + hyper["eps"]), (m, u)


@register
class Nadam(Adam):
    """Nesterov Adam (parity: optimizer/nadam.py — the reference's
    WARMING momentum schedule mu_t = b1*(1 - 0.5*0.96^(t*sd)) with the
    running product m_schedule carried as optimizer state, not the
    torch-style closed-form variant).

    Documented deviation: the reference keeps ONE m_schedule on the
    optimizer object, advanced once per parameter per step — with N
    parameters it grows by mu_t^N each step, coupling every
    parameter's bias correction to the parameter iteration order.
    Here m_schedule is per-parameter (advanced once per update), which
    matches the published algorithm and the reference's own single-
    parameter behavior exactly.
    """

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, **kwargs)
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data),
                jnp.zeros_like(weight._data),
                jnp.ones((), jnp.float32))  # running m_schedule

    def _migrate_state(self, state):
        # pre-round-5 checkpoints stored (m, v); append m_schedule=1.
        # A multi-precision state is (master, inner_tuple) — recurse.
        if isinstance(state, tuple) and len(state) == 2:
            if isinstance(state[1], tuple):
                return (state[0], self._migrate_state(state[1]))
            return state + (onp.ones((), onp.float32),)
        return state

    def _hyper(self, index):
        h = super()._hyper(index)
        h["sd"] = onp.float32(self.schedule_decay)
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        m, v, msched = state
        b1, b2, t = hyper["beta1"], hyper["beta2"], hyper["t"]
        tf = t.astype(jnp.float32)
        sd = hyper["sd"]
        coef2 = 1.0 - jnp.power(b2, tf)
        mu_t = b1 * (1.0 - 0.5 * jnp.power(0.96, tf * sd))
        mu_t1 = b1 * (1.0 - 0.5 * jnp.power(0.96, (tf + 1.0) * sd))
        msched = msched * mu_t
        msched_next = msched * mu_t1
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        g_prime = g / (1.0 - msched)
        m_prime = m / (1.0 - msched_next)
        v_prime = v / coef2
        m_bar = mu_t1 * m_prime + (1.0 - mu_t) * g_prime
        return w - hyper["lr"] * m_bar / (jnp.sqrt(v_prime)
                                          + hyper["eps"]), \
            (m, v, msched)


@register
class AdaBelief(Adam):
    """AdaBelief — second moment tracks the *surprise* ``(g - m)**2``
    instead of ``g**2`` (parity: optimizer/adabelief.py). The
    reference folds epsilon into the variance accumulator each step
    and adds it again in the denominator; kept for numeric parity."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, correct_bias=True, **kwargs):
        super().__init__(learning_rate=learning_rate, beta1=beta1,
                         beta2=beta2, epsilon=epsilon, **kwargs)
        self.correct_bias = correct_bias

    def _hyper(self, index):
        h = super()._hyper(index)
        # None/1.0 so the flag stays a static pytree leaf (same trick
        # as hyper["clip"]) — a bool leaf would be traced by jit
        h["correct"] = 1.0 if self.correct_bias else None
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        m, s = state
        b1, b2, t = hyper["beta1"], hyper["beta2"], hyper["t"]
        m = b1 * m + (1 - b1) * g
        s = b2 * s + (1 - b2) * jnp.square(g - m) + hyper["eps"]
        lr_t = hyper["lr"]
        if hyper["correct"] is not None:
            tf = t.astype(jnp.float32)
            lr_t = lr_t * jnp.sqrt(1.0 - jnp.power(b2, tf)) \
                / (1.0 - jnp.power(b1, tf))
        return w - lr_t * m / (jnp.sqrt(s) + hyper["eps"]), (m, s)


@register
class RMSProp(Optimizer):
    """RMSProp, optionally centered (parity: optimizer/rmsprop.py)."""

    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum, self.epsilon = rho, momentum, epsilon
        self.centered = centered

    def create_state(self, index, weight):
        def z():
            return jnp.zeros_like(weight._data)
        if self.centered:
            # three DISTINCT buffers: the fused update donates states,
            # and one buffer may not be donated twice
            return (z(), z(), z())  # n, g_avg, delta
        return (z(),)

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(rho=onp.float32(self.rho), mom=onp.float32(self.momentum),
                 eps=onp.float32(self.epsilon))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        rho, eps = hyper["rho"], hyper["eps"]
        if len(state) == 1:
            (n,) = state
            n = rho * n + (1 - rho) * jnp.square(g)
            return w - hyper["lr"] * g / jnp.sqrt(n + eps), (n,)
        n, gavg, delta = state
        n = rho * n + (1 - rho) * jnp.square(g)
        gavg = rho * gavg + (1 - rho) * g
        delta = hyper["mom"] * delta - hyper["lr"] * g / \
            jnp.sqrt(n - jnp.square(gavg) + eps)
        return w + delta, (n, gavg, delta)


@register
class AdaGrad(Optimizer):
    """AdaGrad (parity: optimizer/adagrad.py)."""

    def __init__(self, learning_rate=0.01, epsilon=1e-7, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        h = super()._hyper(index)
        h["eps"] = onp.float32(self.epsilon)
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        (h,) = state
        h = h + jnp.square(g)
        return w - hyper["lr"] * g / (jnp.sqrt(h) + hyper["eps"]), (h,)


adagrad = AdaGrad
_REGISTRY["adagrad"] = AdaGrad


@register
class GroupAdaGrad(Optimizer):
    """AdaGrad with one accumulator per ROW (embedding-friendly;
    parity: optimizer/contrib.py GroupAdaGrad). Weight decay is not
    supported, matching the reference's assertion."""

    def __init__(self, learning_rate=0.01, epsilon=1e-6, **kwargs):
        if kwargs.get("wd"):
            raise ValueError(
                "Weight decay is not supported for GroupAdaGrad")
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if weight._data.ndim != 2:
            raise ValueError("GroupAdaGrad requires 2D weights "
                             f"(got shape {tuple(weight.shape)})")
        return (jnp.zeros((weight.shape[0], 1), weight._data.dtype),)

    def _hyper(self, index):
        h = super()._hyper(index)
        h["eps"] = onp.float32(self.epsilon)
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper, wd_in_grad=False)
        (h,) = state
        h = h + jnp.mean(jnp.square(g), axis=1, keepdims=True)
        return w - hyper["lr"] * g / (jnp.sqrt(h) + hyper["eps"]), (h,)


@register
class AdaDelta(Optimizer):
    """AdaDelta (parity: optimizer/adadelta.py)."""

    def __init__(self, learning_rate=1.0, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        # distinct buffers — see RMSProp.create_state
        return (jnp.zeros_like(weight._data),
                jnp.zeros_like(weight._data))

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(rho=onp.float32(self.rho), eps=onp.float32(self.epsilon))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        acc_g, acc_d = state
        rho, eps = hyper["rho"], hyper["eps"]
        acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
        delta = jnp.sqrt(acc_d + eps) / jnp.sqrt(acc_g + eps) * g
        acc_d = rho * acc_d + (1 - rho) * jnp.square(delta)
        return w - hyper["lr"] * delta, (acc_g, acc_d)


@register
class Ftrl(Optimizer):
    """FTRL (parity: optimizer/ftrl.py)."""

    def __init__(self, learning_rate=0.1, lamda1=0.01, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        # distinct buffers — see RMSProp.create_state
        return (jnp.zeros_like(weight._data),
                jnp.zeros_like(weight._data))  # z, n

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(lamda1=onp.float32(self.lamda1), beta=onp.float32(self.beta))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper, wd_in_grad=False)
        z, n = state
        lr, l1, beta, wd = hyper["lr"], hyper["lamda1"], hyper["beta"], hyper["wd"]
        sigma = (jnp.sqrt(n + jnp.square(g)) - jnp.sqrt(n)) / lr
        z = z + g - sigma * w
        n = n + jnp.square(g)
        denom = (beta + jnp.sqrt(n)) / lr + wd
        new_w = jnp.where(jnp.abs(z) > l1,
                          -(z - jnp.sign(z) * l1) / denom,
                          jnp.zeros_like(w))
        return new_w, (z, n)


@register
class FTML(Optimizer):
    """FTML (parity: optimizer/ftml.py)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        # distinct buffers — see RMSProp.create_state
        return (jnp.zeros_like(weight._data),
                jnp.zeros_like(weight._data),
                jnp.zeros_like(weight._data))  # d, v, z

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(beta1=onp.float32(self.beta1), beta2=onp.float32(self.beta2),
                 eps=onp.float32(self.epsilon))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        d, v, z = state
        b1, b2, eps, t = hyper["beta1"], hyper["beta2"], hyper["eps"], \
            hyper["t"].astype(jnp.float32)
        v = b2 * v + (1 - b2) * jnp.square(g)
        d_t = (1 - jnp.power(b1, t)) / hyper["lr"] * \
            (jnp.sqrt(v / (1 - jnp.power(b2, t))) + eps)
        sigma = d_t - b1 * d
        z = b1 * z + (1 - b1) * g - sigma * w
        return -z / d_t, (d_t, v, z)


@register
class LAMB(Optimizer):
    """LAMB layerwise-adaptive large-batch optimizer
    (parity: optimizer/lamb.py; kernels src/operator/contrib/multi_lamb.cc)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data), jnp.zeros_like(weight._data))

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(beta1=onp.float32(self.beta1), beta2=onp.float32(self.beta2),
                 eps=onp.float32(self.epsilon),
                 lb=onp.float32(self.lower_bound if self.lower_bound is not None else 0.0),
                 ub=onp.float32(self.upper_bound if self.upper_bound is not None else 1e30),
                 bc=onp.float32(1.0 if self.bias_correction else 0.0))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper, wd_in_grad=False)
        m, v = state
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["eps"]
        t = hyper["t"].astype(jnp.float32)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        m_hat = jnp.where(hyper["bc"] > 0, m / (1 - jnp.power(b1, t)), m)
        v_hat = jnp.where(hyper["bc"] > 0, v / (1 - jnp.power(b2, t)), v)
        r = m_hat / (jnp.sqrt(v_hat) + eps) + hyper["wd"] * w
        w_norm = jnp.linalg.norm(w)
        r_norm = jnp.linalg.norm(r)
        w_norm_c = jnp.clip(w_norm, hyper["lb"], hyper["ub"])
        ratio = jnp.where((w_norm_c > 0) & (r_norm > 0), w_norm_c / r_norm, 1.0)
        return w - hyper["lr"] * ratio * r, (m, v)


@register
class LARS(Optimizer):
    """LARS (parity: optimizer/lars.py; multi_lars.cc)."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.eta, self.epsilon = momentum, eta, epsilon

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(mom=onp.float32(self.momentum), eta=onp.float32(self.eta),
                 eps=onp.float32(self.epsilon))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = g * hyper["rescale"]
        if hyper["clip"] is not None:
            g = jnp.clip(g, -hyper["clip"], hyper["clip"])
        (mom,) = state
        w_norm = jnp.linalg.norm(w)
        g_norm = jnp.linalg.norm(g)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            hyper["eta"] * w_norm / (g_norm + hyper["wd"] * w_norm + hyper["eps"]),
            1.0)
        lr_l = hyper["lr"] * trust
        mom = hyper["mom"] * mom + lr_l * (g + hyper["wd"] * w)
        return w - mom, (mom,)


@register
class LANS(LAMB):
    """LANS: LAMB with per-block gradient normalization + Nesterov
    (parity: optimizer/lans.py; multi_lans.cc)."""

    @staticmethod
    def _step(w, g, state, hyper):
        g = g * hyper["rescale"]
        if hyper["clip"] is not None:
            g = jnp.clip(g, -hyper["clip"], hyper["clip"])
        g_norm = jnp.linalg.norm(g)
        g = jnp.where(g_norm > 0, g / g_norm, g)
        m, v = state
        b1, b2, eps = hyper["beta1"], hyper["beta2"], hyper["eps"]
        t = hyper["t"].astype(jnp.float32)
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * jnp.square(g)
        m_hat = m / (1 - jnp.power(b1, t))
        v_hat = v / (1 - jnp.power(b2, t))
        w_norm = jnp.linalg.norm(w)
        r1 = m_hat / (jnp.sqrt(v_hat) + eps) + hyper["wd"] * w
        r2 = g / (jnp.sqrt(v_hat) + eps) + hyper["wd"] * w
        r1n, r2n = jnp.linalg.norm(r1), jnp.linalg.norm(r2)
        rat1 = jnp.where((w_norm > 0) & (r1n > 0), w_norm / r1n, 1.0)
        rat2 = jnp.where((w_norm > 0) & (r2n > 0), w_norm / r2n, 1.0)
        upd = b1 * rat1 * r1 + (1 - b1) * rat2 * r2
        return w - hyper["lr"] * upd, (m, v)


@register
class Signum(Optimizer):
    """SignSGD / Signum (parity: optimizer/signum.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return ()
        return (jnp.zeros_like(weight._data),)

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(mom=onp.float32(self.momentum), wd_lh=onp.float32(self.wd_lh))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        if not state:
            return w * (1 - hyper["lr"] * hyper["wd_lh"]) - \
                hyper["lr"] * jnp.sign(g), state
        (mom,) = state
        mom = hyper["mom"] * mom - (1 - hyper["mom"]) * g
        return w * (1 - hyper["lr"] * hyper["wd_lh"]) + \
            hyper["lr"] * jnp.sign(mom), (mom,)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity: optimizer/sgld.py)."""

    def update(self, index, weight, grad, state):
        if not isinstance(index, (list, tuple)):
            index, weight, grad, state = [index], [weight], [grad], [state]
        self._update_count(index)
        for i, w, g, s in zip(index, weight, grad, state):
            hyper = self._hyper(i)
            key = next_key()
            gg = Optimizer._pre(jnp.asarray(g._data, w._data.dtype),
                                w._data, hyper)
            noise = jnp.sqrt(hyper["lr"]) * \
                jax.random.normal(key, w.shape, jnp.float32).astype(w._data.dtype)
            w._install(w._data - hyper["lr"] / 2 * gg + noise)
            self._set_state(i, s, s)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (parity: optimizer/dcasgd.py)."""

    def __init__(self, learning_rate=0.01, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        return (jnp.zeros_like(weight._data), jnp.array(weight._data))

    def _hyper(self, index):
        h = super()._hyper(index)
        h.update(mom=onp.float32(self.momentum), lamda=onp.float32(self.lamda))
        return h

    @staticmethod
    def _step(w, g, state, hyper):
        g = Optimizer._pre(g, w, hyper)
        mom, prev_w = state
        comp = g + hyper["lamda"] * g * g * (w - prev_w)
        mom = hyper["mom"] * mom - hyper["lr"] * comp
        return w + mom, (mom, jnp.array(w))


# ---------------------------------------------------------------------------
# Updater: serializable update-on-kvstore helper (parity: optimizer.Updater)
# ---------------------------------------------------------------------------
class Updater:
    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices, grads, weights = [index], [grad], [weight]
        else:
            indices, grads, weights = index, grad, weight
        for i, w, g in zip(indices, weights, grads):
            if i not in self.states:
                self.states[i] = \
                    self.optimizer.create_state_multi_precision(i, w)
            self.optimizer.update_multi_precision([i], [w], [g],
                                                  [self.states[i]])
            self.states[i] = self.optimizer._last_states[i]

    def get_states(self, dump_optimizer=False):
        import pickle
        host_states = jax.tree_util.tree_map(
            lambda x: onp.asarray(x) if isinstance(x, jax.Array) else x,
            self.states)
        return pickle.dumps((host_states, self.optimizer)
                            if dump_optimizer else host_states)

    def set_states(self, states):
        import pickle
        obj = pickle.loads(states)
        if isinstance(obj, tuple) and len(obj) == 2 and \
                isinstance(obj[1], Optimizer):
            states, self.optimizer = obj
        else:
            states = obj
        states = {k: self.optimizer._migrate_state(v)
                  for k, v in states.items()} \
            if isinstance(states, dict) else states
        self.states = jax.tree_util.tree_map(
            lambda x: jnp.asarray(x) if isinstance(x, onp.ndarray) else x,
            states)


def get_updater(optimizer):
    return Updater(optimizer)

"""CheckpointManager — async sharded checkpointing with atomic commit.

The reference's recovery story is synchronous single-host Save/Load
(``ndarray.cc:1729,1852``) plus "checkpoint + relaunch"; at sharded-era
scale that means a full training-loop stall per save and a restart from
zero after preemption. This manager keeps the training thread out of
the write path:

1. ``save(step, tree)`` SNAPSHOTS the pytree on the caller thread —
   jax arrays are immutable, so the snapshot is a device-side copy
   dispatch (O(dispatch), not O(bytes-to-host)); the copy exists only
   because donated buffers (the fused optimizer states, TrainStep's
   donated update program) would otherwise be invalidated by the very
   next step while the writer still holds a reference.
2. A ``BoundedQueueWorker`` thread does the device→host reads and the
   per-shard file writes. The bounded queue (``max_pending``) is the
   backpressure: a training loop outrunning the disk blocks on the
   queue instead of buying unbounded host memory.
3. Commit is a MARKER FILE written last: a checkpoint directory
   without ``COMMITTED`` does not exist as far as restore is
   concerned, so a kill mid-save can never surface a torn checkpoint.
4. Retention GC keeps the last ``keep_last_n`` committed steps (plus
   any leftover uncommitted debris older than the newest commit).
5. ``restore()`` verifies every shard (length + crc32) against the
   manifest and falls back to the previous committed step on
   corruption — counted as ``checkpoint.restore.corrupt_fallbacks``.

Telemetry (docs/OBSERVABILITY.md): counters
``checkpoint.save.{bytes,retries,errors,corrupt_fallbacks→restore}``,
histograms ``checkpoint.{save,restore}.duration_ms``, gauge
``checkpoint.save.pending``.
"""
from __future__ import annotations

import atexit
import json
import threading
import time
import weakref
import zlib

import numpy as onp

from .. import telemetry
from .._bounded_worker import BoundedQueueWorker
from ._fs import LocalFS
from .manifest import decode_tree, encode_tree, resolve_dtype

__all__ = [
    "CheckpointError", "CheckpointCorruptError", "CheckpointWriteError",
    "CheckpointManager", "write_checkpoint", "read_checkpoint",
    "read_params", "is_committed", "snapshot_tree",
    "MARKER_FILE", "MANIFEST_FILE", "STEP_PREFIX",
]

MARKER_FILE = "COMMITTED"
MANIFEST_FILE = "manifest.json"
STEP_PREFIX = "step_"
_FORMAT = "mxnet_tpu.checkpoint/1"


class CheckpointError(RuntimeError):
    """Base class for checkpoint failures."""


class CheckpointWriteError(CheckpointError):
    """A shard/manifest write failed after exhausting retries."""


class CheckpointCorruptError(CheckpointError):
    """A committed-looking checkpoint failed integrity verification
    (missing/truncated shard, crc mismatch, unreadable manifest)."""


# ---------------------------------------------------------------------------
# snapshot
# ---------------------------------------------------------------------------

_jit_copy = None


def snapshot_tree(tree):
    """Donation-safe snapshot of a pytree, cheap on the caller thread.

    jax.Array leaves get a device-side copy: holding the ORIGINAL
    buffer is unsafe because the fused optimizer update and TrainStep
    donate their state buffers, which invalidates them one step later
    while the async writer still needs the bytes. All jax leaves are
    copied by ONE jitted identity program (one async dispatch per
    snapshot, not one eager op per leaf — per-leaf ``jnp.copy`` of a
    50-param model costs more host time than the training step it is
    supposed not to stall). numpy leaves are copied on host (they are
    tiny: RNG keys, iterator orders); scalars pass through."""
    global _jit_copy
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = [i for i, x in enumerate(leaves) if isinstance(x, jax.Array)]
    if idx:
        if _jit_copy is None:
            import jax.numpy as jnp
            _jit_copy = jax.jit(
                lambda xs: tuple(jnp.copy(x) for x in xs))
        copies = _jit_copy(tuple(leaves[i] for i in idx))
        for i, c in zip(idx, copies):
            leaves[i] = c
    leaves = [x.copy() if isinstance(x, onp.ndarray) else x
              for x in leaves]
    return jax.tree_util.tree_unflatten(treedef, leaves)


# ---------------------------------------------------------------------------
# single-directory write / read (the per-step format)
# ---------------------------------------------------------------------------

def _write_atomic(fs, path, data: bytes, max_retries: int,
                  backoff_s: float):
    """tmp-write + rename, with bounded retry-on-OSError (transient
    NFS/GCS-fuse hiccups). Retries are counted so an unhealthy
    filesystem is visible in telemetry long before it kills a run."""
    tmp = path + ".tmp"
    attempt = 0
    while True:
        try:
            fs.write_bytes(tmp, data)
            fs.replace(tmp, path)
            return
        except OSError as e:
            attempt += 1
            if attempt > max_retries:
                raise CheckpointWriteError(
                    f"writing {path} failed after {max_retries} "
                    f"retries: {e!r}") from e
            telemetry.counter("checkpoint.save.retries")
            time.sleep(backoff_s * (2 ** (attempt - 1)))


def write_checkpoint(directory, tree, metadata=None, fs=None,
                     max_retries: int = 3, backoff_s: float = 0.05):
    """Write one checkpoint into ``directory`` (shards + manifest +
    commit marker, in that order). Synchronous; the manager calls this
    from its worker thread, the ``parallel.save_sharded`` shim calls
    it directly. Returns total payload bytes."""
    fs = fs or LocalFS()
    import os
    directory = os.path.abspath(directory)
    fs.makedirs(directory)
    t0 = telemetry.clock()
    counter = [0]
    total = [0]

    def add_leaf(x):
        arr = onp.asarray(x)  # D2H happens HERE (writer thread)
        data = arr.tobytes()
        name = f"shard_{counter[0]:05d}.bin"
        counter[0] += 1
        _write_atomic(fs, os.path.join(directory, name), data,
                      max_retries, backoff_s)
        total[0] += len(data)
        return {"shard": name, "shape": list(arr.shape),
                "dtype": str(arr.dtype), "nbytes": len(data),
                "crc32": zlib.crc32(data)}

    skeleton = encode_tree(tree, add_leaf)
    manifest = {
        "format": _FORMAT,
        "tree": skeleton,
        "metadata": metadata or {},
        "nbytes": total[0],
        "n_shards": counter[0],
    }
    _write_atomic(fs, os.path.join(directory, MANIFEST_FILE),
                  json.dumps(manifest, indent=1).encode(),
                  max_retries, backoff_s)
    # the commit: restore trusts nothing without this marker
    _write_atomic(fs, os.path.join(directory, MARKER_FILE), b"ok",
                  max_retries, backoff_s)
    telemetry.counter("checkpoint.save.bytes", total[0])
    telemetry.hist_since("checkpoint.save.duration_ms", t0)
    return total[0]


def is_committed(directory, fs=None) -> bool:
    import os
    fs = fs or LocalFS()
    return fs.exists(os.path.join(directory, MARKER_FILE)) and \
        fs.exists(os.path.join(directory, MANIFEST_FILE))


def read_checkpoint(directory, fs=None, verify: bool = True):
    """Read one checkpoint directory -> ``(tree, metadata)`` with host
    numpy leaves. Raises :class:`CheckpointCorruptError` on any
    integrity failure (missing/truncated shard, crc mismatch,
    unreadable manifest)."""
    import os
    fs = fs or LocalFS()
    directory = os.path.abspath(directory)
    t0 = telemetry.clock()
    try:
        manifest = json.loads(
            fs.read_bytes(os.path.join(directory, MANIFEST_FILE)))
        skeleton, metadata = manifest["tree"], manifest.get("metadata", {})
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointCorruptError(
            f"unreadable manifest in {directory}: {e!r}") from e
    total = [0]

    def get_leaf(desc):
        path = os.path.join(directory, desc["shard"])
        try:
            data = fs.read_bytes(path)
        except OSError as e:
            raise CheckpointCorruptError(
                f"missing shard {desc['shard']} in {directory}: "
                f"{e!r}") from e
        if len(data) != desc["nbytes"]:
            raise CheckpointCorruptError(
                f"truncated shard {desc['shard']} in {directory}: "
                f"{len(data)} bytes, manifest says {desc['nbytes']}")
        if verify and zlib.crc32(data) != desc["crc32"]:
            raise CheckpointCorruptError(
                f"crc mismatch in shard {desc['shard']} of {directory}")
        total[0] += len(data)
        arr = onp.frombuffer(data, dtype=resolve_dtype(desc["dtype"]))
        return arr.reshape(desc["shape"]).copy()

    try:
        tree = decode_tree(skeleton, get_leaf)
    except CheckpointCorruptError:
        raise
    except Exception as e:  # noqa: BLE001 — any decode failure is
        # corruption from the caller's point of view
        raise CheckpointCorruptError(
            f"undecodable checkpoint in {directory}: {e!r}") from e
    telemetry.counter("checkpoint.restore.bytes", total[0])
    telemetry.hist_since("checkpoint.restore.duration_ms", t0)
    return tree, metadata


def read_params(path, fs=None):
    """Parameter mapping (``name -> host array``) plus metadata from
    ``path`` — either one checkpoint directory or a manager root (the
    latest committed step is chosen). The serving weight-rollover entry
    point (`GenerationEngine.load_weights`)."""
    import os
    fs = fs or LocalFS()
    path = os.path.abspath(path)
    if not fs.exists(os.path.join(path, MANIFEST_FILE)):
        steps = _committed_steps(path, fs)
        if not steps:
            raise CheckpointError(
                f"{path} holds no committed checkpoint (no "
                f"{MANIFEST_FILE} and no committed {STEP_PREFIX}* "
                f"subdirectory)")
        path = os.path.join(path, _step_dirname(steps[-1]))
    tree, metadata = read_checkpoint(path, fs)
    params = tree.get("params", tree) if isinstance(tree, dict) else tree
    if not isinstance(params, dict):
        raise CheckpointError(
            f"checkpoint at {path} does not contain a parameter "
            f"mapping")
    return params, metadata


# ---------------------------------------------------------------------------
# step-directory bookkeeping
# ---------------------------------------------------------------------------

def _step_dirname(step: int) -> str:
    return f"{STEP_PREFIX}{step:08d}"


def _parse_step(name: str):
    if not name.startswith(STEP_PREFIX):
        return None
    try:
        return int(name[len(STEP_PREFIX):])
    except ValueError:
        return None


def _committed_steps(root, fs):
    import os
    if not fs.isdir(root):
        return []
    steps = []
    for name in fs.listdir(root):
        s = _parse_step(name)
        if s is not None and is_committed(os.path.join(root, name), fs):
            steps.append(s)
    return sorted(steps)


# ---------------------------------------------------------------------------
# the manager
# ---------------------------------------------------------------------------

# Interpreter-exit flush: an async save queued moments before the
# process falls off the end of a script would be silently lost (the
# writer is a daemon thread — the interpreter does not join it). Every
# live manager registers here; one atexit hook flushes them all. A
# manager that was close()d or collected has already left the set.
_live_managers: "weakref.WeakSet" = weakref.WeakSet()


@atexit.register
def _flush_live_managers():
    for mgr in list(_live_managers):
        try:
            mgr.close(timeout=60.0)
        except Exception:  # noqa: BLE001 — exit path: never raise
            pass


class _SaveWorker(BoundedQueueWorker):
    """Writer thread: drains queued (step, snapshot) items through
    ``CheckpointManager._write_step``. Holds only a weakref to the
    manager so an abandoned manager can be collected; pending events
    are always set (never a hung ``wait()``)."""

    def __init__(self, manager: "CheckpointManager", depth: int):
        super().__init__(depth, name="CheckpointManager.saver")
        self._manager = weakref.ref(manager)
        self.start()

    def run(self):
        while True:
            item = self._get()
            if item is self._DONE:
                return
            step, snap, metadata, evt = item
            mgr = self._manager()
            if mgr is None:
                evt.set()
                return
            try:
                mgr._write_step(step, snap, metadata)
            except BaseException as e:  # noqa: BLE001 — surface via
                # wait()/close(); a failed save must not kill the thread
                mgr._set_error(e)
            finally:
                mgr._finish_pending(evt)
            del mgr

    def _drained(self, item):
        # hard-stop path: an un-written save is abandoned, but its
        # waiters are released (close() flushes gracefully first, so
        # this only fires on a timed-out close)
        if isinstance(item, tuple) and len(item) == 4:
            item[3].set()


class CheckpointManager:
    """Periodic training checkpoints under one root directory.

    Parameters
    ----------
    directory : str
        Root; each save lands in ``step_<N>/`` with an atomic
        ``COMMITTED`` marker.
    keep_last_n : int, optional
        Retention: committed steps beyond the newest N are deleted
        after each commit. ``None`` keeps everything.
    async_save : bool
        Write shards on a background worker thread (default). The
        caller-thread cost is then one device-side copy dispatch per
        leaf plus a queue put; ``False`` writes synchronously in
        ``save()``.
    max_pending : int
        Bound on queued-but-unwritten saves; a producer outrunning the
        disk blocks here (backpressure) instead of accumulating
        snapshots.
    max_retries / backoff_s
        Per-file write retry budget and initial exponential backoff.
    fs : optional
        Filesystem implementation (see ``_fs.LocalFS``) — the
        fault-injection seam.
    """

    def __init__(self, directory, keep_last_n=3, async_save: bool = True,
                 max_pending: int = 2, max_retries: int = 3,
                 backoff_s: float = 0.05, fs=None):
        import os
        if keep_last_n is not None and int(keep_last_n) < 1:
            raise ValueError("keep_last_n must be >= 1 or None")
        self.directory = os.path.abspath(directory)
        self.keep_last_n = None if keep_last_n is None else int(keep_last_n)
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self._fs = fs or LocalFS()
        self._fs.makedirs(self.directory)
        self._lock = threading.Lock()
        # serializes actual checkpoint writes + retention GC between
        # the async worker and the synchronous flush path (save_sync):
        # two writers target distinct step dirs, but GC's listdir/
        # rmtree sweep must not race a half-written sibling
        self._io_lock = threading.Lock()
        self._pending: list = []
        self._error = None
        self._closed = False
        self._worker = _SaveWorker(self, max(1, int(max_pending))) \
            if async_save else None
        _live_managers.add(self)

    # -- error/pending plumbing ----------------------------------------
    def _set_error(self, e):
        telemetry.counter("checkpoint.save.errors")
        with self._lock:
            self._error = e

    def _finish_pending(self, evt):
        with self._lock:
            try:
                self._pending.remove(evt)
            except ValueError:
                pass
            depth = len(self._pending)
        evt.set()
        telemetry.gauge("checkpoint.save.pending", depth)

    def _raise_pending_error(self):
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise err

    @property
    def pending(self) -> int:
        """Snapshots queued or being written right now."""
        with self._lock:
            return len(self._pending)

    # -- save ----------------------------------------------------------
    def save(self, step: int, tree, metadata=None, block: bool = False):
        """Checkpoint ``tree`` as ``step``. Returns once the snapshot
        is taken (async mode) or the checkpoint is committed
        (``block=True`` / sync mode). A failure of an earlier async
        save is raised here, on ``wait()``, or on ``close()`` —
        whichever comes first."""
        if self._closed:
            raise CheckpointError("save on a closed CheckpointManager")
        step = int(step)
        if step < 0:
            raise ValueError(f"step must be >= 0, got {step}")
        self._raise_pending_error()
        snap = snapshot_tree(tree)
        if self._worker is None:
            self._write_step(step, snap, metadata)
            return
        evt = threading.Event()
        with self._lock:
            self._pending.append(evt)
            depth = len(self._pending)
        telemetry.gauge("checkpoint.save.pending", depth)
        # blocking put = backpressure once max_pending saves are queued
        self._worker._queue.put((step, snap, metadata, evt))
        if block:
            evt.wait()
            self._raise_pending_error()

    def save_sync(self, step: int, tree, metadata=None):
        """Synchronous commit on the CALLER thread, bypassing the
        async queue — the flush-on-signal path. A SIGTERM handler that
        must persist the current step before the process dies cannot
        queue behind ``max_pending`` earlier saves; this writes (and
        commits) directly, serialized with the worker only around the
        actual file I/O. Returns once the ``COMMITTED`` marker is on
        disk."""
        if self._closed:
            raise CheckpointError("save_sync on a closed "
                                  "CheckpointManager")
        step = int(step)
        if step < 0:
            raise ValueError(f"step must be >= 0, got {step}")
        snap = snapshot_tree(tree)
        self._write_step(step, snap, metadata)

    def _write_step(self, step, snap, metadata):
        import os
        meta = dict(metadata or {})
        meta.setdefault("step", step)
        with self._io_lock:
            write_checkpoint(
                os.path.join(self.directory, _step_dirname(step)), snap,
                metadata=meta, fs=self._fs, max_retries=self.max_retries,
                backoff_s=self.backoff_s)
            self.gc()

    def wait(self, timeout=None):
        """Block until every queued save is committed (or failed);
        re-raises the first failure."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._lock:
                evts = list(self._pending)
            if not evts:
                break
            for evt in evts:
                rem = None if deadline is None \
                    else max(0.0, deadline - time.monotonic())
                if not evt.wait(rem):
                    raise TimeoutError(
                        f"checkpoint saves still pending after "
                        f"{timeout}s")
        self._raise_pending_error()

    # -- inspection / restore ------------------------------------------
    def all_steps(self):
        """Committed step numbers, ascending."""
        return _committed_steps(self.directory, self._fs)

    def latest_step(self):
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_dir(self, step: int) -> str:
        import os
        return os.path.join(self.directory, _step_dirname(int(step)))

    def read_metadata(self, step: int) -> dict:
        """Metadata of a committed step WITHOUT reading its shards —
        one small manifest read. The cheap way to inspect tags/epochs
        across many candidates (the estimator resume path) before
        paying a full verified restore for the chosen one."""
        import os
        step = int(step)
        try:
            manifest = json.loads(self._fs.read_bytes(
                os.path.join(self.step_dir(step), MANIFEST_FILE)))
        except (OSError, ValueError) as e:
            raise CheckpointCorruptError(
                f"unreadable manifest for step {step} under "
                f"{self.directory}: {e!r}") from e
        return manifest.get("metadata", {})

    def restore(self, step=None):
        """Load a committed checkpoint -> ``(step, tree, metadata)``.

        Default: the NEWEST committed step; if it fails verification
        (truncated/corrupt shards under the marker — e.g. bit rot or a
        torn copy), fall back to the previous committed step (counted
        as ``checkpoint.restore.corrupt_fallbacks``) until one reads
        clean. An explicit ``step`` is strict: corruption raises."""
        import warnings
        if step is not None:
            step = int(step)
            if step not in self.all_steps():
                raise CheckpointError(
                    f"step {step} has no committed checkpoint under "
                    f"{self.directory}")
            tree, metadata = read_checkpoint(self.step_dir(step),
                                             self._fs)
            return step, tree, metadata
        candidates = list(reversed(self.all_steps()))
        if not candidates:
            raise CheckpointError(
                f"no committed checkpoint under {self.directory}")
        last_exc = None
        for s in candidates:
            try:
                tree, metadata = read_checkpoint(self.step_dir(s),
                                                 self._fs)
                return s, tree, metadata
            except CheckpointCorruptError as e:
                telemetry.counter("checkpoint.restore.corrupt_fallbacks")
                warnings.warn(
                    f"checkpoint step {s} is corrupt "
                    f"({e}); falling back to the previous "
                    f"committed step")
                last_exc = e
        raise CheckpointError(
            f"every committed checkpoint under {self.directory} "
            f"failed verification") from last_exc

    # -- retention ------------------------------------------------------
    def gc(self):
        """Apply retention: drop committed steps beyond
        ``keep_last_n`` and uncommitted debris older than the newest
        commit (a crashed writer's leftovers)."""
        import os
        committed = self.all_steps()
        doomed = []
        if self.keep_last_n is not None and \
                len(committed) > self.keep_last_n:
            doomed += committed[:-self.keep_last_n]
        newest = committed[-1] if committed else None
        if newest is not None and self._fs.isdir(self.directory):
            for name in self._fs.listdir(self.directory):
                s = _parse_step(name)
                if s is not None and s < newest and s not in committed:
                    doomed.append(s)
        for s in doomed:
            self._fs.rmtree(os.path.join(self.directory,
                                         _step_dirname(s)))
        return doomed

    # -- lifecycle ------------------------------------------------------
    def close(self, timeout: float = 60.0):
        """Flush pending saves (graceful), then stop the worker. The
        first pending failure is raised after the worker is down."""
        if self._closed:
            return
        self._closed = True
        _live_managers.discard(self)
        if self._worker is not None:
            try:
                self.wait(timeout=timeout)
            finally:
                self._worker.stop(timeout=5.0)
        self._raise_pending_error()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            if not self._closed and self._worker is not None:
                self._worker.stop(timeout=1.0)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

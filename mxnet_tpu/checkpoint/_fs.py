"""Filesystem seam for the checkpoint subsystem.

Every byte the :class:`~mxnet_tpu.checkpoint.CheckpointManager` reads
or writes goes through one of these methods, so fault-injection tests
can wrap a :class:`LocalFS` in a flaky/killing mock (truncated shards,
transient write failures, a process death between two writes) without
patching ``os`` globally — and a future remote store (GCS fuse,
tensorstore) only has to implement this surface.
"""
from __future__ import annotations

import os
import shutil


class LocalFS:
    """POSIX-backed implementation. ``replace`` is the atomicity
    primitive: a rename within one directory is atomic on every
    filesystem we care about, so "write sidecar tmp, then replace"
    never exposes a torn file."""

    def makedirs(self, path: str):
        os.makedirs(path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)

    def listdir(self, path: str):
        return os.listdir(path)

    def getsize(self, path: str) -> int:
        return os.path.getsize(path)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def write_bytes(self, path: str, data: bytes):
        with open(path, "wb") as f:
            f.write(data)

    def replace(self, src: str, dst: str):
        os.replace(src, dst)

    def remove(self, path: str):
        os.remove(path)

    def rmtree(self, path: str):
        shutil.rmtree(path, ignore_errors=True)

"""Checkpoint manifest — a JSON skeleton of an arbitrary pytree.

The reference serialized whole NDArrays into one file
(``ndarray.cc:1729`` Save / ``:1852`` Load); a sharded checkpoint
instead stores one raw-bytes shard file per array leaf plus this
manifest describing how to reassemble the tree: container structure
(dict/tuple/list, with key types preserved), inline Python scalars,
and per-shard integrity data (byte length + crc32) used for
truncation/corruption detection at restore.

Array leaves are stored as ``tobytes()`` raw buffers rather than
``.npy`` so non-numpy dtypes (bfloat16, fp8 — ml_dtypes) round-trip
bit-exactly: the manifest records the logical dtype string and the
shard file is just the bytes.
"""
from __future__ import annotations

import numpy as onp

__all__ = ["encode_tree", "decode_tree", "resolve_dtype"]


def resolve_dtype(name: str):
    """dtype-string -> numpy dtype, including the ml_dtypes extras
    (``str(arr.dtype)`` of a bfloat16 array is ``'bfloat16'``, which
    plain ``onp.dtype`` rejects)."""
    try:
        return onp.dtype(name)
    except TypeError:
        import ml_dtypes
        return onp.dtype(getattr(ml_dtypes, name))


def _encode_key(k):
    if isinstance(k, bool) or not isinstance(k, (str, int)):
        raise TypeError(
            f"checkpoint tree dict keys must be str or int, got "
            f"{type(k).__name__}: {k!r}")
    return {"t": "int" if isinstance(k, int) else "str", "v": k}


def _decode_key(node):
    return int(node["v"]) if node["t"] == "int" else str(node["v"])


def encode_tree(obj, add_leaf):
    """Encode ``obj`` into a JSON-able node. ``add_leaf(array)`` is
    called for every array leaf and must return the shard descriptor
    dict (``{"shard", "shape", "dtype", "nbytes", "crc32"}``) — the
    caller owns writing the actual bytes."""
    if obj is None:
        return {"t": "none"}
    if isinstance(obj, (bool, int, float, str)):
        return {"t": "py", "v": obj}
    if isinstance(obj, (list, tuple)):
        return {"t": "tuple" if isinstance(obj, tuple) else "list",
                "v": [encode_tree(x, add_leaf) for x in obj]}
    if isinstance(obj, dict):
        return {"t": "dict",
                "v": [[_encode_key(k), encode_tree(v, add_leaf)]
                      for k, v in obj.items()]}
    # anything else is an array leaf (jax.Array, onp.ndarray, scalars)
    return {"t": "arr", **add_leaf(obj)}


def decode_tree(node, get_leaf):
    """Inverse of :func:`encode_tree`; ``get_leaf(descriptor)`` loads
    (and integrity-checks) one shard and returns the array."""
    t = node["t"]
    if t == "none":
        return None
    if t == "py":
        return node["v"]
    if t == "list":
        return [decode_tree(x, get_leaf) for x in node["v"]]
    if t == "tuple":
        return tuple(decode_tree(x, get_leaf) for x in node["v"])
    if t == "dict":
        return {_decode_key(k): decode_tree(v, get_leaf)
                for k, v in node["v"]}
    if t == "arr":
        return get_leaf(node)
    raise ValueError(f"unknown manifest node type {t!r}")

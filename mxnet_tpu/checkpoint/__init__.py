"""mxnet_tpu.checkpoint — the resilience subsystem.

Replaces the synchronous Orbax wrapper that used to live in
``parallel/checkpoint.py`` (kept there as a deprecation shim) with a
real checkpoint stack:

- :class:`CheckpointManager` — async per-shard save off the training
  thread (donation-safe snapshot + ``BoundedQueueWorker`` writer),
  atomic commit-via-marker, retention GC, retry-with-backoff, and
  corrupt/partial-checkpoint fallback on restore (manager.py).
- :func:`capture_training_state` / :func:`apply_training_state` —
  full resumable state for Trainer/Estimator/TrainStep: params,
  optimizer tensors AND counters, lr-scheduler position, AMP loss
  scale, data-iterator cursor, explicit RNG keys — a resumed run
  continues bit-identically (state.py).
- :func:`save_training_state` / :func:`restore_training_state` — the
  two-liner most callers want.
- :func:`read_params` — the fast parallel-restore entry point serving
  uses for zero-downtime weight rollover
  (``GenerationEngine.load_weights`` / ``InferenceEngine
  .load_weights``).

See docs/CHECKPOINT.md for the on-disk layout, atomicity and
retention rules, resume semantics, and the serving rollover story;
``bench.py --checkpoint`` (BENCH_r10.json) for the measured
async-vs-sync training-step stall.
"""
from __future__ import annotations

from .manager import (  # noqa: F401
    CheckpointCorruptError, CheckpointError, CheckpointManager,
    CheckpointWriteError, MANIFEST_FILE, MARKER_FILE, STEP_PREFIX,
    is_committed, read_checkpoint, read_params, snapshot_tree,
    write_checkpoint,
)
from .state import (  # noqa: F401
    apply_training_state, capture_training_state, swap_param_buffers,
)
from ._fs import LocalFS  # noqa: F401

__all__ = [
    "CheckpointManager", "CheckpointError", "CheckpointCorruptError",
    "CheckpointWriteError", "capture_training_state",
    "apply_training_state", "save_training_state",
    "restore_training_state", "swap_param_buffers", "read_params",
    "read_checkpoint",
    "write_checkpoint", "snapshot_tree", "is_committed", "LocalFS",
]


def save_training_state(target, step, net=None, trainer=None,
                        train_step=None, data_iter=None,
                        include_rng: bool = True, metadata=None,
                        block: bool = False, **manager_kwargs):
    """Capture + save in one call.

    ``target`` is a :class:`CheckpointManager` (reused across steps —
    the async fast path) or a directory string (a throwaway
    synchronous manager is created, committed, and closed). Returns
    the manager so periodic callers can keep it."""
    if isinstance(target, CheckpointManager):
        mgr, own = target, False
    else:
        manager_kwargs.setdefault("async_save", False)
        mgr, own = CheckpointManager(target, **manager_kwargs), True
    tree, meta = capture_training_state(
        net=net, trainer=trainer, train_step=train_step,
        data_iter=data_iter, include_rng=include_rng)
    if metadata:
        meta.update(metadata)
    mgr.save(step, tree, metadata=meta, block=block)
    if own:
        mgr.close()
    return mgr


def restore_training_state(target, net=None, trainer=None,
                           train_step=None, data_iter=None, step=None,
                           strict: bool = True, **manager_kwargs):
    """Restore the latest (or an explicit) committed step into live
    objects -> ``(step, metadata)``. ``target`` as in
    :func:`save_training_state`."""
    if isinstance(target, CheckpointManager):
        mgr = target
    else:
        manager_kwargs.setdefault("async_save", False)
        mgr = CheckpointManager(target, **manager_kwargs)
    step, tree, metadata = mgr.restore(step=step)
    apply_training_state(tree, metadata, net=net, trainer=trainer,
                         train_step=train_step, data_iter=data_iter,
                         strict=strict)
    return step, metadata

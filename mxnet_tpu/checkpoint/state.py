"""Full training-state capture — the resume side of the subsystem.

A checkpoint that only holds params + optimizer moments (the old
``parallel/checkpoint.py`` wrapper) resumes *approximately*: Adam's
bias correction restarts near t=1, warmup/decay schedulers rewind,
the data iterator replays the epoch head, dropout masks diverge. This
module captures everything a killed-and-resumed
``Trainer``/``Estimator``/``TrainStep`` run needs to continue
**bit-identically**:

- parameters (by name, sharding-preserving restore),
- optimizer state tensors (Trainer per-param states or TrainStep's
  fused ``_opt_states``),
- optimizer counters: ``num_update``, ``begin_num_update``,
  ``index_update_count`` (the Adam-t / scheduler clock),
- lr-scheduler position (scalar scheduler attributes — ``base_lr``
  mutations included),
- AMP dynamic-loss-scaler state (scale + unskipped-step window),
- the data-iterator cursor (any iterator exposing
  ``state_dict``/``load_state_dict`` — ``io.NDArrayIter`` does),
- the explicit global RNG key (``random_state.py``) so stochastic
  layers replay the exact mask stream.

``capture_training_state`` returns ``(tree, metadata)`` — array
leaves in the tree (sharded to disk), JSON scalars in the metadata
(folded into the manifest, replacing the old ``opt_counters.json``
sidecar which silently dropped lr-scheduler state).

RESHARD-ON-RESTORE (docs/SHARDING.md): checkpoints hold FULL
(unsharded) arrays — save gathers each global ``jax.Array`` host-side
and the manifest records the full-array shapes — and restore places
every leaf onto the LIVE buffer's ``NamedSharding``
(``_placed_like``). So a TP-/FSDP-sharded ``TrainStep`` resumes
bit-identically onto the same layout, and a checkpoint written under
one layout/mesh shape restores cleanly onto another (the fsdp-on-(8,)
→ tp-on-(2,4) round trip is pinned by tests/test_partition.py):
params land on the new layout at apply time, and optimizer-state
leaves restored before the step is built are re-placed onto the
resolved state shardings by the next ``TrainStep._build``.
"""
from __future__ import annotations

import numpy as onp

from .. import random_state

__all__ = ["capture_training_state", "apply_training_state",
           "swap_param_buffers"]


# ---------------------------------------------------------------------------
# capture
# ---------------------------------------------------------------------------

def _json_scalar(v) -> bool:
    return isinstance(v, (bool, int, float, str)) or (
        isinstance(v, (list, tuple))
        and all(isinstance(x, (bool, int, float, str)) for x in v))


def _scheduler_meta(sched):
    return {
        "class": type(sched).__name__,
        "state": {k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in vars(sched).items() if _json_scalar(v)},
    }


def _optimizer_meta(opt):
    meta = {
        "class": type(opt).__name__,
        "num_update": int(opt.num_update),
        "begin_num_update": int(opt.begin_num_update),
        "index_update_count": {str(k): int(v) for k, v
                               in opt._index_update_count.items()},
        "lr": float(opt.lr),
    }
    if opt.lr_scheduler is not None:
        meta["lr_scheduler"] = _scheduler_meta(opt.lr_scheduler)
    return meta


def capture_training_state(net=None, trainer=None, train_step=None,
                           data_iter=None, include_rng: bool = True):
    """Snapshot-ready ``(tree, metadata)`` for any combination of a
    Gluon ``net``, an imperative ``Trainer``, a compiled
    ``parallel.TrainStep``, and a resumable data iterator. Pass the
    result straight to ``CheckpointManager.save`` (which makes the
    donation-safe device copies)."""
    tree: dict = {}
    meta: dict = {"format": "mxnet_tpu.checkpoint/1"}

    if net is not None:
        tree["params"] = {name: p.data()._data
                          for name, p in net.collect_params().items()}

    if trainer is not None:
        states = {}
        for i, s in enumerate(trainer._states):
            if trainer._states_initialized[i]:
                states[str(i)] = s
        tree["trainer_states"] = states
        meta["optimizer"] = _optimizer_meta(trainer._optimizer)
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler is not None:
            meta["amp_scaler"] = {
                k: v for k, v in vars(scaler).items()
                if isinstance(v, (bool, int, float))}

    if train_step is not None:
        if getattr(train_step, "_opt_states", None) is not None:
            tree["opt_states"] = tuple(train_step._opt_states)
        meta["optimizer"] = _optimizer_meta(train_step.optimizer)

    if data_iter is not None:
        state_fn = getattr(data_iter, "state_dict", None)
        if state_fn is None:
            raise TypeError(
                f"data_iter {type(data_iter).__name__} is not "
                "resumable: it does not expose state_dict()/"
                "load_state_dict() (io.NDArrayIter does)")
        tree["data_iter"] = state_fn()

    if include_rng:
        key, counter = random_state.get_state()
        if key is not None:
            tree["rng"] = {"key": key}
            meta["rng_counter"] = int(counter)
        # numpy's GLOBAL generator too: NDArrayIter.reset() shuffles
        # with it, so without this a multi-epoch shuffled resume
        # diverges at the first epoch boundary after the checkpoint
        # (the mid-epoch order travels in the iterator cursor, but the
        # NEXT epoch's shuffle comes from ambient numpy state)
        name, keys, pos, has_gauss, cached = onp.random.get_state()
        tree["numpy_rng"] = (name, keys, int(pos), int(has_gauss),
                             float(cached))
    return tree, meta


# ---------------------------------------------------------------------------
# apply
# ---------------------------------------------------------------------------

def _placed_like(arr, like):
    """Host array -> device array, on the placement (sharding) of the
    live array it replaces, with the live dtype kept (a checkpoint
    restored into a recast net follows the net)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    if isinstance(like, jax.Array):
        out = jnp.asarray(arr, like.dtype)
        sh = getattr(like, "sharding", None)
        if isinstance(sh, NamedSharding):
            out = jax.device_put(out, sh)
        return out
    return jnp.asarray(arr)


def _to_device(tree, like=None):
    """Map host leaves onto devices, leaf-aligned with ``like`` when
    given (sharding/dtype preservation)."""
    import jax

    if like is not None:
        try:
            return jax.tree_util.tree_map(
                lambda x, l: _placed_like(x, l)
                if isinstance(x, onp.ndarray) else x, tree, like)
        except ValueError:
            pass  # layout changed (optimizer migration): place fresh
    return jax.tree_util.tree_map(
        lambda x: _placed_like(x, None)
        if isinstance(x, onp.ndarray) else x, tree)


def _apply_params(net, saved, strict):
    params = net.collect_params()
    missing = [n for n in saved if n not in params]
    if missing and strict:
        raise KeyError(
            f"checkpoint holds parameters absent from the net: "
            f"{missing[:5]}{'...' if len(missing) > 5 else ''}")
    for name, arr in saved.items():
        p = params.get(name)
        if p is None:
            continue
        if p._data is None:
            # a FRESH net with deferred shape inference (no in_units,
            # no forward pass yet — exactly the resume-after-preemption
            # case): the checkpoint shape finishes the init, the same
            # way Block.load_parameters does via set_data
            from ..numpy import array
            p.set_data(array(onp.asarray(arr)))
            continue
        live = p.data()._data
        if tuple(live.shape) != tuple(arr.shape):
            raise ValueError(
                f"shape mismatch restoring {name}: net has "
                f"{tuple(live.shape)}, checkpoint has "
                f"{tuple(arr.shape)}")
        p.data()._install(_placed_like(arr, live))


def _apply_optimizer_meta(opt, meta):
    if not meta:
        return
    opt.num_update = int(meta["num_update"])
    opt.begin_num_update = int(meta["begin_num_update"])
    opt._index_update_count = {
        int(k): int(v)
        for k, v in meta.get("index_update_count", {}).items()}
    if "lr" in meta:
        opt.lr = float(meta["lr"])
    sched_meta = meta.get("lr_scheduler")
    if sched_meta and opt.lr_scheduler is not None:
        for k, v in sched_meta.get("state", {}).items():
            if hasattr(opt.lr_scheduler, k):
                setattr(opt.lr_scheduler, k, v)


def swap_param_buffers(params, new_params, strict: bool = True):
    """The serving weight-rollover core: install new buffers into live
    ``Parameter``s without touching shapes, dtypes, placement, or any
    cached jitted closure.

    Validates EVERYTHING first (name coverage under ``strict``, shape
    match per parameter) and only then installs — a bad checkpoint can
    never leave a model half-swapped. Same-shape/dtype buffers mean
    the compiled programs that take parameters as runtime arguments
    (CachedOp entries, the GPT generation closures) keep their traces;
    sharded parameters keep their placement via ``device_put`` onto
    the old buffer's sharding. Returns the number of parameters
    swapped."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    missing = [n for n in params if n not in new_params]
    unexpected = [n for n in new_params if n not in params]
    if strict and (missing or unexpected):
        raise ValueError(
            f"checkpoint does not match the model: "
            f"missing={missing[:4]} unexpected={unexpected[:4]}")
    plan = []
    for name, p in params.items():
        if name not in new_params:
            continue
        live = p.data()._data
        arr = new_params[name]
        if tuple(live.shape) != tuple(arr.shape):
            raise ValueError(
                f"shape mismatch for {name}: model "
                f"{tuple(live.shape)}, checkpoint {tuple(arr.shape)}")
        plan.append((p, live, arr))
    for p, live, arr in plan:
        new = jnp.asarray(arr, live.dtype)
        sh = getattr(live, "sharding", None)
        if isinstance(sh, NamedSharding):
            new = jax.device_put(new, sh)
        p.data()._install(new)
    return len(plan)


def apply_training_state(tree, metadata=None, net=None, trainer=None,
                         train_step=None, data_iter=None,
                         strict: bool = True):
    """Restore a ``capture_training_state`` snapshot (as returned by
    ``CheckpointManager.restore``: host numpy leaves) into live
    objects. Only the pieces present in BOTH the checkpoint and the
    arguments are touched."""
    metadata = metadata or {}

    if net is not None and "params" in tree:
        _apply_params(net, tree["params"], strict)

    if trainer is not None:
        saved = tree.get("trainer_states")
        if saved is not None:
            for k, s in saved.items():
                i = int(k)
                if i >= len(trainer._states):
                    if strict:
                        raise KeyError(
                            f"checkpoint state index {i} out of range "
                            f"for a trainer with "
                            f"{len(trainer._states)} parameters")
                    continue
                like = trainer._states[i] \
                    if trainer._states_initialized[i] else None
                trainer._states[i] = _to_device(s, like)
                trainer._states_initialized[i] = True
        _apply_optimizer_meta(trainer._optimizer,
                              metadata.get("optimizer"))
        scaler_meta = metadata.get("amp_scaler")
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        if scaler_meta and scaler is not None:
            for k, v in scaler_meta.items():
                if hasattr(scaler, k):
                    setattr(scaler, k, v)

    if train_step is not None:
        saved = tree.get("opt_states")
        if saved is not None:
            live = getattr(train_step, "_opt_states", None)
            restored = []
            for i, s in enumerate(saved):
                l = live[i] if live is not None and i < len(live) \
                    else None
                restored.append(_to_device(s, l))
            train_step._opt_states = restored
        _apply_optimizer_meta(train_step.optimizer,
                              metadata.get("optimizer"))

    if data_iter is not None and "data_iter" in tree:
        load_fn = getattr(data_iter, "load_state_dict", None)
        if load_fn is None:
            raise TypeError(
                f"data_iter {type(data_iter).__name__} does not "
                "expose load_state_dict()")
        load_fn(tree["data_iter"])

    if "rng" in tree:
        random_state.set_state(tree["rng"]["key"],
                               metadata.get("rng_counter", 0))

    if "numpy_rng" in tree:
        name, keys, pos, has_gauss, cached = tree["numpy_rng"]
        onp.random.set_state((name, onp.asarray(keys, onp.uint32),
                              int(pos), int(has_gauss), float(cached)))

"""Execution engine shim.

The reference implements a 2.6k-LoC threaded dependency engine
(src/engine/threaded_engine.h: ThreadedVar read/write queues, per-device
worker pools, exception capture on vars). On TPU, that machinery is
provided by the runtime itself:

- **Async dispatch**: JAX enqueues every op on the device stream and
  returns immediately; a jax.Array is a future. That is exactly the
  reference's "push returns, NDArray var not ready" contract
  (engine.h:204 PushAsync).
- **Dependency ordering**: data dependencies are carried by the arrays
  themselves; PJRT orders execution on the stream. Read/write hazards
  cannot arise because arrays are immutable — an in-place NDArray update
  installs a *new* buffer (see ndarray.py), which is the functional
  equivalent of the reference's var-version bump.
- **Exception propagation**: device-side errors surface when a buffer is
  awaited, matching the reference's var-attached exceptions re-thrown at
  WaitForVar/WaitForAll (threaded_engine.h:64,189,270).

What remains for us is the *control surface*: waitall / wait_to_read,
a synchronous debug mode (parity: MXNET_ENGINE_TYPE=NaiveEngine,
src/engine/engine.cc:32-58), and a bulk/fusion hint scope. Env var
``MXTPU_ENGINE_TYPE=NaiveEngine`` (or ``NaiveEngine`` in set_engine_type)
makes every op block on completion, giving deterministic, debuggable
stepping like the reference's NaiveEngine (src/engine/naive_engine.cc).
"""
from __future__ import annotations

import os
import threading
import weakref

import jax

from . import telemetry

# Live-array registry so waitall() can block on everything in flight.
# jax arrays are weakref-able but not hashable, so key weakrefs by id;
# the weakref callback drops entries as arrays are garbage collected.
_live_arrays: dict = {}
_live_lock = threading.Lock()
# per-op in-flight peak, kept as a plain int box: track() is the
# hottest path in the framework, so it must not take the telemetry
# registry lock — sample_memory() publishes this to the registry
_live_peak = [0]

_engine_type = os.environ.get("MXTPU_ENGINE_TYPE", os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice"))


def set_engine_type(name: str):
    """'NaiveEngine' -> synchronous execution; anything else -> async."""
    global _engine_type
    _engine_type = name


def engine_type() -> str:
    return _engine_type


def is_naive() -> bool:
    return _engine_type == "NaiveEngine"


def track(data):
    """Register a raw jax value for waitall(); returns the value.

    In naive (synchronous) mode, blocks until the value is ready so
    errors surface at the faulting op — the debug contract of the
    reference's NaiveEngine.
    """
    if is_naive():
        return jax.block_until_ready(data)
    if isinstance(data, jax.Array) and not isinstance(data, jax.core.Tracer):
        key = id(data)

        def _drop(_ref, _key=key):
            _live_arrays.pop(_key, None)

        with _live_lock:
            _live_arrays[key] = weakref.ref(data, _drop)
            n = len(_live_arrays)
            if n > _live_peak[0]:  # inside the lock: a stale compare
                _live_peak[0] = n  # outside could regress the peak
    return data


def sample_memory():
    """Record device-memory / in-flight-buffer watermarks into
    telemetry (parity: the reference's storage profiler attributing
    GPU pool bytes). Prefers PJRT's per-device ``memory_stats()``
    (real HBM bytes_in_use); backends without it (CPU) fall back to
    the bytes held by arrays the engine is tracking. Cheap enough for
    once-per-step sampling, not for per-op paths."""
    if not telemetry.enabled():
        return
    dev_bytes = 0
    try:
        for d in jax.local_devices():
            ms = d.memory_stats()
            if ms:
                dev_bytes += ms.get("bytes_in_use", 0)
    except Exception:  # noqa: BLE001 — backend without memory stats
        dev_bytes = 0
    with _live_lock:
        live = [r() for r in _live_arrays.values()]
    live_bytes = sum(getattr(a, "nbytes", 0) for a in live
                     if a is not None)
    n_live = sum(1 for a in live if a is not None)
    telemetry.gauge("engine.live_arrays", n_live, peak=_live_peak[0])
    telemetry.gauge("engine.live_bytes", live_bytes)
    if dev_bytes:
        telemetry.gauge("engine.device_mem_bytes", dev_bytes)


def waitall():
    """Block until all pushed work has finished (parity: mx.nd.waitall).

    Re-raises the first deferred device error, like the reference's
    WaitForAll → Throw path; any FURTHER deferred errors are logged at
    WARNING (they used to be silently discarded) and counted in
    telemetry as ``engine.suppressed_errors``.
    """
    sample_memory()
    with _live_lock:
        arrays = [r() for r in _live_arrays.values()]
        _live_arrays.clear()
    # the drain empties the registry: zero BOTH current values so the
    # gauges stay consistent with each other (peaks stay monotone)
    telemetry.gauge("engine.live_arrays", 0)
    telemetry.gauge("engine.live_bytes", 0)
    errs = []
    for a in arrays:
        if a is None:
            continue
        try:
            jax.block_until_ready(a)
        except Exception as e:  # keep draining; report the first error
            errs.append(e)
    if errs:
        if len(errs) > 1:
            from . import log
            logger = log.get_logger("mxnet_tpu.engine")
            telemetry.counter("engine.suppressed_errors",
                              len(errs) - 1)
            for e in errs[1:]:
                logger.warning(
                    "waitall: suppressed additional deferred device "
                    "error (%s): %s", type(e).__name__, e)
        raise errs[0]


def wait_to_read(data):
    """Block until one value is ready; re-raise its deferred error."""
    return jax.block_until_ready(data)


class bulk:
    """Hint scope for op bulking (parity: Engine bulk API, engine.h:310).

    The reference batches engine pushes to cut scheduling overhead.
    Here the real bulk-execution surfaces are (a) ``hybridize()`` —
    the whole model becomes one XLA program — and (b)
    ``parallel.TrainStep.run_chain`` — N optimizer steps scanned into
    one XLA program. Eager op-by-op dispatch is already async and
    cheap, so this scope itself is a compatibility no-op; use the two
    mechanisms above where the reference used bulking.
    """

    _warned = False

    def __init__(self, size: int = 0):
        self.size = size

    def __enter__(self):
        # an eager loop wrapped in bulk() gets nothing here — say so
        # once instead of silently doing nothing (round-3 VERDICT
        # Weak #8)
        if not bulk._warned:
            bulk._warned = True
            import warnings
            warnings.warn(
                "mx.engine.bulk is a compatibility no-op: eager "
                "dispatch is already async. For real bulking, "
                "hybridize() the model (one XLA program) or use "
                "parallel.TrainStep.run_chain (N steps per program).",
                stacklevel=2)
        return self

    def __exit__(self, *exc):
        return False

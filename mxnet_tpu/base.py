"""Base utilities for mxnet_tpu.

TPU-native re-imagining of the reference's base layer
(python/mxnet/base.py in szha/mxnet). There is no C handle table or
ctypes `check_call` here: the compute substrate is JAX/XLA, so "handles"
are jax.Array objects and errors are ordinary Python exceptions raised
either at dispatch time (shape/dtype errors) or at synchronization
points (device-side errors) — see engine.py for the async-error story.
"""
from __future__ import annotations

import numpy as onp

__version__ = "0.1.0"


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


_FLOAT_DTYPES = (onp.float16, onp.float32, onp.float64)

# dtype aliases accepted everywhere a dtype can be passed.
_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
}


def resolve_dtype(dtype):
    """Normalize a user-provided dtype to a numpy dtype object.

    Accepts numpy dtypes, python types, strings, and ml_dtypes names
    (e.g. 'bfloat16' resolves through jax.numpy).
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
        if dtype == "bfloat16":
            import jax.numpy as jnp

            return onp.dtype(jnp.bfloat16)
    try:
        return onp.dtype(dtype)
    except TypeError:
        # jax dtypes like jnp.bfloat16 class
        return onp.dtype(getattr(dtype, "dtype", dtype))


def is_np_shape():
    """NumPy-shape semantics are always on in this framework.

    The reference has a global toggle (mxnet.util.set_np_shape) because its
    legacy mx.nd API used 0 to mean "unknown dim". This framework is
    NumPy-semantics from day one; the toggle exists for API parity only.
    """
    return True


def is_np_array():
    return True


def set_np(shape=True, array=True, dtype=False):  # noqa: ARG001 - parity signature
    """Parity shim: numpy semantics are always active."""
    return None


def reset_np():
    return None

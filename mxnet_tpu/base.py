"""Base utilities for mxnet_tpu.

TPU-native re-imagining of the reference's base layer
(python/mxnet/base.py in szha/mxnet). There is no C handle table or
ctypes `check_call` here: the compute substrate is JAX/XLA, so "handles"
are jax.Array objects and errors are ordinary Python exceptions raised
either at dispatch time (shape/dtype errors) or at synchronization
points (device-side errors) — see engine.py for the async-error story.
"""
from __future__ import annotations

import numpy as onp

__version__ = "0.1.0"


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


_FLOAT_DTYPES = (onp.float16, onp.float32, onp.float64)

# dtype aliases accepted everywhere a dtype can be passed.
_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
}


def resolve_dtype(dtype, values=None):
    """Normalize a user-provided dtype to a numpy dtype object.

    Accepts numpy dtypes, python types, strings, and ml_dtypes names
    (e.g. 'bfloat16' resolves through jax.numpy). Every dtype request
    funnels through here, so the 64-bit narrowing policy below applies
    uniformly (creation ops, astype, array); pass `values` when host
    data is at hand to get the integer bounds check.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
        if dtype == "bfloat16":
            import jax.numpy as jnp

            return onp.dtype(jnp.bfloat16)
    try:
        dt = onp.dtype(dtype)
    except TypeError:
        # jax dtypes like jnp.bfloat16 class
        dt = onp.dtype(getattr(dtype, "dtype", dtype))
    return narrow_dtype(values, dt)


# 64-bit dtype policy (reference: src/libinfo.cc INT64_TENSOR_SIZE):
# under the default x64-off jax backend, 64-bit arrays narrow to
# 32-bit BY DESIGN — integers with an overflow check, floats silently
# (float64 inputs are almost always numpy's default-dtype accidents,
# and the reference's compute dtype is float32 anyway). Enabling jax
# x64 mode keeps true 64-bit arrays end to end.
_NARROW64 = {"int64": "int32", "uint64": "uint32", "float64": "float32"}


def narrow_dtype(values, dtype):
    """Apply the 64-bit narrowing policy to (host values, dtype).

    Returns the dtype actually used on device. Raises OverflowError —
    rather than letting jax warn-and-wrap — when integer values do not
    fit in 32 bits.
    """
    if dtype is None:
        return dtype
    dtype = onp.dtype(dtype)
    target = _NARROW64.get(dtype.name)
    if target is None:
        return dtype
    import jax

    if jax.config.jax_enable_x64:
        return dtype
    if dtype.kind in "iu" and values is not None:
        arr = onp.asarray(values)
        # float host data feeding an integer dtype must bounds-check
        # too (e.g. array([1e12], dtype='int64'))
        if arr.size and arr.dtype.kind in "iuf":
            info = onp.iinfo(target)
            bad_nan = arr.dtype.kind == "f" and \
                bool(onp.isnan(arr).any())
            if bad_nan or arr.max(initial=0) > info.max or \
                    arr.min(initial=0) < info.min:
                raise OverflowError(
                    f"{dtype.name} value out of {target} range under the "
                    "default 32-bit index policy; enable jax x64 mode "
                    "(jax.config.update('jax_enable_x64', True)) for "
                    "true 64-bit arrays")
    return onp.dtype(target)


def is_np_shape():
    """NumPy-shape semantics are always on in this framework.

    The reference has a global toggle (mxnet.util.set_np_shape) because its
    legacy mx.nd API used 0 to mean "unknown dim". This framework is
    NumPy-semantics from day one; the toggle exists for API parity only.
    """
    return True


def is_np_array():
    return True


# --- np-default-dtype mode (reference: mxnet.util.set_np(dtype=True) /
# use_np_default_dtype, tests/python/unittest/test_numpy_default_dtype.py):
# default-dtype ops (array/ones/zeros/linspace/random.* ...) produce
# float64 instead of the deep-numpy float32 default. float64 only
# survives on device under jax x64, so the toggle flips that too and
# restores the prior x64 state on exit.
_np_default_dtype_state = {"on": False, "prev_x64": None}


def default_float():
    """The current default float dtype for creation/random ops."""
    return onp.float64 if _np_default_dtype_state["on"] else onp.float32


def is_np_default_dtype():
    return _np_default_dtype_state["on"]


def _set_np_default_dtype(on):
    import jax

    st = _np_default_dtype_state
    if on and not st["on"]:
        st["prev_x64"] = bool(jax.config.jax_enable_x64)
        jax.config.update("jax_enable_x64", True)
        st["on"] = True
    elif not on and st["on"]:
        if not st["prev_x64"]:
            jax.config.update("jax_enable_x64", False)
        st["on"] = False


def set_np(shape=True, array=True, dtype=False):  # noqa: ARG001 - shape/array always on
    """NumPy shape/array semantics are always active in this framework
    (the reference toggles exist for its legacy mx.nd API). The dtype
    flag is REAL: set_np(dtype=True) switches the default float dtype
    to float64, classic-NumPy style."""
    _set_np_default_dtype(bool(dtype))


def reset_np():
    _set_np_default_dtype(False)


def legacy_reshape_shape(in_shape, shape, reverse=False):
    """Decode the reference Reshape op's special codes into a concrete
    output shape (parity: src/operator/tensor/matrix_op-inl.h
    InferReshapeShape; docs src/operator/tensor/matrix_op.cc:146-184).

    Codes: 0 copies the positionally matching input dim; -1 infers one
    dim from the remaining size; -2 copies all remaining input dims;
    -3 merges two consecutive input dims; -4 d1 d2 splits one input dim
    (d1 or d2 may be -1). With ``reverse=True`` codes are matched from
    the right.
    """
    in_shape = tuple(int(d) for d in in_shape)
    tgt = [int(s) for s in shape]
    if reverse:
        if -4 in tgt:
            raise ValueError("legacy reshape: reverse=True with a -4 "
                             "split code is not supported")
        out = legacy_reshape_shape(in_shape[::-1], tgt[::-1])
        return tuple(out)[::-1]

    total = 1
    for d in in_shape:
        total *= d
    out = []
    i_in = 0
    infer_at = None
    i = 0
    while i < len(tgt):
        s = tgt[i]
        if s > 0:
            out.append(s)
            i_in += 1
        elif s == 0:
            if i_in >= len(in_shape):
                raise ValueError(f"legacy reshape: 0 at position {i} "
                                 f"has no matching input dim "
                                 f"(input {in_shape})")
            out.append(in_shape[i_in])
            i_in += 1
        elif s == -1:
            if infer_at is not None:
                raise ValueError("legacy reshape: at most one -1")
            infer_at = len(out)
            out.append(-1)
            i_in += 1
        elif s == -2:
            out.extend(in_shape[i_in:])
            i_in = len(in_shape)
        elif s == -3:
            if i_in + 1 >= len(in_shape):
                raise ValueError("legacy reshape: -3 needs two "
                                 f"consecutive input dims (input "
                                 f"{in_shape}, at input pos {i_in})")
            out.append(in_shape[i_in] * in_shape[i_in + 1])
            i_in += 2
        elif s == -4:
            if i + 2 >= len(tgt):
                raise ValueError("legacy reshape: -4 must be followed "
                                 "by two split dims")
            if i_in >= len(in_shape):
                raise ValueError("legacy reshape: -4 has no input dim "
                                 "left to split")
            d = in_shape[i_in]
            d1, d2 = tgt[i + 1], tgt[i + 2]
            if d1 == -1 and d2 == -1:
                raise ValueError("legacy reshape: -4 split can infer "
                                 "at most one side")
            if d1 == -1:
                d1 = d // d2
            if d2 == -1:
                d2 = d // d1
            if d1 * d2 != d:
                raise ValueError(f"legacy reshape: -4 split {d1}x{d2} "
                                 f"!= input dim {d}")
            out.extend([d1, d2])
            i_in += 1
            i += 2
        else:
            raise ValueError(f"legacy reshape: bad code {s}")
        i += 1
    if infer_at is not None:
        known = 1
        for d in out:
            if d != -1:
                known *= d
        if known == 0 or total % known:
            raise ValueError(f"legacy reshape: cannot infer -1 "
                             f"({in_shape} -> {tuple(tgt)})")
        out[infer_at] = total // known
    return tuple(out)

"""Base utilities for mxnet_tpu.

TPU-native re-imagining of the reference's base layer
(python/mxnet/base.py in szha/mxnet). There is no C handle table or
ctypes `check_call` here: the compute substrate is JAX/XLA, so "handles"
are jax.Array objects and errors are ordinary Python exceptions raised
either at dispatch time (shape/dtype errors) or at synchronization
points (device-side errors) — see engine.py for the async-error story.
"""
from __future__ import annotations

import numpy as onp

__version__ = "0.1.0"


class MXNetError(RuntimeError):
    """Error raised by the framework (parity with mxnet.base.MXNetError)."""


_FLOAT_DTYPES = (onp.float16, onp.float32, onp.float64)

# dtype aliases accepted everywhere a dtype can be passed.
_DTYPE_ALIASES = {
    "float": "float32",
    "double": "float64",
    "half": "float16",
    "int": "int32",
    "long": "int64",
    "bool_": "bool",
}


def resolve_dtype(dtype, values=None):
    """Normalize a user-provided dtype to a numpy dtype object.

    Accepts numpy dtypes, python types, strings, and ml_dtypes names
    (e.g. 'bfloat16' resolves through jax.numpy). Every dtype request
    funnels through here, so the 64-bit narrowing policy below applies
    uniformly (creation ops, astype, array); pass `values` when host
    data is at hand to get the integer bounds check.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        dtype = _DTYPE_ALIASES.get(dtype, dtype)
        if dtype == "bfloat16":
            import jax.numpy as jnp

            return onp.dtype(jnp.bfloat16)
    try:
        dt = onp.dtype(dtype)
    except TypeError:
        # jax dtypes like jnp.bfloat16 class
        dt = onp.dtype(getattr(dtype, "dtype", dtype))
    return narrow_dtype(values, dt)


# 64-bit dtype policy (reference: src/libinfo.cc INT64_TENSOR_SIZE):
# under the default x64-off jax backend, 64-bit arrays narrow to
# 32-bit BY DESIGN — integers with an overflow check, floats silently
# (float64 inputs are almost always numpy's default-dtype accidents,
# and the reference's compute dtype is float32 anyway). Enabling jax
# x64 mode keeps true 64-bit arrays end to end.
_NARROW64 = {"int64": "int32", "uint64": "uint32", "float64": "float32"}


def narrow_dtype(values, dtype):
    """Apply the 64-bit narrowing policy to (host values, dtype).

    Returns the dtype actually used on device. Raises OverflowError —
    rather than letting jax warn-and-wrap — when integer values do not
    fit in 32 bits.
    """
    if dtype is None:
        return dtype
    dtype = onp.dtype(dtype)
    target = _NARROW64.get(dtype.name)
    if target is None:
        return dtype
    import jax

    if jax.config.jax_enable_x64:
        return dtype
    if dtype.kind in "iu" and values is not None:
        arr = onp.asarray(values)
        # float host data feeding an integer dtype must bounds-check
        # too (e.g. array([1e12], dtype='int64'))
        if arr.size and arr.dtype.kind in "iuf":
            info = onp.iinfo(target)
            bad_nan = arr.dtype.kind == "f" and \
                bool(onp.isnan(arr).any())
            if bad_nan or arr.max(initial=0) > info.max or \
                    arr.min(initial=0) < info.min:
                raise OverflowError(
                    f"{dtype.name} value out of {target} range under the "
                    "default 32-bit index policy; enable jax x64 mode "
                    "(jax.config.update('jax_enable_x64', True)) for "
                    "true 64-bit arrays")
    return onp.dtype(target)


def is_np_shape():
    """NumPy-shape semantics are always on in this framework.

    The reference has a global toggle (mxnet.util.set_np_shape) because its
    legacy mx.nd API used 0 to mean "unknown dim". This framework is
    NumPy-semantics from day one; the toggle exists for API parity only.
    """
    return True


def is_np_array():
    return True


def set_np(shape=True, array=True, dtype=False):  # noqa: ARG001 - parity signature
    """Parity shim: numpy semantics are always active."""
    return None


def reset_np():
    return None

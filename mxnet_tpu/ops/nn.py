"""Neural-network operators over raw jax arrays.

TPU-native equivalents of the reference's src/operator/nn/* kernels
(convolution.cc, pooling.cc, batch_norm.cc, softmax, dropout, fully
connected, layer/group/instance norm). Instead of hand-written
CUDA/oneDNN kernels these lower to XLA HLO: convolutions and matmuls
map directly onto the MXU via lax.conv_general_dilated / dot_general in
(optionally) bfloat16; elementwise epilogues fuse into them during XLA
compilation. All functions are pure: stateful pieces (BN running stats,
dropout RNG) are threaded explicitly by the callers in gluon/ and npx.

Layout note: the reference defaults to NCHW/OIHW. XLA:TPU internally
prefers NHWC and will transpose as needed; we accept both via `layout`
and default to NCHW for API parity. The Gluon conv layers expose
`layout='NHWC'` for peak TPU throughput.
"""
from __future__ import annotations

import math
from typing import Sequence, Tuple

import numpy as onp
import jax
import jax.numpy as jnp
from jax import lax


def _tuplize(v, n):
    if isinstance(v, (int, onp.integer)):
        return (int(v),) * n
    t = tuple(int(x) for x in v)
    if len(t) == 1:
        return t * n
    return t


# ---------------------------------------------------------------------------
# dense / conv / pooling
# ---------------------------------------------------------------------------
def fully_connected(x, weight, bias=None, flatten=True):
    """y = x @ W^T + b (parity: src/operator/nn/fully_connected.cc).

    weight layout: (out_units, in_units) — reference layout.
    """
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    y = lax.dot_general(x, weight, (((x.ndim - 1,), (1,)), ((), ())))
    if bias is not None:
        y = y + bias
    return y


def _conv_dims(layout: str):
    """(lhs_spec, rhs_spec, out_spec) for lax.conv_general_dilated."""
    if layout in ("NCHW", "NCW", "NCDHW"):
        n = len(layout) - 2
        spatial = "DHW"[-n:] if layout.startswith("NCD") else ("W" if n == 1 else "HW")
        lhs = "NC" + spatial
        rhs = "OI" + spatial
        out = "NC" + spatial
    else:  # NHWC family
        n = len(layout) - 2
        spatial = layout[1:-1]
        lhs = "N" + spatial + "C"
        rhs = "O" + spatial + "I"
        out = "N" + spatial + "C"
    return lhs, rhs, out


def convolution(x, weight, bias=None, kernel=None, stride=1, dilate=1, pad=0,
                num_group=1, layout="NCHW"):
    """N-D convolution (parity: src/operator/nn/convolution.cc).

    weight layout matches the reference: (out_ch, in_ch/groups, *kernel)
    for NCHW; (out_ch, *kernel, in_ch/groups) for NHWC.
    """
    nsp = x.ndim - 2
    stride = _tuplize(stride, nsp)
    dilate = _tuplize(dilate, nsp)
    pad = _tuplize(pad, nsp)
    lhs, rhs, out = _conv_dims(layout)
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (lhs, rhs, out))
    y = lax.conv_general_dilated(
        x, weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if bias is not None:
        if layout.startswith("NC"):
            y = y + bias.reshape((1, -1) + (1,) * nsp)
        else:
            y = y + bias
    return y


def deconvolution(x, weight, bias=None, stride=1, dilate=1, pad=0, adj=0,
                  num_group=1, target_shape=None, layout="NCHW"):
    """Transposed convolution (parity: src/operator/nn/deconvolution.cc).

    weight layout (reference): (in_ch, out_ch/groups, *kernel).
    """
    nsp = x.ndim - 2
    stride = _tuplize(stride, nsp)
    dilate = _tuplize(dilate, nsp)
    pad = _tuplize(pad, nsp)
    adj = _tuplize(adj, nsp)
    # Implement as gradient of convolution: lax.conv_transpose with
    # explicit padding chosen to mimic the reference's output size:
    #   out = (in-1)*stride - 2*pad + dilate*(k-1) + 1 + adj
    if layout.startswith("NC"):
        kshape = weight.shape[2:]
    else:
        kshape = weight.shape[1:-1]
    pads = []
    for i in range(nsp):
        k = (kshape[i] - 1) * dilate[i] + 1
        lo = k - 1 - pad[i]
        hi = k - 1 - pad[i] + adj[i]
        pads.append((lo, hi))
    lhs, rhs, out = _conv_dims(layout)
    # Reference Deconvolution is the GRADIENT of its Convolution
    # (which is cross-correlation): each input pixel scatters w[k]
    # UNflipped (deconvolution.cc). lax.conv_transpose without
    # transpose_kernel applies correlation on the dilated input — the
    # flipped-kernel scatter — so use transpose_kernel=True, which
    # flips the spatial axes AND swaps the kernel's I/O labels: the
    # reference weight (in, out/g, *k) is therefore declared "OI" +
    # spatial here. Pinned by tests/test_operator_conformance.py::
    # test_deconvolution_inverts_stride2_shape.
    if layout.startswith("NC"):
        rhs_spec = "OI" + rhs[2:]
    else:
        rhs_spec = "O" + rhs[1:-1] + "I"
    dn = lax.conv_dimension_numbers(x.shape, weight.shape, (lhs, rhs_spec, out))
    if num_group != 1:
        # grouped deconv: split channels, run per group, concat
        cax = 1 if layout.startswith("NC") else x.ndim - 1
        xs = jnp.split(x, num_group, axis=cax)
        ws = jnp.split(weight, num_group, axis=0)
        ys = [lax.conv_transpose(xg, wg, strides=stride, padding=pads,
                                 rhs_dilation=dilate, dimension_numbers=dn,
                                 transpose_kernel=True)
              for xg, wg in zip(xs, ws)]
        y = jnp.concatenate(ys, axis=cax)
    else:
        y = lax.conv_transpose(x, weight, strides=stride, padding=pads,
                               rhs_dilation=dilate, dimension_numbers=dn,
                               transpose_kernel=True)
    if bias is not None:
        if layout.startswith("NC"):
            y = y + bias.reshape((1, -1) + (1,) * nsp)
        else:
            y = y + bias
    return y


def pooling(x, kernel=1, pool_type="max", stride=None, pad=0,
            global_pool=False, pooling_convention="valid",
            count_include_pad=True, p_value=2, layout="NCHW"):
    """Pooling (parity: src/operator/nn/pooling.cc)."""
    nsp = x.ndim - 2
    channel_last = not layout.startswith("NC")
    if global_pool:
        axes = tuple(range(1, 1 + nsp)) if channel_last else \
            tuple(range(2, 2 + nsp))
        if pool_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        if pool_type == "avg":
            return jnp.mean(x, axis=axes, keepdims=True)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p_value), axis=axes,
                                 keepdims=True), 1.0 / p_value)
    kernel = _tuplize(kernel, nsp)
    stride = _tuplize(stride if stride is not None else kernel, nsp)
    pad = _tuplize(pad, nsp)

    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0),) + tuple((p, p) for p in pad) + ((0, 0),)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)

    if pooling_convention == "full":
        # ceil instead of floor for output size: pad extra on the high side
        new_pads = list(pads)
        off = 1 if channel_last else 2
        for i in range(nsp):
            in_sz = x.shape[off + i]
            k, s, p = kernel[i], stride[i], pad[i]
            out_full = int(math.ceil((in_sz + 2 * p - k) / s)) + 1
            needed = (out_full - 1) * s + k - in_sz - p
            new_pads[off + i] = (p, max(needed, p))
        pads = tuple(new_pads)
    elif pooling_convention == "same":
        new_pads = list(pads)
        off = 1 if channel_last else 2
        for i in range(nsp):
            in_sz = x.shape[off + i]
            k, s = kernel[i], stride[i]
            out_same = int(math.ceil(in_sz / s))
            total = max((out_same - 1) * s + k - in_sz, 0)
            new_pads[off + i] = (total // 2, total - total // 2)
        pads = tuple(new_pads)

    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
            jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        s = lax.reduce_window(x, 0.0 if jnp.issubdtype(x.dtype, jnp.floating)
                              else 0, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return s / denom
        ones = jnp.ones(x.shape, x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / cnt
    if pool_type == "lp":
        s = lax.reduce_window(jnp.power(jnp.abs(x), p_value), 0.0, lax.add,
                              window, strides, pads)
        return jnp.power(s, 1.0 / p_value)
    raise ValueError(f"unknown pool_type {pool_type!r}")


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
def accum_dtype(dtype):
    """The ONE accumulation-dtype policy for reduced-precision inputs:
    normalization statistics (mean/var) and softmax-style reductions
    accumulate in fp32 when the input is a 16-bit float, and in the
    input's own dtype otherwise (fp32/fp64 stay put — for fp32 inputs
    every ``astype`` this implies is an identity, keeping the fp32
    path bitwise unchanged). Every norm below routes through this
    helper so the bf16 compute path upcasts exactly once instead of
    each op hand-rolling (and potentially double-casting) its own
    rule."""
    return jnp.float32 if dtype in (jnp.bfloat16, jnp.float16) \
        else dtype


def batch_norm_train(x, gamma, beta, axis=1, eps=1e-5):
    """Returns (out, batch_mean, batch_var). Caller updates running stats.

    Parity: src/operator/nn/batch_norm.cc forward-train. var is the
    biased (population) variance like the reference.
    """
    axes = tuple(i for i in range(x.ndim) if i != axis)
    compute_dtype = accum_dtype(x.dtype)
    xc = x.astype(compute_dtype)
    mean = jnp.mean(xc, axis=axes)
    var = jnp.var(xc, axis=axes)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    inv = lax.rsqrt(var + eps).reshape(shape)
    out = (xc - mean.reshape(shape)) * inv
    out = out * gamma.astype(compute_dtype).reshape(shape) + \
        beta.astype(compute_dtype).reshape(shape)
    return out.astype(x.dtype), mean, var


def batch_norm_inference(x, gamma, beta, moving_mean, moving_var, axis=1,
                         eps=1e-5):
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    compute_dtype = accum_dtype(x.dtype)
    xc = x.astype(compute_dtype)
    inv = lax.rsqrt(moving_var.astype(compute_dtype) + eps).reshape(shape)
    out = (xc - moving_mean.astype(compute_dtype).reshape(shape)) * inv
    out = out * gamma.astype(compute_dtype).reshape(shape) + \
        beta.astype(compute_dtype).reshape(shape)
    return out.astype(x.dtype)


def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    """Parity: src/operator/nn/layer_norm.cc. Statistics accumulate
    per the :func:`accum_dtype` policy (fp32 for 16-bit inputs —
    mean/var of a bf16 residual stream in bf16 loses the mantissa
    the normalization exists to use); output returns in ``x``'s
    dtype so the reduced-precision activation flow is preserved."""
    compute_dtype = accum_dtype(x.dtype)
    xc = x.astype(compute_dtype)
    mean = jnp.mean(xc, axis=axis, keepdims=True)
    var = jnp.var(xc, axis=axis, keepdims=True)
    out = (xc - mean) * lax.rsqrt(var + eps)
    if axis < 0:
        axis += x.ndim
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    out = out * gamma.astype(compute_dtype).reshape(shape) + \
        beta.astype(compute_dtype).reshape(shape)
    return out.astype(x.dtype)


def group_norm(x, gamma, beta, num_groups=1, eps=1e-5):
    """Parity: src/operator/nn/group_norm.cc. Layout NC+spatial."""
    n, c = x.shape[0], x.shape[1]
    g = num_groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    shape = [1] * x.ndim
    shape[1] = c
    return out * gamma.reshape(shape) + beta.reshape(shape)


def instance_norm(x, gamma, beta, eps=1e-5):
    """Parity: src/operator/instance_norm.cc. Layout NC+spatial."""
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps)
    shape = [1] * x.ndim
    shape[1] = x.shape[1]
    return out * gamma.reshape(shape) + beta.reshape(shape)


def l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        axes = tuple(range(1, x.ndim))
    elif mode == "channel":
        axes = (1,)
    else:  # spatial
        axes = tuple(range(2, x.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + eps)
    return x / norm


def rms_norm(x, gamma, axis=-1, eps=1e-6):
    """RMSNorm (no reference analog; standard for modern LLM blocks)."""
    ms = jnp.mean(jnp.square(x), axis=axis, keepdims=True)
    return x * lax.rsqrt(ms + eps) * gamma


# ---------------------------------------------------------------------------
# activations / softmax
# ---------------------------------------------------------------------------
def activation(x, act_type):
    if act_type == "relu":
        return jax.nn.relu(x)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "log_sigmoid":
        return jax.nn.log_sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return jax.nn.soft_sign(x)
    if act_type == "mish":
        return x * jnp.tanh(jax.nn.softplus(x))
    if act_type == "relu6":
        return jax.nn.relu6(x)
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "silu" or act_type == "swish":
        return jax.nn.silu(x)
    raise ValueError(f"unknown activation {act_type!r}")


def leaky_relu(x, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125,
               upper_bound=0.334):
    """Parity: src/operator/leaky_relu.cc (leaky/prelu/elu/selu/gelu)."""
    if act_type == "leaky":
        return jnp.where(x >= 0, x, slope * x)
    if act_type == "prelu":
        g = gamma
        if g.ndim < x.ndim and g.ndim == 1:
            shape = [1] * x.ndim
            if x.ndim > 1:
                shape[1] = g.shape[0] if g.shape[0] != 1 else 1
            g = g.reshape(shape)
        return jnp.where(x >= 0, x, g * x)
    if act_type == "elu":
        return jnp.where(x >= 0, x, slope * jnp.expm1(x))
    if act_type == "selu":
        return jax.nn.selu(x)
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    if act_type == "silu":
        return jax.nn.silu(x)
    raise ValueError(f"unknown leaky_relu act_type {act_type!r}")


def softmax(x, axis=-1, temperature=None, length=None):
    """Parity: src/operator/nn/softmax.cc (with optional length masking)."""
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        x = _mask_by_length(x, length, axis)
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1, temperature=None, length=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        x = _mask_by_length(x, length, axis)
    return jax.nn.log_softmax(x, axis=axis)


def _mask_by_length(x, length, axis):
    ax = axis % x.ndim
    idx = jnp.arange(x.shape[ax])
    idx = idx.reshape((1,) * ax + (-1,) + (1,) * (x.ndim - ax - 1))
    ln = length.reshape(length.shape + (1,) * (x.ndim - length.ndim))
    mask = idx < ln
    return jnp.where(mask, x, -jnp.inf)


def masked_softmax(x, mask=None, axis=-1, temperature=1.0):
    if temperature != 1.0:
        x = x / temperature
    if mask is not None:
        x = jnp.where(mask, x, -1e30 if x.dtype == jnp.bfloat16 else -jnp.inf)
    return jax.nn.softmax(x, axis=axis)


def softmin(x, axis=-1):
    return jax.nn.softmax(-x, axis=axis)


# ---------------------------------------------------------------------------
# dropout / misc
# ---------------------------------------------------------------------------
def dropout(x, key, p=0.5, axes=None):
    """Parity: src/operator/nn/dropout.cc. Inverted dropout; `axes`
    broadcasts the mask (spatial dropout)."""
    if p <= 0.0:
        return x
    shape = list(x.shape)
    if axes:
        for ax in axes:
            shape[ax] = 1
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, tuple(shape))
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def embedding(indices, weight, sparse_grad=False):
    """Parity: src/operator/tensor/indexing_op.cc Embedding."""
    return jnp.take(weight, indices.astype(jnp.int32), axis=0)


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    # float label arrays are common at the API boundary (reference
    # semantics); jax.nn.one_hot deprecates float inputs — cast
    idx = indices if jnp.issubdtype(jnp.asarray(indices).dtype,
                                    jnp.integer) \
        else jnp.asarray(indices).astype(jnp.int32)
    return jax.nn.one_hot(idx, depth, dtype=jnp.dtype(dtype)) * \
        (on_value - off_value) + off_value


def topk(x, k=1, axis=-1, ret_typ="indices", is_ascend=False, dtype="float32"):
    ax = axis % x.ndim
    xm = jnp.moveaxis(x, ax, -1)
    if is_ascend:
        # bottom-k via stable ascending argsort: negation would wrap
        # unsigned dtypes (and INT_MIN) and rank them wrongly
        idx = jnp.argsort(xm, axis=-1)[..., :k]
        vals = jnp.take_along_axis(xm, idx, -1)
    else:
        vals, idx = lax.top_k(xm, k)
    if ret_typ == "mask":
        # 0/1 mask in the data dtype with ones at top-k positions
        # (parity: src/operator/tensor/ordering_op-inl.h kReturnMask).
        # idx still indexes the last (sort) axis here; top_k indices
        # are distinct, so summing the k one-hots stays 0/1.
        onehot = jax.nn.one_hot(idx, xm.shape[-1], dtype=x.dtype)
        return jnp.moveaxis(onehot.sum(-2), -1, ax)
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax)
    if ret_typ == "indices":
        return idx.astype(jnp.dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx.astype(jnp.dtype(dtype))
    raise ValueError(f"unsupported ret_typ {ret_typ!r}")


def pick(x, index, axis=-1, mode="clip", keepdims=False):
    """Parity: src/operator/tensor/broadcast_reduce_op_index.cc pick."""
    ax = axis % x.ndim
    idx = jnp.expand_dims(index.astype(jnp.int32), ax)
    out = jnp.take_along_axis(x, idx, axis=ax)
    return out if keepdims else jnp.squeeze(out, axis=ax)


def sequence_mask(x, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    """Parity: src/operator/sequence_mask.cc (time-major by default)."""
    if not use_sequence_length or sequence_length is None:
        return x
    t = x.shape[axis]
    idx = jnp.arange(t)
    idx = idx.reshape((-1, 1) if axis == 0 else (1, -1))
    ln = sequence_length.reshape((1, -1) if axis == 0 else (-1, 1))
    mask = idx < ln
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    return jnp.where(mask, x, value)


def sequence_last(x, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * x.ndim
        idx[axis] = -1
        return x[tuple(idx)]
    ln = (sequence_length - 1).astype(jnp.int32)
    xm = jnp.moveaxis(x, axis, 0)
    batch = jnp.arange(xm.shape[1])
    return xm[ln, batch]


def sequence_reverse(x, sequence_length=None, use_sequence_length=False,
                     axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(x, axis=axis)
    xm = jnp.moveaxis(x, axis, 0)
    t = xm.shape[0]
    idx = jnp.arange(t).reshape(-1, 1)
    ln = sequence_length.reshape(1, -1).astype(jnp.int32)
    rev_idx = jnp.where(idx < ln, ln - 1 - idx, idx)
    out = jnp.take_along_axis(
        xm, rev_idx.reshape(rev_idx.shape + (1,) * (xm.ndim - 2)), axis=0)
    return jnp.moveaxis(out, 0, axis)


# ---------------------------------------------------------------------------
# fused RNN (parity: src/operator/rnn-inl.h — multi-layer, bidirectional,
# variable-length RNN/LSTM/GRU with the cuDNN flat-parameter layout)
# ---------------------------------------------------------------------------
# TPU design: the input projection for ALL timesteps is one large matmul
# (T*N, I)x(I, G*H) that XLA tiles onto the MXU; only the hidden-to-
# hidden recurrence runs under lax.scan. Gate conventions follow the
# reference/cuDNN: LSTM gates [i, f, g, o]; GRU gates [r, z, n] with
# "linear before reset" (reset applied after the h2h matmul).

_RNN_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}


def rnn_param_size(mode, input_size, state_size, num_layers, bidirectional,
                   projection_size=None):
    """Length of the flat parameter vector (parity: the reference's
    GetRnnParamSize, src/operator/rnn-inl.h:182 — with projection the
    recurrent input is the projected state and the (proj, state)
    projection matrices are appended after all weights+biases)."""
    g = _RNN_GATES[mode]
    d = 2 if bidirectional else 1
    rec = projection_size if projection_size else state_size
    size = 0
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else rec * d
        size += d * g * state_size * (in_size + rec  # weights
                                      + 2)           # both biases
    if projection_size:
        size += projection_size * state_size * num_layers * d
    return size


def _rnn_unpack(params, mode, input_size, state_size, num_layers,
                bidirectional, projection_size=None):
    """Split the flat vector into per-(layer, direction) weight/bias
    arrays: all weights first — with the LSTMP projection matrix
    interleaved after each h2h (the reference's order,
    python/mxnet/gluon/rnn/rnn_layer.py:216-227) — then all biases."""
    g = _RNN_GATES[mode]
    d = 2 if bidirectional else 1
    h = state_size
    rec = projection_size if projection_size else h
    pos = 0
    weights, biases, projs = [], [], []
    for layer in range(num_layers):
        in_size = input_size if layer == 0 else rec * d
        for _ in range(d):
            wi = params[pos:pos + g * h * in_size].reshape(g * h, in_size)
            pos += g * h * in_size
            wh = params[pos:pos + g * h * rec].reshape(g * h, rec)
            pos += g * h * rec
            weights.append((wi, wh))
            if projection_size:
                projs.append(params[pos:pos + rec * h].reshape(rec, h))
                pos += rec * h
    for layer in range(num_layers):
        for _ in range(d):
            bi = params[pos:pos + g * h]
            pos += g * h
            bh = params[pos:pos + g * h]
            pos += g * h
            biases.append((bi, bh))
    return weights, biases, projs


def _rnn_layer_scan(mode, xp, bh, h0, c0, wh, mask, clip_min, clip_max,
                    clip_nan, wr=None):
    """Scan one direction of one layer.

    xp: (T, N, G*H) precomputed input projection (+ i2h bias; for
    rnn/lstm also + h2h bias). bh: h2h bias, used separately only by
    GRU's linear-before-reset candidate. mask: (T, N, 1) or None.
    wr: optional (P, H) LSTMP projection — the recurrent/output state
    becomes r = (o*tanh(c)) @ wr.T (rnn-inl.h projection path).
    """
    h_dim = h0.shape[-1]

    def step(carry, inp):
        if mask is None:
            x_t, m_t = inp, None
        else:
            x_t, m_t = inp
        if mode == "lstm":
            h, c = carry
            gates = x_t + h @ wh.T
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            if clip_min is not None and clip_max is not None:
                if clip_nan:
                    c_new = jnp.nan_to_num(c_new, nan=0.0)
                c_new = jnp.clip(c_new, clip_min, clip_max)
            h_new = o * jnp.tanh(c_new)
            if wr is not None:
                h_new = h_new @ wr.T
            if m_t is not None:
                h_new = jnp.where(m_t, h_new, h)
                c_new = jnp.where(m_t, c_new, c)
            out = h_new if m_t is None else jnp.where(m_t, h_new,
                                                      jnp.zeros_like(h_new))
            return (h_new, c_new), out
        h = carry
        if mode == "gru":
            hh = h @ wh.T + bh
            xr, xz, xn = jnp.split(x_t, 3, axis=-1)
            hr, hz, hn = jnp.split(hh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h_new = (1.0 - z) * n + z * h
        else:
            pre = x_t + h @ wh.T
            h_new = jnp.tanh(pre) if mode == "rnn_tanh" else jax.nn.relu(pre)
        if m_t is not None:
            h_new = jnp.where(m_t, h_new, h)
            out = jnp.where(m_t, h_new, jnp.zeros_like(h_new))
        else:
            out = h_new
        return h_new, out

    carry0 = (h0, c0) if mode == "lstm" else h0
    xs = xp if mask is None else (xp, mask)
    carry, ys = jax.lax.scan(step, carry0, xs)
    if mode == "lstm":
        return ys, carry[0], carry[1]
    return ys, carry, jnp.zeros((0, h0.shape[0], h_dim), xp.dtype)


def rnn(data, params, state, state_cell=None, sequence_length=None,
        mode="lstm", state_size=None, num_layers=1, bidirectional=False,
        p=0.0, key=None, train=False, projection_size=None,
        lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False):
    """Fused multi-layer RNN. data (T, N, I); state (L*D, N, H) — or
    (L*D, N, P) for LSTMP; returns (output (T, N, H*D or P*D), h_n,
    [c_n])."""
    if projection_size and mode != "lstm":
        raise ValueError("projection_size is only defined for LSTM "
                         "(rnn-inl.h LSTMP)")
    g = _RNN_GATES[mode]
    d = 2 if bidirectional else 1
    t_len, batch, input_size = data.shape
    if state_size is not None:
        h = state_size
    elif projection_size:
        h = state_cell.shape[-1]
    else:
        h = state.shape[-1]
    weights, biases, projs = _rnn_unpack(params, mode, input_size, h,
                                         num_layers, bidirectional,
                                         projection_size)

    mask = None
    if sequence_length is not None:
        mask = (jnp.arange(t_len)[:, None] <
                sequence_length[None, :].astype(jnp.int32))[..., None]

    x = data
    h_outs, c_outs = [], []
    for layer in range(num_layers):
        dir_outs = []
        for di in range(d):
            idx = layer * d + di
            wi, wh = weights[idx]
            bi, bh = biases[idx]
            xin = x
            if di == 1:
                xin = sequence_reverse(
                    x, sequence_length,
                    use_sequence_length=sequence_length is not None)
            # whole-sequence input projection: the MXU-sized matmul
            xp = xin @ wi.T + bi
            if mode != "gru":
                xp = xp + bh
            ys, hn, cn = _rnn_layer_scan(
                mode, xp, bh, state[idx],
                state_cell[idx] if state_cell is not None else None,
                wh, mask, lstm_state_clip_min, lstm_state_clip_max,
                lstm_state_clip_nan,
                wr=projs[idx] if projs else None)
            if di == 1:
                ys = sequence_reverse(
                    ys, sequence_length,
                    use_sequence_length=sequence_length is not None)
            dir_outs.append(ys)
            h_outs.append(hn)
            c_outs.append(cn)
        x = dir_outs[0] if d == 1 else jnp.concatenate(dir_outs, axis=-1)
        if train and p > 0.0 and layer < num_layers - 1 and key is not None:
            x = dropout(x, jax.random.fold_in(key, layer), p=p)
    h_n = jnp.stack(h_outs)
    if mode == "lstm":
        return x, h_n, jnp.stack(c_outs)
    return x, h_n

"""Spatial warping ops: grid sampling, spatial transformer,
correlation, count sketch — the reference's legacy vision-op family
(registered via MXNET_REGISTER_OP_PROPERTY rather than
NNVM_REGISTER_OP: src/operator/bilinear_sampler.cc,
grid_generator.cc, spatial_transformer.cc, correlation.cc,
src/operator/contrib/count_sketch.cc).

All pure jax with static shapes; bilinear sampling shares the
gather-plus-lerp pattern of detection.roi_align.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def grid_generator(data, transform_type="affine", target_shape=None):
    """GridGenerator (grid_generator.cc).

    'affine': data (B, 6) affine θ → grid (B, 2, H, W) of normalized
    (x, y) sampling coords in [-1, 1] over target_shape (H, W).
    'warp': data (B, 2, H, W) pixel flow → identity grid + normalized
    flow."""
    if transform_type == "affine":
        H, W = target_shape
        theta = data.reshape(-1, 2, 3)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], 0).reshape(3, -1)  # (3, HW)
        out = jnp.einsum("bij,jn->bin", theta, base)        # (B, 2, HW)
        return out.reshape(-1, 2, H, W)
    if transform_type == "warp":
        B, _, H, W = data.shape
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        # pixel-unit flow normalizes by (size-1)/2
        fx = data[:, 0] * 2.0 / jnp.maximum(W - 1, 1)
        fy = data[:, 1] * 2.0 / jnp.maximum(H - 1, 1)
        return jnp.stack([gx[None] + fx, gy[None] + fy], 1)
    raise ValueError(f"unknown transform_type {transform_type!r}")


def bilinear_sampler(data, grid):
    """BilinearSampler (bilinear_sampler.cc): data (B, C, H, W), grid
    (B, 2, H', W') of normalized (x, y) in [-1, 1]; samples outside
    the border read 0 (the reference's zero padding)."""
    B, C, H, W = data.shape
    x = (grid[:, 0] + 1.0) * (W - 1) / 2.0      # (B, H', W')
    y = (grid[:, 1] + 1.0) * (H - 1) / 2.0

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def tap(yi, xi):
        inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)

        def one(img, yb, xb, mb):
            v = img[:, yb, xb]                   # (C, H', W')
            return v * mb[None]
        return jax.vmap(one)(data, yc, xc, inside.astype(data.dtype))

    g00 = tap(y0, x0)
    g01 = tap(y0, x0 + 1)
    g10 = tap(y0 + 1, x0)
    g11 = tap(y0 + 1, x0 + 1)
    wx = wx[:, None]
    wy = wy[:, None]
    return (g00 * (1 - wy) * (1 - wx) + g01 * (1 - wy) * wx +
            g10 * wy * (1 - wx) + g11 * wy * wx)


def spatial_transformer(data, loc, target_shape,
                        transform_type="affine",
                        sampler_type="bilinear"):
    """SpatialTransformer (spatial_transformer.cc) = affine
    GridGenerator ∘ BilinearSampler."""
    assert transform_type == "affine" and sampler_type == "bilinear"
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


def correlation(data1, data2, kernel_size=1, max_displacement=1,
                stride1=1, stride2=1, pad_size=0, is_multiply=True):
    """FlowNet correlation layer (correlation.cc:47-82).

    data1/data2 (B, C, H, W). Output (B, D*D, outH, outW) where
    D = 2*(max_displacement//stride2) + 1; each output channel is the
    kernel_size² patch correlation at one (stride2-quantized)
    displacement, normalized by kernel_size²*C."""
    B, C, H, W = data1.shape
    kr = kernel_size // 2
    border = max_displacement + kr
    pw = W + 2 * pad_size
    ph = H + 2 * pad_size
    out_w = -(-(pw - border * 2) // stride1)   # ceil
    out_h = -(-(ph - border * 2) // stride1)
    rad = max_displacement // stride2
    D = 2 * rad + 1
    sumelems = kernel_size * kernel_size * C

    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))

    ys = jnp.arange(out_h) * stride1 + max_displacement   # centers
    xs = jnp.arange(out_w) * stride1 + max_displacement
    ky = jnp.arange(-kr, kr + 1)
    kx = jnp.arange(-kr, kr + 1)

    def at(img, dy, dx):
        """img patches around (ys+dy, xs+dx): (B, C, outH, outW, k, k)."""
        yy = ys[:, None] + ky[None, :] + dy      # (outH, k)
        xx = xs[:, None] + kx[None, :] + dx      # (outW, k)
        yy = jnp.clip(yy, 0, ph - 1)
        xx = jnp.clip(xx, 0, pw - 1)
        return img[:, :, yy[:, None, :, None], xx[None, :, None, :]]

    outs = []
    for dyi in range(-rad, rad + 1):
        for dxi in range(-rad, rad + 1):
            a = at(p1, 0, 0)
            b = at(p2, dyi * stride2, dxi * stride2)
            if is_multiply:
                v = (a * b).sum(axis=(1, 4, 5))
            else:
                v = jnp.abs(a - b).sum(axis=(1, 4, 5))
            outs.append(v / sumelems)
    # channel order: row-major over (dy, dx) like the reference's
    # (top_channel / width, top_channel % width)
    return jnp.stack(outs, 1)


def count_sketch(data, h, s, out_dim):
    """Count sketch projection (contrib/count_sketch.cc): data (N, D),
    h (D,) target buckets in [0, out_dim), s (D,) signs ±1 →
    out (N, out_dim) with out[n, h[i]] += s[i] * data[n, i]."""
    hi = h.reshape(-1).astype(jnp.int32)
    si = s.reshape(-1).astype(data.dtype)
    contrib = data * si[None, :]
    out = jnp.zeros((data.shape[0], int(out_dim)), data.dtype)
    return out.at[:, hi].add(contrib)

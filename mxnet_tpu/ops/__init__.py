"""Operator invocation machinery.

This is the TPU-native replacement for the reference's imperative dispatch
chain (python op wrapper → FFI → Imperative::Invoke → engine push →
FCompute kernel; see SURVEY.md §3.1 and src/imperative/imperative.cc:98).

Design: an "operator" here is a plain Python callable over raw
``jax.Array`` values, already closed over its static attributes (axis,
kernel size, ...). ``apply_op`` is the single funnel every frontend op
goes through. It:

1. unwraps NDArray arguments to raw jax values,
2. dispatches eagerly through JAX (async: returns futures immediately —
   the engine contract of the reference, engine.py),
3. when autograd is recording and a differentiable input is on the tape,
   captures the op's VJP (``jax.vjp``) at invoke time — the residuals it
   stores are the moral equivalent of the reference's retained
   forward buffers (Imperative::RecordOp, imperative.cc:204),
4. wraps outputs back into NDArrays on the right context.

Shape/dtype inference (the reference's SetShapeType,
imperative_utils.h:169) is performed by JAX's eager dispatch itself;
kernel selection/fusion is XLA's job. There is deliberately no
per-op jit here: eager JAX dispatch already lowers each primitive to a
cached compiled kernel, and *graph-level* fusion happens when a model is
hybridized (one whole-graph XLA program, see gluon/block.py).
"""
from __future__ import annotations

import jax
import numpy as onp

from .. import engine


def _is_tracer(x):
    return isinstance(x, jax.core.Tracer)


def _needs_grad_dtype(dt) -> bool:
    """Cotangents only exist for inexact dtypes."""
    return onp.issubdtype(onp.dtype(dt), onp.inexact) or str(dt) == "bfloat16"


def apply_op(fn, *args, nout: int = 1, ctx=None, name: str = None):
    """Invoke ``fn`` over mixed NDArray / raw arguments.

    Positional NDArray arguments are the differentiable inputs; all
    static attributes must already be closed over in ``fn``.

    Returns a single NDArray (nout==1) or a tuple of NDArrays.
    """
    from ..ndarray.ndarray import NDArray  # local: avoid import cycle
    from .. import autograd

    datas = []
    nd_positions = []
    for i, a in enumerate(args):
        if isinstance(a, NDArray):
            datas.append(a._data)
            nd_positions.append(i)
        else:
            datas.append(a)

    # AMP cast insertion at the single dispatch funnel: every op —
    # eager or inside the hybridize trace — gets the same cast-list
    # treatment (parity: amp.init namespace patching, amp/amp.py:308).
    # Casts are folded INTO fn so jax.vjp differentiates through them
    # and cotangent dtypes stay consistent across precision boundaries.
    from .. import amp as _amp
    if _amp.is_active() and name is not None and nd_positions:
        _plan = _amp.autocast_plan(name, datas, nd_positions)
        if _plan:
            _orig_fn = fn

            def fn(*xs, _of=_orig_fn, _cm=_plan):
                xs = list(xs)
                for _i, _dt in _cm.items():
                    xs[_i] = xs[_i].astype(_dt)
                return _of(*xs)

    record = autograd.is_recording() and any(
        autograd._on_tape(args[i]) for i in nd_positions
    )

    if record:
        # Differentiate w.r.t. float NDArray inputs only.
        diff_idx = [
            i
            for i in nd_positions
            if _needs_grad_dtype(datas[i].dtype)
        ]
        if diff_idx:
            def closed(*diff_datas):
                # Always return a tuple so every VJP takes a tuple
                # cotangent (uniform backward calling convention).
                full = list(datas)
                for j, d in zip(diff_idx, diff_datas):
                    full[j] = d
                out = fn(*full)
                return tuple(out) if isinstance(out, (tuple, list)) else (out,)

            outs, vjp_fn = jax.vjp(closed, *[datas[i] for i in diff_idx])
            # Int-valued outputs (argmax of a diff op etc.) can't carry
            # cotangents; if none of the outputs are inexact, drop the tape.
            if any(_needs_grad_dtype(o.dtype) for o in outs):
                wrapped = _wrap_outputs(outs, args, nd_positions, ctx)
                autograd._record(
                    name or getattr(fn, "__name__", "op"),
                    closed,
                    vjp_fn,
                    [args[i] for i in diff_idx],
                    wrapped,
                )
                return wrapped[0] if nout == 1 and len(wrapped) == 1 else tuple(wrapped)
            # fall through: treat as non-differentiable
            return _finish(outs, args, nd_positions, ctx, nout)

    out = fn(*datas)
    outs = out if isinstance(out, (tuple, list)) else (out,)
    return _finish(outs, args, nd_positions, ctx, nout)


def _infer_ctx(args, nd_positions, ctx):
    if ctx is not None:
        return ctx
    for i in nd_positions:
        return args[i].ctx
    from ..context import current_context

    return current_context()


def _wrap_outputs(outs, args, nd_positions, ctx):
    from ..ndarray.ndarray import NDArray

    octx = _infer_ctx(args, nd_positions, ctx)
    return [NDArray(engine.track(o), ctx=octx) for o in outs]


def _finish(outs, args, nd_positions, ctx, nout):
    wrapped = _wrap_outputs(outs, args, nd_positions, ctx)
    if nout == 1 and len(wrapped) == 1:
        return wrapped[0]
    return tuple(wrapped)

"""Batched multi-tenant LoRA: a stacked adapter bank applied inside
the one fixed-shape decode program.

A fine-tuned variant served as its own engine costs a full parameter
copy, its own KV pool, and its own compiled closures — N tenants cost
N x HBM and N x compile caches. LoRA (Hu et al., 2021) collapses that:
a tenant is a low-rank delta ``y = base(x) + (x @ A) @ B * (alpha/r)``
over frozen base weights, a few percent of the parameter bytes. The
serving twist here is the BATCHED bank: all adapters of one engine
live stacked as

    A:     (n_adapters, d_in, rank)
    B:     (n_adapters, rank, d_out)
    scale: (n_adapters,)            # alpha / rank per adapter

and one decode step over B slots gathers each row's adapter INSIDE the
trace by a per-slot ``(B,)`` int32 index vector::

    y[b] = base(x[b]) + (x[b] @ A[idx[b]]) @ B[idx[b]] * scale[idx[b]]

so a batch mixing any number of tenants (base-model rows included)
runs ONE compiled program — the index vector is runtime data, exactly
like the int8 quant tables of ops/quantized.py. Adapter slot 0 is
RESERVED all-zeros: a base-model request rides the same program and
its delta is exactly ``+ 0.0``, bit-identical to a LoRA-free engine
(the engine maps "no adapter" to index 0 and never hands slot 0 to a
tenant).

The ``ops.lora.trace`` telemetry counter increments only when a
LoRA-bearing closure actually TRACES (this module's ``apply`` runs at
trace time only) — the bank analog of ``model.gpt.trace``, used by
tests and ``bench.py --lora`` to prove adapter load/unload/refresh
causes zero retraces.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import telemetry, tracing

__all__ = ["init_bank", "set_slot", "clear_slot", "apply",
           "bank_bytes"]


def init_bank(n_adapters, d_in, d_out, rank):
    """Allocate an all-zeros stacked adapter bank for one projection:
    ``{"A": (n, d_in, r), "B": (n, r, d_out), "scale": (n,)}`` fp32.
    Slot 0 is the reserved base-model (all-zeros) adapter — ``n``
    must leave at least one loadable slot beside it."""
    n, r = int(n_adapters), int(rank)
    if r < 1:
        raise ValueError(f"lora rank must be >= 1, got {rank}")
    if n < 2:
        raise ValueError(
            f"n_adapters must be >= 2 (slot 0 is the reserved "
            f"all-zeros base adapter), got {n_adapters}")
    return {
        "A": jnp.zeros((n, int(d_in), r), jnp.float32),
        "B": jnp.zeros((n, r, int(d_out)), jnp.float32),
        "scale": jnp.zeros((n,), jnp.float32),
    }


def set_slot(bank, idx, a, b, alpha):
    """Install adapter ``(a, b, alpha)`` into bank slot ``idx``
    (host-side: returns a NEW bank pytree with the same structure —
    the closures take the bank as a runtime argument, so installing
    refreshed arrays retraces nothing). Slot 0 is immutable."""
    idx = int(idx)
    n, d_in, r = bank["A"].shape
    d_out = bank["B"].shape[2]
    if idx == 0:
        raise ValueError("adapter slot 0 is the reserved all-zeros "
                         "base adapter and cannot be written")
    if not 0 < idx < n:
        raise ValueError(f"adapter slot {idx} out of range (bank holds "
                         f"{n} slots)")
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)
    if a.shape != (d_in, r):
        raise ValueError(f"adapter A shape {a.shape} != bank slot "
                         f"shape {(d_in, r)}")
    if b.shape != (r, d_out):
        raise ValueError(f"adapter B shape {b.shape} != bank slot "
                         f"shape {(r, d_out)}")
    return {
        "A": bank["A"].at[idx].set(a),
        "B": bank["B"].at[idx].set(b),
        "scale": bank["scale"].at[idx].set(float(alpha) / r),
    }


def clear_slot(bank, idx):
    """Zero bank slot ``idx`` back to the base (no-op) adapter —
    same runtime-argument/no-retrace contract as :func:`set_slot`."""
    idx = int(idx)
    if idx == 0:
        raise ValueError("adapter slot 0 is already the reserved "
                         "all-zeros base adapter")
    return {
        "A": bank["A"].at[idx].set(0.0),
        "B": bank["B"].at[idx].set(0.0),
        "scale": bank["scale"].at[idx].set(0.0),
    }


def apply(y, x, bank, idx):
    """``y + (x @ A[idx]) @ B[idx] * scale[idx]`` — the batched
    adapter delta over a projection's pre-activation output.

    ``y``/``x`` are ``(B, S, d_out)``/``(B, S, d_in)`` (decode steps
    run S=1), ``idx`` is the per-row ``(B,)`` int32 adapter index —
    gathered inside the trace, so tenant mix is runtime data. Rows
    with ``idx == 0`` add an exact ``0.0`` (slot 0 is all-zeros):
    base-model rows are bit-identical to the LoRA-free program's
    output. The low-rank factors contract in fp32 regardless of the
    base path (int8 engines keep the delta fp32 over the dequant
    base)."""
    telemetry.counter("ops.lora.trace")  # trace-time only
    tracing.flight.record("compile", what="ops.lora")
    idx = jnp.asarray(idx, jnp.int32)
    a = bank["A"][idx]                          # (B, d_in, r)
    b = bank["B"][idx]                          # (B, r, d_out)
    s = bank["scale"][idx]                      # (B,)
    lo = jnp.einsum("bsd,bdr->bsr", jnp.asarray(x, jnp.float32), a)
    delta = jnp.einsum("bsr,bro->bso", lo, b) * s[:, None, None]
    return y + delta


def bank_bytes(banks):
    """Total HBM bytes of a model's adapter banks (an iterable of
    per-block ``{proj: bank}`` dicts) — the numerator of the
    tenants-per-HBM-byte consolidation story (``bench.py --lora``)."""
    total = 0
    for tab in banks:
        for bank in tab.values():
            total += sum(int(v.size) * v.dtype.itemsize
                         for v in bank.values())
    return total

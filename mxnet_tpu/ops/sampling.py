"""Sampling heads for the serving stack: temperature / top-k / top-p
logit warping, per-row categorical sampling with EXPLICIT PRNG keys,
and the speculative-decoding accept rule.

Design constraints (serving/generate.py is the caller):

- Every function is a pure jnp program over FIXED shapes — the engine
  jits each one once per shape at ``warmup()`` and the steady state
  compiles nothing. Per-request knobs (``temperature``/``top_k``/
  ``top_p``) are RUNTIME ``(B,)`` vectors, one entry per slot, so a
  mixed batch of greedy and stochastic requests runs the same program.
- Randomness is an explicit per-row key (raw ``(B, 2)`` uint32 PRNG
  key data — random_state.py's convention). Each call SPLITS every
  row's key inside the trace and returns the advanced keys; the engine
  threads them like it threads the KV cache. A request's key stream
  therefore depends only on its seed and the engine configuration —
  same-seed reruns are bitwise-reproducible across engine restarts,
  and co-tenants can never perturb a stream (rows are independent).
- ``temperature <= 0`` marks a GREEDY row: the sampled paths are
  bypassed with ``argmax`` over the UNWARPED logits (bit-equal to the
  engine's host-side greedy argmax), so greedy requests riding in a
  sampling batch stay token-identical to a pure-greedy engine.

The warp order is the conventional one (HF ``LogitsProcessor`` chain):
temperature first, then top-k, then top-p over the renormalized
post-top-k distribution. ``top_k <= 0`` (or >= vocab) and
``top_p >= 1`` disable their filters.

``speculative_accept`` implements both acceptance disciplines of
docs/SERVING.md "Speculative decoding":

- greedy rows: accept draft token ``d_{j+1}`` while it equals the
  target's argmax ``t_j``, then commit the target's own token at the
  first mismatch (or the bonus token after k accepts) — the committed
  stream is EXACTLY what non-speculative greedy decode would emit.
- stochastic rows: the standard speculative-sampling rule (Leviathan
  et al. 2023; Chen et al. 2023): accept ``d`` with probability
  ``min(1, p(d)/q(d))`` where ``p``/``q`` are the WARPED target/draft
  distributions, and on rejection sample from the residual
  ``norm(max(p - q, 0))`` — the marginal distribution of every
  committed token is exactly the warped target distribution.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

#: the attention convention's finite -inf (ops/attention.py NEG_INF):
#: masked logits must survive softmax without minting NaNs
NEG_INF = -1e30

__all__ = ["warp_logits", "sample_tokens", "sample_with_probs",
           "greedy_accept", "speculative_accept"]


def warp_logits(logits, temperature, top_k, top_p):
    """Apply temperature, then top-k, then top-p to ``logits``
    (..., V). The knobs broadcast over the leading axes (the serving
    engine passes ``(B,)`` vectors against ``(B, V)`` logits, and the
    accept rule ``(B, 1)`` against ``(B, K+1, V)``). Masked entries
    are set to ``NEG_INF``; at least one entry per row always
    survives. ``temperature <= 0`` rows are warped at temperature 1 —
    the caller treats them as greedy and never samples the result."""
    v = logits.shape[-1]
    temperature = jnp.asarray(temperature, jnp.float32)
    top_k = jnp.asarray(top_k, jnp.int32)
    top_p = jnp.asarray(top_p, jnp.float32)
    t = jnp.where(temperature > 0, temperature, 1.0)[..., None]
    x = logits.astype(jnp.float32) / t
    # top-k: keep the k largest (k <= 0 or >= V disables)
    desc = jnp.sort(x, axis=-1)[..., ::-1]
    k_eff = jnp.clip(top_k, 1, v)
    kth = jnp.take_along_axis(
        desc, jnp.broadcast_to(k_eff - 1, x.shape[:-1])[..., None],
        axis=-1)
    k_on = (top_k > 0) & (top_k < v)
    x = jnp.where(k_on[..., None] & (x < kth), NEG_INF, x)
    # top-p: smallest prefix of the (post-top-k) sorted distribution
    # whose mass reaches p; a token is kept iff the mass BEFORE it is
    # still below p, so the head token always survives
    probs = jax.nn.softmax(x, axis=-1)
    order = jnp.argsort(-probs, axis=-1)
    ps = jnp.take_along_axis(probs, order, axis=-1)
    cum = jnp.cumsum(ps, axis=-1)
    p_on = (top_p > 0) & (top_p < 1.0)
    keep_sorted = ((cum - ps) < jnp.clip(top_p, 0.0, 1.0)[..., None]) \
        | ~p_on[..., None]
    inv = jnp.argsort(order, axis=-1)
    keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
    return jnp.where(keep, x, NEG_INF)


def _split_rows(keys):
    """Split every row's key: ``(B, 2)`` uint32 -> (advanced keys,
    subkeys), both ``(B, 2)``."""
    nk = jax.vmap(jax.random.split)(jnp.asarray(keys, jnp.uint32))
    return nk[:, 0], nk[:, 1]


def sample_tokens(keys, logits, temperature, top_k, top_p):
    """One sampling step over a row batch: warp ``logits`` (B, V) with
    each row's knobs and draw one token per row with its own subkey.
    Greedy rows (``temperature <= 0``) take ``argmax`` of the RAW
    logits instead (bit-equal to host-side greedy). Returns
    ``(tokens (B,) int32, advanced keys (B, 2))`` — thread the keys
    into the next call."""
    greedy = jnp.asarray(temperature, jnp.float32) <= 0
    w = warp_logits(logits, temperature, top_k, top_p)
    new_keys, sub = _split_rows(keys)
    sampled = jax.vmap(jax.random.categorical)(sub, w)
    tok = jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled)
    return tok.astype(jnp.int32), new_keys


def sample_with_probs(keys, logits, temperature, top_k, top_p):
    """``sample_tokens`` that also returns the full WARPED probability
    rows (B, V) the tokens were drawn from — the draft-model step of
    speculative decoding, whose ``q`` distribution the accept rule
    needs (both the proposed token's probability and the full residual
    ``max(p - q, 0)``). Greedy rows' probabilities are returned but
    unused (the greedy accept rule compares argmaxes)."""
    greedy = jnp.asarray(temperature, jnp.float32) <= 0
    w = warp_logits(logits, temperature, top_k, top_p)
    probs = jax.nn.softmax(w, axis=-1)
    new_keys, sub = _split_rows(keys)
    sampled = jax.vmap(jax.random.categorical)(sub, w)
    tok = jnp.where(greedy, jnp.argmax(logits, axis=-1), sampled)
    return tok.astype(jnp.int32), probs, new_keys


def greedy_accept(target_logits, draft_tokens):
    """The GREEDY accept rule alone: accept draft token ``d_{j+1}``
    while it equals the target argmax ``t_j``, commit the target's
    token at the cut. Returns ``(commit (B, K+1) int32, n_commit
    (B,) int32)`` — the committed stream is exactly non-speculative
    greedy decode's. This is ``speculative_accept`` restricted to
    ``temperature <= 0`` rows, WITHOUT the stochastic machinery (the
    sorts and the categorical draws cost more than the whole verify
    matmul at small models — an all-greedy engine iteration must not
    pay for them)."""
    b, k1, _v = target_logits.shape
    k = k1 - 1
    draft_tokens = jnp.asarray(draft_tokens, jnp.int32)
    tgt = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)
    acc = (draft_tokens == tgt[:, :k]).astype(jnp.int32)
    n_acc = jnp.cumprod(acc, axis=-1).sum(axis=-1)
    cut = jnp.take_along_axis(tgt, n_acc[:, None], axis=1)[:, 0]
    j = jnp.arange(k1, dtype=jnp.int32)[None, :]
    d_pad = jnp.concatenate(
        [draft_tokens, jnp.zeros((b, 1), jnp.int32)], axis=1)
    commit = jnp.where(j < n_acc[:, None], d_pad,
                       jnp.where(j == n_acc[:, None], cut[:, None], 0))
    return commit.astype(jnp.int32), (n_acc + 1).astype(jnp.int32)


def speculative_accept(keys, target_logits, draft_tokens, draft_probs,
                       temperature, top_k, top_p):
    """The speculative-decoding accept rule over one verify step.

    ``target_logits`` (B, K+1, V) are the target model's logits at the
    K+1 verified positions (position j predicts the token AFTER the
    j-th verified input, i.e. after ``[last, d_1 .. d_j]``);
    ``draft_tokens`` (B, K) are the draft's proposals ``d_1 .. d_K``;
    ``draft_probs`` (B, K, V) the WARPED draft distributions each was
    drawn from (``sample_with_probs``). Knobs are per-row ``(B,)``.

    Returns ``(commit (B, K+1) int32, n_commit (B,) int32, advanced
    keys)``: row b commits ``commit[b, :n_commit[b]]`` — the accepted
    draft prefix plus exactly one target-derived token (the argmax /
    residual sample at the first rejection, or the bonus token after a
    full accept). ``1 <= n_commit <= K+1`` always: every verify step
    commits at least the token non-speculative decode would have."""
    b, k1, v = target_logits.shape
    k = k1 - 1
    temperature = jnp.asarray(temperature, jnp.float32)
    greedy = temperature <= 0
    draft_tokens = jnp.asarray(draft_tokens, jnp.int32)

    # greedy rule: accept while draft argmax == target argmax, then
    # take the target's token — exactly non-speculative greedy output
    tgt = jnp.argmax(target_logits, axis=-1).astype(jnp.int32)  # (B,K1)
    acc_g = draft_tokens == tgt[:, :k]

    # stochastic rule on the warped target distribution
    w = warp_logits(target_logits, temperature[:, None],
                    jnp.asarray(top_k, jnp.int32)[:, None],
                    jnp.asarray(top_p, jnp.float32)[:, None])
    p = jax.nn.softmax(w, axis=-1)                               # (B,K1,V)
    new_keys, sub = _split_rows(keys)
    u = jax.vmap(
        lambda kk: jax.random.uniform(jax.random.fold_in(kk, 0), (k,))
    )(sub) if k else jnp.zeros((b, 0), jnp.float32)
    p_d = jnp.take_along_axis(p[:, :k], draft_tokens[..., None],
                              axis=-1)[..., 0]                   # (B,K)
    q_d = jnp.take_along_axis(draft_probs, draft_tokens[..., None],
                              axis=-1)[..., 0]
    acc_s = u <= p_d / jnp.maximum(q_d, 1e-20)   # u < min(1, p/q)

    acc = jnp.where(greedy[:, None], acc_g, acc_s)
    n_acc = jnp.cumprod(acc.astype(jnp.int32), axis=-1).sum(axis=-1)

    # the token at the cut position: target argmax (greedy) or a
    # sample from the residual norm(max(p - q, 0)); after a full
    # accept the "residual" at the bonus position is p itself (q = 0)
    idx = jnp.broadcast_to(n_acc[:, None, None], (b, 1, v))
    p_cut = jnp.take_along_axis(p, idx, axis=1)[:, 0]            # (B,V)
    q_pad = jnp.concatenate(
        [draft_probs, jnp.zeros((b, 1, v), draft_probs.dtype)], axis=1)
    q_cut = jnp.take_along_axis(q_pad, idx, axis=1)[:, 0]
    resid = jnp.maximum(p_cut - q_cut, 0.0)
    rs = resid.sum(axis=-1, keepdims=True)
    # a numerically-empty residual (p == q to the last ulp) means the
    # rejection had probability ~0 — fall back to p rather than NaN
    dist = jnp.where(rs > 1e-20, resid / jnp.maximum(rs, 1e-20), p_cut)
    cut_s = jax.vmap(
        lambda kk, d: jax.random.categorical(
            jax.random.fold_in(kk, 1),
            jnp.log(jnp.maximum(d, 1e-38))))(sub, dist)
    cut_g = jnp.take_along_axis(tgt, n_acc[:, None], axis=1)[:, 0]
    cut = jnp.where(greedy, cut_g, cut_s).astype(jnp.int32)

    j = jnp.arange(k1, dtype=jnp.int32)[None, :]
    d_pad = jnp.concatenate(
        [draft_tokens, jnp.zeros((b, 1), jnp.int32)], axis=1)
    commit = jnp.where(j < n_acc[:, None], d_pad,
                       jnp.where(j == n_acc[:, None], cut[:, None], 0))
    return (commit.astype(jnp.int32), (n_acc + 1).astype(jnp.int32),
            new_keys)

"""Detection-family kernels: box IoU/NMS/codec, anchor matching.

TPU-native equivalents of the reference's detection contrib ops
(src/operator/contrib/bounding_box.cc, multibox_detection.cc,
multibox_target.cc, bipartite_matching.cc). All kernels are pure jax
with static shapes and `lax.fori_loop` for the sequential suppress /
match phases, so they jit and batch cleanly on TPU.

Box formats: 'corner' = (xmin, ymin, xmax, ymax); 'center' =
(cx, cy, w, h) — the reference's in_format/out_format convention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def corner_to_center(b):
    xmin, ymin, xmax, ymax = jnp.split(b, 4, axis=-1)
    return jnp.concatenate([(xmin + xmax) / 2, (ymin + ymax) / 2,
                            xmax - xmin, ymax - ymin], -1)


def center_to_corner(b):
    cx, cy, w, h = jnp.split(b, 4, axis=-1)
    return jnp.concatenate([cx - w / 2, cy - h / 2,
                            cx + w / 2, cy + h / 2], -1)


def _area(b):  # corner format
    return jnp.maximum(b[..., 2] - b[..., 0], 0) * \
        jnp.maximum(b[..., 3] - b[..., 1], 0)


def box_iou(lhs, rhs, fmt="corner"):
    """Pairwise IoU: lhs (..., N, 4), rhs (..., M, 4) -> (..., N, M)."""
    if fmt == "center":
        lhs, rhs = center_to_corner(lhs), center_to_corner(rhs)
    lt = jnp.maximum(lhs[..., :, None, :2], rhs[..., None, :, :2])
    rb = jnp.minimum(lhs[..., :, None, 2:4], rhs[..., None, :, 2:4])
    wh = jnp.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = _area(lhs)[..., :, None] + _area(rhs)[..., None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def box_encode(samples, matches, anchors, refs, means, stds):
    """SSD regression targets (parity: bounding_box.cc BoxEncode).

    samples (B,N) in {0:ignore,-1:negative,1:positive}, matches (B,N)
    GT index per anchor, anchors (B,N,4) corner, refs (B,M,4) corner
    GT boxes. Returns (targets (B,N,4), masks (B,N,4))."""
    ref = jnp.take_along_axis(refs, matches[..., None].astype(jnp.int32)
                              .clip(0), axis=1)
    a_c = corner_to_center(anchors)
    g_c = corner_to_center(ref)
    means = jnp.asarray(means, a_c.dtype)
    stds = jnp.asarray(stds, a_c.dtype)
    t_xy = (g_c[..., :2] - a_c[..., :2]) / jnp.maximum(a_c[..., 2:], 1e-12)
    t_wh = jnp.log(jnp.maximum(g_c[..., 2:], 1e-12)
                   / jnp.maximum(a_c[..., 2:], 1e-12))
    t = (jnp.concatenate([t_xy, t_wh], -1) - means) / stds
    mask = jnp.broadcast_to((samples > 0.5)[..., None],
                            t.shape).astype(t.dtype)
    return t * mask, mask


def box_decode(data, anchors, stds=(1.0, 1.0, 1.0, 1.0),
               means=(0.0, 0.0, 0.0, 0.0), clip=-1.0, fmt="corner"):
    """Invert box_encode: data (B,N,4) deltas, anchors (1,N,4)."""
    a = anchors if fmt == "center" else corner_to_center(anchors)
    stds = jnp.asarray(stds, data.dtype)
    means = jnp.asarray(means, data.dtype)
    d = data * stds + means
    xy = d[..., :2] * a[..., 2:] + a[..., :2]
    wh = jnp.exp(d[..., 2:]) * a[..., 2:]
    out = center_to_corner(jnp.concatenate([xy, wh], -1))
    if clip > 0:
        out = jnp.clip(out, 0.0, clip)
    return out


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner"):
    """Greedy NMS (parity: bounding_box.cc BoxNMS semantics).

    data (..., N, K): rows with score < valid_thresh are invalid;
    survivors sorted by score desc; a row is suppressed when its IoU
    with a higher-scored kept row of the same class (or any class when
    force_suppress) exceeds overlap_thresh. Suppressed/invalid rows
    have ALL fields set to -1. Output keeps the input shape with kept
    rows compacted to the front (reference behavior)."""
    orig_shape = data.shape
    flat = data.reshape((-1,) + orig_shape[-2:])

    def one(batch):
        n = batch.shape[0]
        score = batch[:, score_index]
        boxes = lax.dynamic_slice_in_dim(batch, coord_start, 4, axis=1)
        if in_format == "center":
            boxes = center_to_corner(boxes)
        valid = score > valid_thresh
        if id_index >= 0 and background_id >= 0:
            valid &= batch[:, id_index] != background_id
        order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
        sboxes = boxes[order]
        svalid = valid[order]
        if topk > 0:
            svalid &= jnp.arange(n) < topk
        iou = box_iou(sboxes, sboxes)
        if id_index >= 0 and not force_suppress:
            cls = batch[order, id_index]
            same = cls[:, None] == cls[None, :]
            iou = jnp.where(same, iou, 0.0)

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & keep[i] & \
                (jnp.arange(n) > i)
            return keep & ~sup

        keep = lax.fori_loop(0, n, body, svalid)
        kept_sorted = batch[order]
        kept_sorted = jnp.where(keep[:, None], kept_sorted, -1.0)
        # compact kept rows to the front (stable on score order)
        rank = jnp.argsort(~keep, stable=True)
        return kept_sorted[rank]

    out = jax.vmap(one)(flat)
    return out.reshape(orig_shape)


def bipartite_matching(score, threshold, is_ascend=False, topk=-1):
    """Greedy bipartite matching (parity: bipartite_matching.cc).

    score (..., N, M). Returns (row_match (..., N), col_match (..., M))
    where row_match[i] = matched column or -1, col_match[j] = matched
    row or -1. Greedy: repeatedly take the globally best unmatched
    pair passing `threshold`."""
    orig = score.shape
    flat = score.reshape((-1,) + orig[-2:])
    n, m = orig[-2], orig[-1]
    sign = 1.0 if is_ascend else -1.0
    iters = min(n, m) if topk <= 0 else min(topk, min(n, m))

    def one(s):
        key = s * sign  # minimize key

        def body(_, st):
            key_st, row, col = st
            idx = jnp.argmin(key_st)
            i, j = idx // m, idx % m
            ok = (s[i, j] >= threshold) if not is_ascend else \
                (s[i, j] <= threshold)
            row = jnp.where(ok, row.at[i].set(j), row)
            col = jnp.where(ok, col.at[j].set(i), col)
            key_st = jnp.where(ok, key_st.at[i, :].set(jnp.inf)
                               .at[:, j].set(jnp.inf), key_st)
            key_st = jnp.where(ok, key_st, key_st.at[i, j].set(jnp.inf))
            return key_st, row, col

        row0 = jnp.full((n,), -1, jnp.int32)
        col0 = jnp.full((m,), -1, jnp.int32)
        _, row, col = lax.fori_loop(0, iters, body, (key, row0, col0))
        return row, col

    rows, cols = jax.vmap(one)(flat)
    return (rows.reshape(orig[:-1]),
            cols.reshape(orig[:-2] + (m,)))


def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5,
                    minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training targets (parity: multibox_target.cc).

    anchor (1,A,4) corner; label (B,N,5) rows [cls, xmin,ymin,xmax,
    ymax] padded with cls<0; cls_pred (B,C,A) (used for hard negative
    mining when negative_mining_ratio > 0). Returns
    (box_target (B,A*4), box_mask (B,A*4), cls_target (B,A))."""
    a = anchor[0]                            # (A, 4)
    A = a.shape[0]

    def one(lab, cpred):
        gt_valid = lab[:, 0] >= 0            # (N,)
        gt_boxes = lab[:, 1:5]
        iou = box_iou(a, gt_boxes)           # (A, N)
        iou = jnp.where(gt_valid[None, :], iou, -1.0)
        # stage 1: each GT grabs its best anchor (greedy bipartite)
        row, col = bipartite_matching(iou, 1e-12)
        matches = row                         # (A,) GT idx or -1
        # stage 2: remaining anchors take their best GT above thresh
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        stage2 = (matches < 0) & (best_iou >= overlap_threshold)
        matches = jnp.where(stage2, best_gt, matches)
        positive = matches >= 0
        samples = jnp.where(positive, 1.0, -1.0)

        if negative_mining_ratio > 0:
            # hard negatives: highest max-class-prob anchors whose best
            # IoU is below the mining threshold
            max_pos = jnp.sum(positive)
            quota = jnp.maximum(
                (negative_mining_ratio * max_pos).astype(jnp.int32),
                minimum_negative_samples)
            neg_ok = (~positive) & (best_iou < negative_mining_thresh)
            hardness = jnp.where(neg_ok, jnp.max(cpred, axis=0), -jnp.inf)
            order = jnp.argsort(-hardness)
            rank = jnp.empty_like(order).at[order].set(jnp.arange(A))
            chosen_neg = neg_ok & (rank < quota)
            samples = jnp.where(positive, 1.0,
                                jnp.where(chosen_neg, -1.0, 0.0))

        targets, masks = box_encode(
            samples[None], matches[None], a[None], gt_boxes[None],
            (0.0, 0.0, 0.0, 0.0), variances)
        gt_cls = jnp.take(lab[:, 0], matches.clip(0)) + 1.0
        cls_t = jnp.where(positive, gt_cls,
                          jnp.where(samples < -0.5, 0.0,
                                    float(ignore_label)))
        return targets[0].reshape(-1), masks[0].reshape(-1), cls_t

    bt, bm, ct = jax.vmap(one)(label, cls_pred)
    return bt, bm, ct


def multibox_detection(cls_prob, loc_pred, anchor, clip=True,
                       threshold=0.01, background_id=0,
                       nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD inference: decode + per-class NMS (multibox_detection.cc).

    cls_prob (B,C,A), loc_pred (B,A*4), anchor (1,A,4) corner.
    Returns (B, A, 6): [class_id, score, xmin, ymin, xmax, ymax],
    suppressed rows = -1."""
    B, C, A = cls_prob.shape
    deltas = loc_pred.reshape(B, A, 4)
    boxes = box_decode(deltas, corner_to_center(anchor),
                       stds=variances, fmt="center",
                       clip=1.0 if clip else -1.0)
    # best non-background class per anchor
    fg = jnp.concatenate([cls_prob[:, :background_id],
                          cls_prob[:, background_id + 1:]], axis=1) \
        if 0 <= background_id < C else cls_prob
    cls_id = jnp.argmax(fg, axis=1).astype(cls_prob.dtype)   # (B, A)
    # map back around the removed background row
    if 0 <= background_id < C:
        cls_id = jnp.where(cls_id >= background_id, cls_id + 1, cls_id)
    score = jnp.take_along_axis(
        cls_prob, cls_id[:, None].astype(jnp.int32), axis=1)[:, 0]
    keep = score > threshold
    # output ids index the non-background classes: only classes ABOVE
    # the background row shift down by one
    if 0 <= background_id < C:
        fg_id = jnp.where(cls_id > background_id, cls_id - 1, cls_id)
    else:
        fg_id = cls_id
    out_id = jnp.where(keep, fg_id, -1.0)
    out = jnp.concatenate([out_id[..., None], score[..., None], boxes],
                          -1)
    out = jnp.where(keep[..., None], out, -1.0)
    return box_nms(out, overlap_thresh=nms_threshold, valid_thresh=0.0,
                   topk=nms_topk, coord_start=2, score_index=1,
                   id_index=0, background_id=-1,
                   force_suppress=force_suppress)


def roi_align(data, rois, pooled_size, spatial_scale=1.0,
              sample_ratio=-1, position_sensitive=False, aligned=False):
    """ROIAlign (parity: src/operator/contrib/roi_align.cc — Mask R-CNN
    bilinear-sampled ROI pooling, avg mode).

    data (B, C, H, W); rois (N, 5) rows [batch_idx, x1, y1, x2, y2] in
    image coords. Returns (N, C, ph, pw) — or (N, C/(ph*pw), ph, pw)
    when position_sensitive. sample_ratio <= 0 picks an adaptive
    ceil(roi_extent / pooled) grid per the reference, but a static one
    (2) is used under jit when extents are data-dependent."""
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else pooled_size
    sr = int(sample_ratio) if sample_ratio and sample_ratio > 0 else 2

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        off = 0.5 if aligned else 0.0
        x1, y1, x2, y2 = (roi[1] * spatial_scale - off,
                          roi[2] * spatial_scale - off,
                          roi[3] * spatial_scale - off,
                          roi[4] * spatial_scale - off)
        rw = x2 - x1 if aligned else jnp.maximum(x2 - x1, 1.0)
        rh = y2 - y1 if aligned else jnp.maximum(y2 - y1, 1.0)
        bw, bh = rw / pw, rh / ph
        # sr x sr sample grid inside each bin
        iy = (jnp.arange(sr) + 0.5) / sr
        ix = (jnp.arange(sr) + 0.5) / sr
        by = y1 + (jnp.arange(ph)[:, None] + iy[None, :]) * bh
        bx = x1 + (jnp.arange(pw)[:, None] + ix[None, :]) * bw
        ys = by.reshape(-1)                    # (ph*sr,)
        xs = bx.reshape(-1)                    # (pw*sr,)
        img = data[bidx]                       # (C, H, W)
        H, W = img.shape[1], img.shape[2]
        y = jnp.clip(ys, 0.0, H - 1.0)
        x = jnp.clip(xs, 0.0, W - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, H - 1)
        x1i = jnp.minimum(x0 + 1, W - 1)
        wy = y - y0
        wx = x - x0
        # bilinear sample on the full (ys, xs) grid
        g00 = img[:, y0[:, None], x0[None, :]]
        g01 = img[:, y0[:, None], x1i[None, :]]
        g10 = img[:, y1i[:, None], x0[None, :]]
        g11 = img[:, y1i[:, None], x1i[None, :]]
        top = g00 * (1 - wx)[None, None, :] + g01 * wx[None, None, :]
        bot = g10 * (1 - wx)[None, None, :] + g11 * wx[None, None, :]
        smp = top * (1 - wy)[None, :, None] + bot * wy[None, :, None]
        C = img.shape[0]
        smp = smp.reshape(C, ph, sr, pw, sr)
        pooled = smp.mean(axis=(2, 4))         # (C, ph, pw)
        if position_sensitive:
            c = C // (ph * pw)
            pooled = pooled.reshape(c, ph, pw, ph, pw)
            pooled = pooled[:, jnp.arange(ph)[:, None],
                            jnp.arange(pw)[None, :],
                            jnp.arange(ph)[:, None],
                            jnp.arange(pw)[None, :]]
        return pooled

    return jax.vmap(one)(rois)


def rroi_align(data, rois, pooled_size, spatial_scale=1.0,
               sampling_ratio=-1):
    """Rotated ROIAlign (parity: src/operator/contrib/rroi_align.cc).

    rois (N, 6): [batch_idx, cx, cy, w, h, theta_degrees]; the sample
    grid lives in the ROI's local frame and rotates by theta around
    (cx, cy): x = xx·cosθ + yy·sinθ + cx, y = yy·cosθ − xx·sinθ + cy
    (rroi_align.cc:70-72). Samples past the −1/size apron contribute
    0; in-apron coordinates clamp to the border."""
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else pooled_size
    sr = int(sampling_ratio) if sampling_ratio and sampling_ratio > 0 \
        else 2

    def one(roi):
        bidx = roi[0].astype(jnp.int32)
        cx = roi[1] * spatial_scale
        cy = roi[2] * spatial_scale
        rw = jnp.maximum(roi[3] * spatial_scale, 1.0)
        rh = jnp.maximum(roi[4] * spatial_scale, 1.0)
        theta = roi[5] * (jnp.pi / 180.0)
        bh, bw = rh / ph, rw / pw
        # local-frame sample coords (relative to the ROI center)
        yy = (-rh / 2.0 + (jnp.arange(ph)[:, None] * bh)
              + (jnp.arange(sr)[None, :] + 0.5) * bh / sr).reshape(-1)
        xx = (-rw / 2.0 + (jnp.arange(pw)[:, None] * bw)
              + (jnp.arange(sr)[None, :] + 0.5) * bw / sr).reshape(-1)
        ct, st = jnp.cos(theta), jnp.sin(theta)
        xs = xx[None, :] * ct + yy[:, None] * st + cx   # (phs, pws)
        ys = yy[:, None] * ct - xx[None, :] * st + cy
        img = data[bidx]
        H, W = img.shape[1], img.shape[2]
        inside = (ys >= -1.0) & (ys <= H) & (xs >= -1.0) & (xs <= W)
        y = jnp.clip(ys, 0.0, H - 1.0)
        x = jnp.clip(xs, 0.0, W - 1.0)
        y0 = jnp.floor(y).astype(jnp.int32)
        x0 = jnp.floor(x).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, H - 1)
        x1 = jnp.minimum(x0 + 1, W - 1)
        wy = y - y0
        wx = x - x0
        g00 = img[:, y0, x0]
        g01 = img[:, y0, x1]
        g10 = img[:, y1, x0]
        g11 = img[:, y1, x1]
        smp = (g00 * (1 - wy) * (1 - wx) + g01 * (1 - wy) * wx +
               g10 * wy * (1 - wx) + g11 * wy * wx)
        smp = jnp.where(inside[None], smp, 0.0)
        C = img.shape[0]
        smp = smp.reshape(C, ph, sr, pw, sr)
        return smp.mean(axis=(2, 4))

    return jax.vmap(one)(rois)


def _rpn_anchors(h, w, feature_stride, scales, ratios):
    """RPN base anchors shifted over the feature grid (proposal-inl.h
    GenerateAnchors): returns (h*w*A, 4) corner boxes in image
    coords."""
    base = feature_stride - 1.0
    cx = cy = base / 2.0
    anchors = []
    for r in ratios:
        size = feature_stride * feature_stride
        size_r = size / r
        ws = round(float(jnp.sqrt(jnp.asarray(size_r))))
        hs = round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            anchors.append([cx - (wss - 1) / 2.0, cy - (hss - 1) / 2.0,
                            cx + (wss - 1) / 2.0, cy + (hss - 1) / 2.0])
    A = len(anchors)
    anc = jnp.asarray(anchors, jnp.float32)           # (A, 4)
    sx = jnp.arange(w) * feature_stride
    sy = jnp.arange(h) * feature_stride
    shift = jnp.stack([
        jnp.tile(sx[None, :], (h, 1)).reshape(-1),
        jnp.tile(sy[:, None], (1, w)).reshape(-1),
    ], -1)                                            # (h*w, 2) x,y
    shift4 = jnp.concatenate([shift, shift], -1)      # (h*w, 4)
    return (anc[None, :, :] + shift4[:, None, :]).reshape(-1, 4), A


def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
             feature_stride=16):
    """RPN proposal generation (parity:
    src/operator/contrib/proposal.cc — decode anchor deltas, clip to
    the image, drop boxes under rpn_min_size, keep the pre-nms top-K
    by objectness, NMS at `threshold`, emit rpn_post_nms_top_n rows
    [batch_idx, x1, y1, x2, y2]).

    cls_prob (B, 2A, h, w) — objectness scores in the second half of
    channel pairs; bbox_pred (B, 4A, h, w); im_info (B, 3)
    [height, width, scale]."""
    B, _, h, w = cls_prob.shape
    anchors, A = _rpn_anchors(h, w, feature_stride,
                              [float(s) for s in scales],
                              [float(r) for r in ratios])
    N = anchors.shape[0]

    def one(score_map, delta_map, info):
        # foreground scores: channels [A:2A]; layout (A, h, w)
        scores = score_map[A:].transpose(1, 2, 0).reshape(-1)  # hw*A
        deltas = delta_map.transpose(1, 2, 0).reshape(-1, 4)
        ih, iw = info[0], info[1]
        # decode (center-form deltas, the Faster-RCNN convention)
        aw = anchors[:, 2] - anchors[:, 0] + 1.0
        ah = anchors[:, 3] - anchors[:, 1] + 1.0
        acx = anchors[:, 0] + 0.5 * (aw - 1.0)
        acy = anchors[:, 1] + 0.5 * (ah - 1.0)
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        bw = jnp.exp(deltas[:, 2]) * aw
        bh = jnp.exp(deltas[:, 3]) * ah
        x1 = jnp.clip(cx - 0.5 * (bw - 1.0), 0, iw - 1.0)
        y1 = jnp.clip(cy - 0.5 * (bh - 1.0), 0, ih - 1.0)
        x2 = jnp.clip(cx + 0.5 * (bw - 1.0), 0, iw - 1.0)
        y2 = jnp.clip(cy + 0.5 * (bh - 1.0), 0, ih - 1.0)
        min_size = rpn_min_size * info[2]
        valid = ((x2 - x1 + 1.0) >= min_size) & \
            ((y2 - y1 + 1.0) >= min_size)
        scores_v = jnp.where(valid, scores, -jnp.inf)
        pre = min(rpn_pre_nms_top_n, N)
        top_scores, order = jax.lax.top_k(scores_v, pre)
        rows = jnp.stack([jnp.zeros_like(top_scores), top_scores,
                          x1[order], y1[order], x2[order], y2[order]],
                         -1)
        kept = box_nms(rows[None], overlap_thresh=threshold,
                       valid_thresh=-jnp.inf, topk=rpn_post_nms_top_n,
                       coord_start=2, score_index=1)[0]
        out = kept[:rpn_post_nms_top_n, 2:6]
        return out

    boxes = jax.vmap(one)(cls_prob, bbox_pred, im_info)   # (B, P, 4)
    bidx = jnp.broadcast_to(
        jnp.arange(B, dtype=boxes.dtype)[:, None, None],
        (B, rpn_post_nms_top_n, 1))
    return jnp.concatenate([bidx, boxes], -1).reshape(-1, 5)


def deformable_psroi_pooling(data, rois, trans, spatial_scale,
                             output_dim, group_size, pooled_size,
                             part_size=0, sample_per_part=1,
                             trans_std=0.0, no_trans=False):
    """Deformable position-sensitive ROI pooling (parity:
    src/operator/contrib/deformable_psroi_pooling.cc:80-146).

    data (B, C, H, W) with C = output_dim * group_size²; rois (N, 5)
    [batch, x1, y1, x2, y2]; trans (N, 2*num_classes, P, P) learned
    per-part offsets (ignored when no_trans). Returns
    (N, output_dim, pooled, pooled); empty bins read 0."""
    P = int(part_size) or int(pooled_size)
    ps = int(pooled_size)
    gs = int(group_size)
    od = int(output_dim)
    spp = int(sample_per_part)
    B, C, H, W = data.shape
    num_classes = 1 if no_trans else trans.shape[1] // 2
    ch_each = max(od // num_classes, 1)

    # static per-bin lookups
    bin_i = jnp.arange(ps)
    gh = jnp.clip((bin_i * gs) // ps, 0, gs - 1)          # (ps,)
    part = jnp.clip((bin_i * P) // ps, 0, P - 1)           # (ps,)
    cls = jnp.clip(jnp.arange(od) // ch_each, 0, num_classes - 1)
    c_map = (jnp.arange(od)[:, None, None] * gs +
             gh[None, :, None]) * gs + gh[None, None, :]   # (od,ps,ps)

    def one(roi, tr):
        bidx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale - 0.5
        y1 = jnp.round(roi[2]) * spatial_scale - 0.5
        x2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
        y2 = (jnp.round(roi[4]) + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bh, bw = rh / ps, rw / ps
        sub_h, sub_w = bh / spp, bw / spp
        if no_trans:
            tx = jnp.zeros((od, ps, ps), data.dtype)
            ty = jnp.zeros((od, ps, ps), data.dtype)
        else:
            # trans[(cls*2), part_h, part_w] per (ctop, bin_y, bin_x)
            tx = tr[cls * 2][:, part, :][:, :, part] * trans_std
            ty = tr[cls * 2 + 1][:, part, :][:, :, part] * trans_std
        wstart = bin_i[None, None, :] * bw + x1 + tx * rw  # (od,ps,ps)
        hstart = bin_i[None, :, None] * bh + y1 + ty * rh
        img = data[bidx]
        acc = jnp.zeros((od, ps, ps), data.dtype)
        cnt = jnp.zeros((od, ps, ps), data.dtype)
        for ih in range(spp):
            for iw in range(spp):
                w = wstart + iw * sub_w
                h = hstart + ih * sub_h
                ok = (w >= -0.5) & (w <= W - 0.5) & \
                    (h >= -0.5) & (h <= H - 0.5)
                wc = jnp.clip(w, 0.0, W - 1.0)
                hc = jnp.clip(h, 0.0, H - 1.0)
                x0 = jnp.floor(wc).astype(jnp.int32)
                y0 = jnp.floor(hc).astype(jnp.int32)
                x1i = jnp.minimum(x0 + 1, W - 1)
                y1i = jnp.minimum(y0 + 1, H - 1)
                fx = wc - x0
                fy = hc - y0
                v = (img[c_map, y0, x0] * (1 - fy) * (1 - fx) +
                     img[c_map, y0, x1i] * (1 - fy) * fx +
                     img[c_map, y1i, x0] * fy * (1 - fx) +
                     img[c_map, y1i, x1i] * fy * fx)
                acc = acc + jnp.where(ok, v, 0.0)
                cnt = cnt + ok.astype(data.dtype)
        return jnp.where(cnt > 0, acc / jnp.maximum(cnt, 1.0), 0.0)

    return jax.vmap(one)(rois, trans if not no_trans else
                         jnp.zeros((rois.shape[0], 2, P, P),
                                   data.dtype))

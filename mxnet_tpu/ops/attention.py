"""Attention kernels: Pallas flash attention + ring attention (sp).

The reference has NO long-context support (SURVEY.md §5: "ring
attention, context parallel — absent upstream"); these are first-class
here because they shape the core design on TPU:

- `flash_attention` — blockwise online-softmax attention. On TPU the
  forward runs as a Pallas kernel (one q-block per grid step, KV
  streamed through VMEM, fp32 accumulators — the MXU-friendly
  formulation); backward recomputes attention blockwise (flash-style
  rematerialization: O(S) memory, no S×S residuals).
- `ring_attention` — sequence parallelism over the 'sp' mesh axis:
  each device holds a sequence shard of Q/K/V; KV shards rotate
  around the ring via `lax.ppermute` while every device accumulates
  online-softmax partial results. Collective-permute overlaps with
  the next block's compute under XLA's latency-hiding scheduler, so
  the ring rides the ICI torus at full bandwidth.
- `decode_attention` — the autoregressive fast path: one query per
  sequence against a preallocated KV cache buffer, masked to each
  row's valid length (serving/generate.py slot batches). jnp path
  everywhere; Pallas TPU kernel (scalar-prefetched lengths, KV
  streamed through VMEM) behind the same `_use_pallas()` gate.

All shapes are (batch, heads, seq, head_dim). `kv_len` arguments mean
"only the first kv_len entries of the key/value buffer are real" —
the cache-backed convention: buffers are allocated at S_max, filled
left-to-right, and the padded tail must never contribute attention
mass. The causal offset is then end-aligned against the VALID prefix
(`offset = kv_len - seq_q`), so prefill over a cache buffer and
decode steps against the same buffer agree with `mha_reference` run
on the sliced cache.
"""
from __future__ import annotations

import contextlib
import functools
import math
import threading

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# blockwise reference (differentiable, fuses well under XLA)
# ---------------------------------------------------------------------------
def _attn_block(q, k, v, m_prev, l_prev, acc_prev, scale, mask=None):
    """One online-softmax accumulation step.

    q: (..., Sq, D); k/v: (..., Sk, D); m/l: (..., Sq); acc (..., Sq, D).
    """
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])
    alpha = jnp.exp(m_prev - m_new)
    l_new = alpha * l_prev + p.sum(axis=-1)
    acc_new = acc_prev * alpha[..., None] + jnp.einsum(
        "...qk,...kd->...qd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_new, l_new, acc_new


def mha_reference(q, k, v, causal=False, scale=None):
    """Plain attention (for tests and tiny sequences)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col <= row + (sk - sq), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------
def _causal_valid(row, col, offset):
    """End-aligned causal convention (matches mha_reference):
    query row r may attend key col c iff c <= r + offset, offset =
    seq_k - seq_q (so the LAST query sees the whole key)."""
    return col <= row + offset


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale,
                      causal, block_k, seq_k_padded, kv_len, offset):
    """One (batch*head, q-block) grid step; stream KV through VMEM."""
    import jax.experimental.pallas as pl
    q = q_ref[0].astype(jnp.float32) * scale          # (bq, d)
    bq, d = q.shape
    nk = seq_k_padded // block_k
    q_block = pl.program_id(1)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]   # (bk, d)
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (bq, bk)
        row = lax.broadcasted_iota(jnp.int32, (bq, block_k), 0) \
            + q_block * bq
        col = lax.broadcasted_iota(jnp.int32, (bq, block_k), 1) \
            + j * block_k
        valid = col < kv_len                           # padding mask
        if causal:
            valid = valid & _causal_valid(row, col, offset)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = lax.fori_loop(0, nk, body, (m0, l0, acc0))
    l_safe = jnp.where(l > 0, l, 1.0)                  # padded q rows
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(l_safe)


def _pad_seq(x, block):
    s = x.shape[2]
    pad = (-s) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))


def flash_attention_pallas(q, k, v, causal=False, scale=None,
                           block_q=128, block_k=128, interpret=False,
                           kv_len=None):
    """Pallas forward (see pallas_guide.md patterns); any seq length
    (inputs are block-padded, padding masked). ``kv_len`` marks the
    valid key prefix of a longer (cache) buffer — keys at or beyond
    it are masked and the causal diagonal is end-aligned against the
    valid prefix, not the buffer end. Returns (out, lse)."""
    import jax.experimental.pallas as pl

    b, h, sq, d = q.shape
    sk = k.shape[2]
    kv_len = sk if kv_len is None else int(kv_len)
    if not 0 < kv_len <= sk:
        raise ValueError(f"kv_len={kv_len} out of range for key "
                         f"buffer of length {sk}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, max(sq, 1))
    block_k = min(block_k, max(sk, 1))
    qp, kp, vp = _pad_seq(q, block_q), _pad_seq(k, block_k), \
        _pad_seq(v, block_k)
    sqp, skp = qp.shape[2], kp.shape[2]
    qr = qp.reshape(b * h, sqp, d)
    kr = kp.reshape(b * h, skp, d)
    vr = vp.reshape(b * h, skp, d)

    kernel = functools.partial(
        _flash_fwd_kernel, scale=scale, causal=causal, block_k=block_k,
        seq_k_padded=skp, kv_len=kv_len, offset=kv_len - sq)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sqp // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, skp, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, skp, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q), lambda i, j: (i, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sqp, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sqp), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    return (out.reshape(b, h, sqp, d)[:, :, :sq],
            lse.reshape(b, h, sqp)[:, :, :sq])


# ---------------------------------------------------------------------------
# blockwise jnp forward (non-TPU path) — O(S·block) memory
# ---------------------------------------------------------------------------
def _blockwise_fwd(q, k, v, causal, scale, block=512, kv_len=None):
    sq, sk = q.shape[-2], k.shape[-2]
    kv_len = sk if kv_len is None else int(kv_len)
    offset = kv_len - sq
    kp, vp = _pad_seq(k, block), _pad_seq(v, block)
    nb = kp.shape[-2] // block

    def step(carry, j):
        m, l, acc = carry
        kj = lax.dynamic_slice_in_dim(kp, j * block, block, axis=-2)
        vj = lax.dynamic_slice_in_dim(vp, j * block, block, axis=-2)
        s = jnp.einsum("...qd,...kd->...qk", q, kj) \
            .astype(jnp.float32) * scale
        row = lax.broadcasted_iota(jnp.int32, (sq, block), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, block), 1) + j * block
        valid = col < kv_len
        if causal:
            valid = valid & _causal_valid(row, col, offset)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "...qk,...kd->...qd", p.astype(vj.dtype), vj
        ).astype(jnp.float32)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full(q.shape[:-1], NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1], jnp.float32)
    acc0 = jnp.zeros(q.shape, jnp.float32)
    (m, l, acc), _ = lax.scan(step, (m0, l0, acc0), jnp.arange(nb))
    l_safe = jnp.where(l > 0, l, 1.0)
    return (acc / l_safe[..., None]).astype(q.dtype), m + jnp.log(l_safe)


# ---------------------------------------------------------------------------
# public flash_attention with blockwise (O(S·block)) backward
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, scale=None, kv_len=None):
    """``kv_len`` (static int) marks the valid key prefix of a longer
    cache buffer: keys beyond it are masked out of the softmax and the
    causal diagonal end-aligns to the valid prefix (the last query row
    sees keys [0, kv_len))."""
    return _flash_fwd(q, k, v, causal, scale, kv_len)[0]


#: threads currently tracing under jnp_only() — the SPMD-serving
#: escape hatch (see below)
_JNP_ONLY = threading.local()


@contextlib.contextmanager
def jnp_only():
    """Force the jnp paths while tracing under this context.

    Tensor-parallel serving compiles the generation closures SPMD over
    the device mesh (params and KV sharded by heads); a ``pallas_call``
    inside such a program would need an explicit ``shard_map`` wrapping
    it per shard, which the decode kernels do not have — so a
    mesh-sharded engine traces its closures under this context and the
    kernels stay on the (numerically identical) jnp paths, partitioned
    by GSPMD like any other op. Scoped per thread (trace-time only):
    an unsharded engine tracing concurrently still takes Pallas."""
    prev = getattr(_JNP_ONLY, "on", False)
    _JNP_ONLY.on = True
    try:
        yield
    finally:
        _JNP_ONLY.on = prev


def _use_pallas():
    if getattr(_JNP_ONLY, "on", False):
        return False
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _flash_fwd(q, k, v, causal, scale, kv_len=None):
    # validate here (not only in the Pallas path) so the jnp fallback
    # rejects a bad kv_len too instead of attending zero-padded keys
    if kv_len is not None and not 0 < int(kv_len) <= k.shape[2]:
        raise ValueError(f"kv_len={kv_len} out of range for key "
                         f"buffer of length {k.shape[2]}")
    scale_v = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas():
        out, lse = flash_attention_pallas(q, k, v, causal=causal,
                                          scale=scale_v, kv_len=kv_len)
    else:
        out, lse = _blockwise_fwd(q, k, v, causal, scale_v,
                                  kv_len=kv_len)
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, scale, kv_len, res, do):
    """Blockwise flash backward: rematerializes attention one KV (then
    one Q) block at a time — no S×S residual ever materializes.
    Masked-out cache tail (cols >= kv_len) gets p=0, so its dk/dv are
    exactly zero and dq ignores it."""
    q, k, v, o, lse = res
    scale_v = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    block = 512
    sq, sk = q.shape[-2], k.shape[-2]
    kv_len = sk if kv_len is None else int(kv_len)
    offset = kv_len - sq
    do32 = do.astype(jnp.float32)
    delta = (do32 * o.astype(jnp.float32)).sum(-1)          # (..., sq)

    kp, vp = _pad_seq(k, block), _pad_seq(v, block)
    nb_k = kp.shape[-2] // block

    def dq_step(dq_acc, j):
        kj = lax.dynamic_slice_in_dim(kp, j * block, block, axis=-2)
        vj = lax.dynamic_slice_in_dim(vp, j * block, block, axis=-2)
        s = jnp.einsum("...qd,...kd->...qk", q, kj) \
            .astype(jnp.float32) * scale_v
        row = lax.broadcasted_iota(jnp.int32, (sq, block), 0)
        col = lax.broadcasted_iota(jnp.int32, (sq, block), 1) + j * block
        valid = col < kv_len
        if causal:
            valid = valid & _causal_valid(row, col, offset)
        p = jnp.where(valid, jnp.exp(s - lse[..., None]), 0.0)
        dp = jnp.einsum("...qd,...kd->...qk", do32,
                        vj.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale_v
        dq_acc = dq_acc + jnp.einsum("...qk,...kd->...qd", ds,
                                     kj.astype(jnp.float32))
        return dq_acc, None

    dq, _ = lax.scan(dq_step, jnp.zeros(q.shape, jnp.float32),
                     jnp.arange(nb_k))

    qp = _pad_seq(q, block)
    dop = _pad_seq(do32, block)
    pad_q = qp.shape[-2] - sq
    lsep = jnp.pad(lse, [(0, 0)] * (lse.ndim - 1) + [(0, pad_q)])
    deltap = jnp.pad(delta, [(0, 0)] * (delta.ndim - 1) + [(0, pad_q)])
    nb_q = qp.shape[-2] // block

    def dkv_step(carry, i):
        dk_acc, dv_acc = carry
        qi = lax.dynamic_slice_in_dim(qp, i * block, block, axis=-2)
        doi = lax.dynamic_slice_in_dim(dop, i * block, block, axis=-2)
        lsei = lax.dynamic_slice_in_dim(lsep, i * block, block, axis=-1)
        deltai = lax.dynamic_slice_in_dim(deltap, i * block, block,
                                          axis=-1)
        s = jnp.einsum("...qd,...kd->...qk", qi, k) \
            .astype(jnp.float32) * scale_v
        row = lax.broadcasted_iota(jnp.int32, (block, sk), 0) + i * block
        col = lax.broadcasted_iota(jnp.int32, (block, sk), 1)
        valid = (row < sq) & (col < kv_len)
        if causal:
            valid = valid & _causal_valid(row, col, offset)
        p = jnp.where(valid, jnp.exp(s - lsei[..., None]), 0.0)
        dv_acc = dv_acc + jnp.einsum("...qk,...qd->...kd", p, doi)
        dp = jnp.einsum("...qd,...kd->...qk", doi, v.astype(jnp.float32))
        ds = p * (dp - deltai[..., None]) * scale_v
        dk_acc = dk_acc + jnp.einsum("...qk,...qd->...kd", ds,
                                     qi.astype(jnp.float32))
        return (dk_acc, dv_acc), None

    (dk, dv), _ = lax.scan(
        dkv_step,
        (jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape, jnp.float32)),
        jnp.arange(nb_q))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_flash_fwd, _flash_bwd)


# ---------------------------------------------------------------------------
# decode attention (single-query KV-cache attention, per-row lengths)
# ---------------------------------------------------------------------------
def _masked_attend(q, k, v, valid, scale):
    """Single-pass masked-softmax attention: score, mask, softmax with
    the two non-obvious guards the cache paths need — RE-MASK after
    the exp (a fully-masked row's scores are all NEG_INF, so
    exp(s - m) would be exp(0)=1 across the board instead of 0) and an
    l_safe denominator (a fully-masked row — an empty serving slot —
    returns zeros, not NaN). Shared by decode attention and chunked
    prefill, which differ only in the validity predicate."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    s = jnp.where(valid, s, NEG_INF)
    m = s.max(axis=-1, keepdims=True)
    p = jnp.where(valid, jnp.exp(s - m), 0.0)
    l = p.sum(axis=-1, keepdims=True)
    l_safe = jnp.where(l > 0, l, 1.0)
    p = (p / l_safe).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _decode_fwd_jnp(q, k, v, lengths, scale):
    """Masked single-pass attention: every query row of batch b attends
    keys [0, lengths[b]) of its cache row. Small S_max fits one score
    materialization (B, H, Sq, S_max) — the decode working set is tiny
    compared to prefill, and XLA fuses the chain."""
    shape = (*q.shape[:3], k.shape[2])
    col = lax.broadcasted_iota(jnp.int32, shape, 3)
    return _masked_attend(q, k, v,
                          col < lengths[:, None, None, None], scale)


def _decode_fwd_kernel(len_ref, q_ref, k_ref, v_ref, *rest, scale,
                       block_k, nkb, quant=False):
    """One (batch, head, kv-block) grid step. ``len_ref`` is the
    scalar-prefetched per-slot length vector (SMEM); blocks at or past
    the slot's valid prefix skip compute entirely (their BlockSpec
    index map also re-requests the already-resident block, so no data
    moves for them). Online-softmax state lives in VMEM scratch, which
    persists across the innermost (kv-block) grid axis; the output
    block is written once, on the last grid step.

    ``quant=True`` (int8 KV cache) adds two ``(1, 1)`` scale inputs
    right after ``v_ref``: the resident int8 block is dequantized
    IN-REGISTER with its slot's (dense) or page's (paged) per-head
    scale — the fp32 K/V never exist outside VMEM, so the cache's HBM
    footprint (and the DMA per step) is the int8 bytes."""
    import jax.experimental.pallas as pl
    if quant:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    kb = pl.program_id(2)
    length = len_ref[b]
    nblocks = (length + block_k - 1) // block_k   # this slot's valid blocks

    @pl.when(kb == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(kb < nblocks)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32) * scale    # (sq, d)
        sq = q.shape[0]
        k = k_ref[0, 0].astype(jnp.float32)            # (block_k, d)
        v = v_ref[0, 0].astype(jnp.float32)
        if quant:
            k = k * ks_ref[0, 0]
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)        # (sq, bk)
        col = lax.broadcasted_iota(jnp.int32, (sq, block_k), 1) \
            + kb * block_k
        # masks both the final partial block of the valid prefix and
        # any cache tail past sk (the last grid block may overhang)
        s = jnp.where(col < length, s, NEG_INF)
        # v's overhang rows may hold garbage (even NaN): p is 0 there,
        # but 0 * NaN is NaN, so zero them before the accumulate
        vrow = lax.broadcasted_iota(jnp.int32, (block_k, 1), 0) \
            + kb * block_k
        v = jnp.where(vrow < length, v, 0.0)
        # m/l scratch is (sq, 128) with all lanes equal (TPU-friendly
        # layout); [:, :1] slices recover the per-row scalar
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, :1])
        m_ref[...] = m_new
        l_ref[...] = alpha * l_prev + p.sum(axis=-1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha[:, :1] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == nkb - 1)
    def _finalize():
        l = l_ref[...][:, :1]
        l_safe = jnp.where(l > 0, l, 1.0)  # length==0: an empty slot
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def decode_attention_pallas(q, k, v, lengths, scale=None, block_k=128,
                            interpret=False, k_scale=None, v_scale=None):
    """Pallas decode kernel: grid over (batch, head, kv-block) with the
    per-slot lengths scalar-prefetched into the KV BlockSpec index
    maps. Blocks past a slot's valid prefix are clamped to its last
    valid block — the TPU pipeline elides the copy when the block
    index repeats — so a 40-token slot in a 2048-row cache MOVES
    ceil(40/block_k) KV blocks, not S_max rows; compute for those
    steps is skipped in the kernel. No host-side padding: a final
    partial block is masked in-kernel. ``k_scale``/``v_scale``
    ``(B, H)`` mark an int8 KV cache: the streamed int8 blocks are
    dequantized in VMEM with each slot's per-head scale."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_k = min(block_k, max(sk, 1))
    nkb = (sk + block_k - 1) // block_k
    quant = k_scale is not None

    def _kv_index(i, j, kb, lens):
        last = jnp.maximum((lens[i] + block_k - 1) // block_k - 1, 0)
        return (i, j, jnp.minimum(kb, last), 0)

    in_specs = [
        pl.BlockSpec((1, 1, sq, d),
                     lambda i, j, kb, lens: (i, j, 0, 0)),
        pl.BlockSpec((1, 1, block_k, d), _kv_index),
        pl.BlockSpec((1, 1, block_k, d), _kv_index),
    ]
    operands = [q, k, v]
    if quant:
        in_specs += [pl.BlockSpec((1, 1),
                                  lambda i, j, kb, lens: (i, j))] * 2
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, h, nkb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, sq, d),
                               lambda i, j, kb, lens: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((sq, 128), jnp.float32),   # running max
            pltpu.VMEM((sq, 128), jnp.float32),   # running denominator
            pltpu.VMEM((sq, d), jnp.float32),     # running numerator
        ],
    )
    kernel = functools.partial(_decode_fwd_kernel, scale=scale,
                               block_k=block_k, nkb=nkb, quant=quant)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), *operands)


def gather_pages(pool, table):
    """Materialize each slot's logical KV view from a paged pool:
    ``pool`` (n_pages, H, page_size, D) + ``table`` (B, P_max) int32
    -> (B, H, P_max * page_size, D). Logical position ``t`` of slot
    ``b`` lives at ``pool[table[b, t // ps], :, t % ps]``. Free table
    entries point at the reserved scrap page (id 0) — their rows are
    garbage that per-row length masking must exclude."""
    g = pool[table]                       # (B, P_max, H, ps, D)
    b, pm, h, ps, d = g.shape
    return g.transpose(0, 2, 1, 3, 4).reshape(b, h, pm * ps, d)


def _paged_decode_fwd_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref,
                             *rest, **kw):
    """Paged decode grid step: the page table participates only in the
    BlockSpec index maps (it chooses WHICH pool page each grid step
    DMAs); once the right (1, 1, page_size, d) pool block is resident
    the arithmetic is exactly the dense decode kernel's."""
    del tbl_ref
    _decode_fwd_kernel(len_ref, q_ref, k_ref, v_ref, *rest, **kw)


def expand_page_scales(pool_scale, table, page_size):
    """Broadcast per-head-per-PAGE scales onto token positions:
    ``pool_scale`` (n_pages, H) + ``table`` (B, P_max) ->
    (B, H, P_max * page_size) — position ``t`` of slot ``b`` carries
    the scale of its page ``table[b, t // page_size]``. The dequant
    companion of ``gather_pages`` for an int8 pool."""
    g = pool_scale[table]                       # (B, P_max, H)
    return jnp.repeat(g.transpose(0, 2, 1), page_size, axis=2)


def paged_decode_attention_pallas(q, k_pool, v_pool, table, lengths,
                                  scale=None, interpret=False,
                                  k_scale=None, v_scale=None):
    """Pallas paged-decode kernel: grid (batch, head, page-slot) with
    BOTH the per-slot lengths and the page table scalar-prefetched into
    the KV BlockSpec index maps. Grid step ``kb`` of slot ``i`` DMAs
    pool page ``table[i, kb]`` — so the data that moves is each slot's
    OWN pages, wherever they sit in the pool, and (as in the dense
    decode kernel) steps at or past the slot's valid prefix clamp to
    its last valid page: a repeated block index lets the TPU pipeline
    elide the copy, bounding DMA to ceil(len/page_size) pages per
    slot. Compute for those steps is skipped in the kernel.
    ``k_scale``/``v_scale`` (n_pages, H) mark an int8 pool: each
    resident page is dequantized in VMEM with ITS OWN per-head scale
    (the scale rides the same table-indexed BlockSpec as the page)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    n_pages, hp, ps, dp = k_pool.shape
    if (hp, dp) != (h, d):
        raise ValueError(
            f"pool layout {k_pool.shape} does not match q {q.shape}")
    p_max = table.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    quant = k_scale is not None

    def _kv_index(i, j, kb, lens, tbl):
        last = jnp.maximum((lens[i] + ps - 1) // ps - 1, 0)
        return (tbl[i, jnp.minimum(kb, last)], j, 0, 0)

    def _sc_index(i, j, kb, lens, tbl):
        last = jnp.maximum((lens[i] + ps - 1) // ps - 1, 0)
        return (tbl[i, jnp.minimum(kb, last)], j)

    in_specs = [
        pl.BlockSpec((1, 1, sq, d),
                     lambda i, j, kb, lens, tbl: (i, j, 0, 0)),
        pl.BlockSpec((1, 1, ps, d), _kv_index),
        pl.BlockSpec((1, 1, ps, d), _kv_index),
    ]
    operands = [q, k_pool, v_pool]
    if quant:
        in_specs += [pl.BlockSpec((1, 1), _sc_index)] * 2
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, p_max),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, sq, d),
                               lambda i, j, kb, lens, tbl: (i, j, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((sq, 128), jnp.float32),   # running max
            pltpu.VMEM((sq, 128), jnp.float32),   # running denominator
            pltpu.VMEM((sq, d), jnp.float32),     # running numerator
        ],
    )
    kernel = functools.partial(_paged_decode_fwd_kernel, scale=scale,
                               block_k=ps, nkb=p_max, quant=quant)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), table.astype(jnp.int32), *operands)


def paged_decode_attention(q, k_pool, v_pool, table, lengths,
                           scale=None, k_scale=None, v_scale=None):
    """Decode attention against a PAGED KV cache.

    ``q`` is (B, H, Sq, D); ``k_pool``/``v_pool`` are the global page
    pools (n_pages, H, page_size, D); ``table`` (B, P_max) int32 maps
    each slot's logical page index to a physical pool page; ``lengths``
    (B,) int32 marks each slot's valid token prefix. Semantics equal
    ``decode_attention`` over the gathered per-slot view — the jnp
    path literally IS that (gather + the same masked softmax, so a
    paged cache holding the same values produces bit-identical logits
    to the dense cache); the Pallas TPU path streams only each slot's
    valid pages through VMEM via scalar-prefetched (lengths, table)
    index maps.

    ``k_scale``/``v_scale`` (n_pages, H) fp32 mark an INT8 pool
    (half the HBM per cached token vs bf16, a quarter vs fp32): the
    jnp path dequantizes the gathered view with each page's per-head
    scale; the Pallas path dequantizes each page in VMEM after the
    DMA — int8 is what moves."""
    scale_v = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    lengths = jnp.asarray(lengths, jnp.int32)
    table = jnp.asarray(table, jnp.int32)
    if _use_pallas():
        return paged_decode_attention_pallas(q, k_pool, v_pool, table,
                                             lengths, scale=scale_v,
                                             k_scale=k_scale,
                                             v_scale=v_scale)
    k = gather_pages(k_pool, table)
    v = gather_pages(v_pool, table)
    if k_scale is not None:
        ps = k_pool.shape[2]
        k = k.astype(jnp.float32) \
            * expand_page_scales(k_scale, table, ps)[..., None]
        v = v.astype(jnp.float32) \
            * expand_page_scales(v_scale, table, ps)[..., None]
    return _decode_fwd_jnp(q, k, v, lengths, scale_v)


def chunked_prefill_attention(q, k, v, start, scale=None):
    """Attention for one PREFILL CHUNK against a cache buffer.

    ``q`` (B, H, C, D) holds the chunk's queries at global positions
    ``start + i`` (``start`` is a (B,) int32 or scalar — traced, so
    every chunk of every prompt runs ONE compiled program); ``k``/``v``
    (B, H, S, D) are each row's gathered cache holding valid keys
    ``[0, start + C)`` (earlier chunks plus this one, already written).
    Row ``i`` attends keys ``[0, start + i]`` — the causal mask in
    global coordinates, which also masks every unwritten/garbage cache
    position since nothing beyond ``start + i`` is ever valid for that
    query. Single-pass masked softmax (the decode-attention
    formulation): the chunk working set is (C, S), tiny next to a
    monolithic prefill's (S, S)."""
    scale_v = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    start = jnp.asarray(start, jnp.int32)
    if start.ndim == 0:
        start = start[None]
    shape = (*q.shape[:3], k.shape[2])
    row = lax.broadcasted_iota(jnp.int32, shape, 2)
    col = lax.broadcasted_iota(jnp.int32, shape, 3)
    valid = col <= start[:, None, None, None] + row
    return _masked_attend(q, k, v, valid, scale_v)


def decode_attention(q, k, v, lengths, scale=None, k_scale=None,
                     v_scale=None):
    """Autoregressive decode attention against a preallocated KV cache.

    ``q`` is (B, H, Sq, D) — Sq is 1 on the decode hot path; ``k``/``v``
    are the cache buffers (B, H, S_max, D) filled left-to-right;
    ``lengths`` (B,) int32 marks each slot's valid prefix INCLUDING the
    just-inserted token. Every query attends keys [0, lengths[b]) — no
    intra-query causal structure (the single new token sees the whole
    valid cache), matching ``mha_reference(q, k[:, :, :len],
    v[:, :, :len])`` per row. A row with lengths==0 (an empty serving
    slot riding along in the fixed-shape batch) returns zeros.

    ``k_scale``/``v_scale`` (B, H) fp32 mark an INT8 cache: the
    stored int8 K/V dequantize with each slot's per-head scale — in
    VMEM on the Pallas path (int8 is what streams from HBM), before
    the masked softmax on the jnp path.
    """
    scale_v = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    lengths = jnp.asarray(lengths, jnp.int32)
    if _use_pallas():
        return decode_attention_pallas(q, k, v, lengths, scale=scale_v,
                                       k_scale=k_scale, v_scale=v_scale)
    if k_scale is not None:
        k = k.astype(jnp.float32) * k_scale[:, :, None, None]
        v = v.astype(jnp.float32) * v_scale[:, :, None, None]
    return _decode_fwd_jnp(q, k, v, lengths, scale_v)


# ---------------------------------------------------------------------------
# ring attention (sequence parallel over 'sp')
# ---------------------------------------------------------------------------
def ring_attention_local(q, k, v, axis_name="sp", causal=False, scale=None):
    """Per-shard body to run under shard_map: q/k/v are the LOCAL
    sequence shards (b, h, s_local, d)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    s_local = q.shape[2]
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        m, l, acc, kv = carry
        kc, vc = kv
        src = (my - t) % n                      # whose KV shard this is
        # global-position causal mask for this (q-shard, kv-shard) pair
        if causal:
            row = lax.broadcasted_iota(jnp.int32, (s_local, s_local), 0) \
                + my * s_local
            col = lax.broadcasted_iota(jnp.int32, (s_local, s_local), 1) \
                + src * s_local
            mask = col <= row
        else:
            mask = None
        m, l, acc = _attn_block(q, kc, vc, m, l, acc, scale, mask)
        kv = jax.tree.map(lambda x: lax.ppermute(x, axis_name, perm),
                          (kc, vc))
        return (m, l, acc, kv), None

    # init carries FROM q so their device-variance matches the loop
    # body's outputs (shard_map tracks varying-over-axis types)
    m0 = jnp.full_like(q[..., 0], NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros_like(q[..., 0], dtype=jnp.float32)
    acc0 = jnp.zeros_like(q, dtype=jnp.float32)
    (m, l, acc, _), _ = lax.scan(step, (m0, l0, acc0, (k, v)),
                                 jnp.arange(n))
    return (acc / l[..., None]).astype(q.dtype)


def ring_attention(q, k, v, mesh=None, axis_name="sp", causal=False,
                   scale=None):
    """Sequence-parallel attention: shards the sequence axis (2) of
    q/k/v over `axis_name` and runs the ring. Returns the same global
    array layout as the input."""
    from jax.sharding import PartitionSpec as P
    from .._shard_compat import shard_map
    from .. import parallel

    mesh = mesh or parallel.get_mesh()
    if mesh is None or axis_name not in mesh.shape:
        return flash_attention(q, k, v, causal, scale)
    if q.shape[2] % mesh.shape[axis_name] != 0:
        # sequence not divisible by the sp axis (e.g. a shape-inference
        # probe with a tiny sequence): single-device attention is exact
        return flash_attention(q, k, v, causal, scale)
    if not isinstance(q, jax.core.Tracer):
        # Eager call (e.g. the deferred-init shape probe): committing
        # the output to the mesh would poison later eager ops that mix
        # it with single-device weights. The ring engages inside jitted
        # programs (hybridize / TrainStep) — the production path.
        return flash_attention(q, k, v, causal, scale)
    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention_local, axis_name=axis_name,
                          causal=causal, scale=scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)

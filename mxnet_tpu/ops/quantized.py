"""Fused dequantize-matmul: the weight-only int8 decode kernel.

Decode at small batch is weight-bandwidth-bound: every step re-streams
the full parameter set from HBM, so shrinking the bytes — not the
FLOPs — raises the ceiling. Weights are stored per-output-channel
symmetric int8 (``quantize_channelwise``) and the projection matmul
dequantizes them ON THE FLY, one output-channel block at a time:

    y = x @ (wq.astype(f32) * scale[:, None]).T

with the converted block living only in VMEM (Pallas TPU kernel) or
cache (blocked jnp path) — the dequantized weight never materializes
in HBM. Contrast contrib/quantization.py, which quantizes the
ACTIVATIONS too and runs int8 x int8 contractions (the MXU inference
path): here activations stay fp32, so the only error source is the
weight rounding — the property the serving engine's bounded-divergence
gate (docs/SERVING.md "Low-precision decode") is built on.

Parity discipline: ``dequant_matmul`` (jnp) and
``dequant_matmul_pallas`` perform the IDENTICAL per-block computation
— same block boundaries, same convert-multiply-dot order, same
``preferred_element_type`` — so the pair is bitwise-identical on one
backend (tested in tests/test_quantized.py); the engine-level int8
claims then reduce to properties of ONE numerical path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .attention import _use_pallas

__all__ = ["quantize_channelwise", "dequant_matmul",
           "dequant_matmul_pallas", "kv_scale", "kv_quantize"]

_INT8_MAX = 127.0
#: default output-channel block: 512 f32-dequantized channels of a
#: K<=4096 weight stay comfortably inside VMEM (and L2 on CPU)
_BLOCK_N = 512


def quantize_channelwise(w, axis=0):
    """fp32 weight -> ``(int8 weight, fp32 scales)`` with a symmetric
    range per output channel (``axis``; Dense layout is ``(out, in)``
    so the default quantizes each output row against its own absmax —
    the error of one channel never inflates another's scale).
    ``dequant == wq.astype(f32) * scale`` broadcast over ``axis``;
    scales are returned flat ``(w.shape[axis],)``."""
    w = jnp.asarray(w)
    if w.dtype != jnp.float32:
        w = w.astype(jnp.float32)
    red = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.abs(w).max(axis=red, keepdims=True)
    # all-zero channels get scale eps/127, quantize to 0, dequantize
    # to exact 0 — never a div-by-zero NaN
    scale = (jnp.maximum(absmax, 1e-12) / _INT8_MAX).astype(jnp.float32)
    wq = jnp.clip(jnp.round(w / scale), -_INT8_MAX, _INT8_MAX) \
        .astype(jnp.int8)
    return wq, scale.reshape(-1)


def kv_scale(x, axes):
    """amax-derived symmetric int8 scale over ``axes`` (fp32) — the KV
    companion of ``quantize_channelwise``, kept here so the whole
    int8 convention (amax/127 range, eps floor, round-then-clip)
    lives in one module."""
    return (jnp.max(jnp.abs(x), axis=axes) / _INT8_MAX) \
        .astype(jnp.float32)


def kv_quantize(x, scale):
    """Quantize K/V values with a broadcast-ready ``scale``. The
    epsilon floor keeps an unwritten slot's zero scale from minting
    NaN int8 garbage — those rows are masked out of attention, but a
    NaN V row would still poison the ``p @ v`` accumulation
    (0 * NaN)."""
    s = jnp.maximum(scale, 1e-12)
    return jnp.clip(jnp.round(x / s), -_INT8_MAX, _INT8_MAX) \
        .astype(jnp.int8)


def _block_n(n, block_n):
    """Output channels per block: the requested width when it divides
    ``n``, otherwise the whole matrix in one block (model dims here
    are powers of two; an uneven tail would force a second program
    shape)."""
    bn = min(int(block_n), n)
    return bn if n % bn == 0 else n


def _dequant_dot(x2, wq_blk, s_blk):
    """The ONE canonical block computation both paths run: convert the
    int8 block, scale per output channel, contract x's feature axis
    against the weight's ``in`` axis in fp32. Kept as a shared helper
    so the jnp/Pallas pair cannot drift apart numerically."""
    wf = wq_blk.astype(jnp.float32) * s_blk[:, None]
    return lax.dot_general(x2, wf, (((1,), (1,)), ((), ())),
                           preferred_element_type=jnp.float32)


def dequant_matmul(x, wq, scales, block_n=_BLOCK_N):
    """``x @ dequant(wq, scales).T`` — blocked jnp reference.

    ``x`` is ``(..., K)`` fp32, ``wq`` ``(N, K)`` int8 (the Dense
    ``(out, in)`` layout), ``scales`` ``(N,)`` fp32. Returns
    ``(..., N)`` fp32. The weight is dequantized ``block_n`` output
    channels at a time inside a ``lax.map`` — the converted block is
    consumed by its dot before the next one exists, so peak extra
    memory is one block, not the whole fp32 weight. On TPU dispatches
    to the Pallas kernel (same per-block arithmetic)."""
    wq = jnp.asarray(wq)
    scales = jnp.asarray(scales)
    x = jnp.asarray(x)
    n, k = wq.shape
    if x.shape[-1] != k:
        raise ValueError(f"x features {x.shape[-1]} do not match "
                         f"quantized weight in-dim {k}")
    if scales.shape != (n,):
        raise ValueError(f"scales shape {scales.shape} must be ({n},)")
    if _use_pallas():
        return dequant_matmul_pallas(x, wq, scales, block_n=block_n)
    lead = x.shape[:-1]
    x2 = x.reshape(-1, k).astype(jnp.float32)
    bn = _block_n(n, block_n)
    nb = n // bn

    def body(j):
        wq_blk = lax.dynamic_slice(wq, (j * bn, 0), (bn, k))
        s_blk = lax.dynamic_slice(scales, (j * bn,), (bn,))
        return _dequant_dot(x2, wq_blk, s_blk)

    if nb == 1:
        out = _dequant_dot(x2, wq, scales)
    else:
        out = lax.map(body, jnp.arange(nb))        # (nb, B, bn)
        out = out.transpose(1, 0, 2).reshape(x2.shape[0], n)
    return out.reshape(*lead, n)


def _dequant_matmul_kernel(x_ref, wq_ref, s_ref, o_ref):
    """One output-channel-block grid step: the int8 weight block and
    its scales stream into VMEM, dequantize in-register, one fp32 dot.
    The dequantized fp32 weight exists ONLY as this block."""
    o_ref[...] = _dequant_dot(x_ref[...], wq_ref[...], s_ref[...])


def dequant_matmul_pallas(x, wq, scales, block_n=_BLOCK_N,
                          interpret=False):
    """Pallas fused dequant-matmul: grid over output-channel blocks;
    each step DMAs one ``(block_n, K)`` int8 block + its ``(block_n,)``
    scales, dequantizes in VMEM, and writes one fp32 output block —
    per-block arithmetic identical to the jnp path (bitwise-parity
    tested)."""
    import jax.experimental.pallas as pl

    n, k = wq.shape
    lead = x.shape[:-1]
    x2 = x.reshape(-1, k).astype(jnp.float32)
    b = x2.shape[0]
    bn = _block_n(n, block_n)
    out = pl.pallas_call(
        _dequant_matmul_kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((b, k), lambda j: (0, 0)),
            pl.BlockSpec((bn, k), lambda j: (j, 0)),
            pl.BlockSpec((bn,), lambda j: (j,)),
        ],
        out_specs=pl.BlockSpec((b, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((b, n), jnp.float32),
        interpret=interpret,
    )(x2, wq, scales)
    return out.reshape(*lead, n)

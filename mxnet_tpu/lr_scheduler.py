"""Learning-rate schedulers.

API parity with the reference's ``python/mxnet/lr_scheduler.py``
(class names, constructor signatures, ``__call__(num_update)``), but a
different design: every schedule here is a **pure function of
``num_update``** computed in closed form. The reference mutates
``self.base_lr`` incrementally inside ``__call__`` (a running
``count``/``cur_step_ind`` state machine), which makes the schedule
depend on the call history; these are stateless, so a scheduler can be
queried at arbitrary points (plotting, resume-from-checkpoint at step
N, jitted lookup tables) and always returns the same value for the
same ``num_update``.
"""
from __future__ import annotations

import math


class LRScheduler:
    """Base class: warmup handling + the ``__call__`` contract.

    Subclasses implement :meth:`_decayed_lr`, the post-warmup schedule,
    as a pure function of the number of post-warmup updates.
    """

    def __init__(self, base_lr=0.01, warmup_steps=0, warmup_begin_lr=0,
                 warmup_mode="linear"):
        if warmup_begin_lr > base_lr:
            raise ValueError("base lr has to be higher than warmup lr")
        if warmup_steps < 0:
            raise ValueError("warmup steps has to be positive or 0")
        if warmup_mode not in ("linear", "constant"):
            raise ValueError("Supports only linear and constant warmup modes")
        self.base_lr = base_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr
        self.warmup_final_lr = base_lr
        self.warmup_mode = warmup_mode

    def get_warmup_lr(self, num_update):
        assert num_update < self.warmup_steps
        if self.warmup_mode == "constant":
            return self.warmup_begin_lr
        frac = num_update / self.warmup_steps
        return self.warmup_begin_lr + frac * (self.warmup_final_lr
                                              - self.warmup_begin_lr)

    def _decayed_lr(self, steps_after_warmup):
        raise NotImplementedError

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self.get_warmup_lr(num_update)
        return self._decayed_lr(num_update - self.warmup_steps)


class FactorScheduler(LRScheduler):
    """``lr = base_lr * factor**k`` after every ``step`` updates,
    floored at ``stop_factor_lr``."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8, base_lr=0.01,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def _decayed_lr(self, t):
        # the k-th decay fires once num_update exceeds k*step (warmup
        # offset included in the reference's accounting: it counts raw
        # num_update, so re-add it here)
        num_update = t + self.warmup_steps
        n_decays = max(0, (num_update - 1) // self.step)
        return max(self.base_lr * self.factor ** n_decays,
                   self.stop_factor_lr)


class MultiFactorScheduler(LRScheduler):
    """``lr = base_lr * factor**k`` where ``k`` counts the milestones in
    ``step`` that ``num_update`` has passed."""

    def __init__(self, step, factor=1, base_lr=0.01, warmup_steps=0,
                 warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list of milestones")
        if any(s < 1 for s in step):
            raise ValueError("Schedule step must be greater or equal than 1")
        if any(b <= a for a, b in zip(step, step[1:])):
            raise ValueError("Schedule step must be an increasing list")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = step
        self.factor = factor

    def _decayed_lr(self, t):
        num_update = t + self.warmup_steps
        n_decays = sum(1 for milestone in self.step
                       if num_update > milestone)
        return self.base_lr * self.factor ** n_decays


class _SpanScheduler(LRScheduler):
    """Shared shape for schedules that anneal base_lr -> final_lr over
    ``max_update`` total updates: subclasses map the elapsed fraction
    to a remaining-lr fraction in [0, 1]."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(base_lr, warmup_steps, warmup_begin_lr, warmup_mode)
        if not isinstance(max_update, int) or max_update < 1:
            raise ValueError(
                "maximum number of updates must be strictly positive")
        self.max_update = max_update
        self.final_lr = final_lr
        self.max_steps = max_update - warmup_steps

    def _remaining(self, frac_elapsed):
        raise NotImplementedError

    def _decayed_lr(self, t):
        frac = min(t / self.max_steps, 1.0) if self.max_steps > 0 else 1.0
        span = self.base_lr - self.final_lr
        return self.final_lr + span * self._remaining(frac)


class PolyScheduler(_SpanScheduler):
    """Polynomial decay: remaining fraction ``(1 - t/T)**pwr``."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0,
                 warmup_steps=0, warmup_begin_lr=0, warmup_mode="linear"):
        super().__init__(max_update, base_lr, final_lr,
                         warmup_steps, warmup_begin_lr, warmup_mode)
        self.power = pwr

    def _remaining(self, frac_elapsed):
        return (1.0 - frac_elapsed) ** self.power


class CosineScheduler(_SpanScheduler):
    """Cosine decay: remaining fraction ``(1 + cos(pi * t/T)) / 2``."""

    def _remaining(self, frac_elapsed):
        return 0.5 * (1.0 + math.cos(math.pi * frac_elapsed))

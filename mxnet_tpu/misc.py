"""Deprecated learning-rate scheduler aliases (parity:
python/mxnet/misc.py — the reference keeps these as the historic home
of FactorScheduler before lr_scheduler.py existed)."""
from .lr_scheduler import LRScheduler as LearningRateScheduler  # noqa: F401
from .lr_scheduler import FactorScheduler  # noqa: F401

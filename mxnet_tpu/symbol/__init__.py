"""mx.sym — legacy symbolic graph-building API.

Capability parity with the reference's `mxnet.symbol`
(python/mxnet/symbol/symbol.py, ~3.3k LoC over the nnvm C++ graph).
TPU-native design: a Symbol is a small, JSON-serializable op DAG whose
nodes name functions in the `mx.np`/`mx.npx` namespaces. Evaluation
walks the DAG once under `jax.jit` tracing, so a bound Executor is ONE
compiled XLA program — the reference needs CachedOp + graph passes +
memory planning for the same effect (SURVEY.md §3.3); here that whole
pipeline is XLA.

Like the reference's 2.x line, the Executor shim delegates to the
imperative autograd machinery for gradients
(python/mxnet/executor.py:124 delegates to CachedOp + autograd).
"""
from . import _ops  # generated op wrappers (PEP 562)  # noqa: F401
from ._ops import *  # noqa: F401,F403
# core names last so they win any collision with generated op wrappers
from .symbol import (  # noqa: E402,F401
    Symbol, var, Variable, Group, load, load_json, fromjson,
    zeros, ones, full,
)


def __getattr__(name):
    """Op wrappers are generated on demand from mx.np/mx.npx
    (reference parity: symbol/register.py codegen at import)."""
    return getattr(_ops, name)


def __dir__():
    return sorted(set(globals()) | set(dir(_ops)))

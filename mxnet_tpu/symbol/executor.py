"""Executor — bound evaluation of a Symbol.

Parity: python/mxnet/executor.py (the 2.x shim whose forward delegates
to CachedOp and whose backward delegates to autograd). Here forward
jits the DAG walk into one XLA program per input signature; backward
runs the same imperative autograd used everywhere else.
"""
from __future__ import annotations


class Executor:
    def __init__(self, symbol, ctx, args, args_grad, grad_req):
        import mxnet_tpu as mx
        self._symbol = symbol
        self._ctx = ctx or mx.current_context()
        self.arg_dict = dict(args)
        self.grad_dict = dict(args_grad) if args_grad else {}
        # grad_req may be one string for all args, or a per-name dict
        # (reference bind() accepts both; list form maps positionally)
        names = list(self.arg_dict)
        if isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(names, grad_req))
        if isinstance(grad_req, dict):
            self._req = {n: grad_req.get(n, "write") for n in names}
        else:
            self._req = {n: grad_req for n in names}
        self._grad_req = grad_req if isinstance(grad_req, str) \
            else "write"
        self.outputs = []
        self._recorded = None

    def forward(self, is_train=False, **kwargs):
        import mxnet_tpu as mx
        from mxnet_tpu import autograd
        self.arg_dict.update({k: v if isinstance(v, mx.NDArray)
                              else mx.np.array(v)
                              for k, v in kwargs.items()})
        want_grad = is_train and self.grad_dict and any(
            self._req.get(n, "null") != "null" for n in self.grad_dict)
        if want_grad:
            for name in self.grad_dict:
                if self._req.get(name, "null") != "null":
                    self.arg_dict[name].attach_grad(
                        self._req[name])
            with autograd.record():
                outs = self._symbol._eval(self.arg_dict)
            self._recorded = outs
        else:
            outs = self._symbol._eval(self.arg_dict)
            self._recorded = None
        self.outputs = outs
        return outs

    def backward(self, out_grads=None):
        from mxnet_tpu import autograd
        if self._recorded is None:
            raise RuntimeError("call forward(is_train=True) before backward")
        heads = self._recorded
        autograd.backward(heads, head_grads=out_grads)
        for name, g in self.grad_dict.items():
            if self._req.get(name, "null") == "null":
                continue  # per-name null: no gradient written
            arr = self.arg_dict[name]
            if arr.grad is not None:
                if self._req[name] == "add":
                    # accumulate across forward/backward rounds
                    # (reference executor grad_req='add' semantics —
                    # attach_grad re-zeroes the tape buffer per
                    # forward, so the executor's grad array is the
                    # accumulator)
                    g[:] = g + arr.grad
                else:
                    g[:] = arr.grad
        return [self.grad_dict.get(n) for n in self._symbol.list_arguments()]

"""Importer for reference (legacy nnvm) ``-symbol.json`` graphs.

The reference serializes symbols as nnvm JSON (node list with
3-element ``[nid, idx, version]`` input entries, string-valued attrs,
``node_row_ptr``; written by nnvm's JSON pass and loaded through
``python/mxnet/symbol/symbol.py load``).  This module converts such a
graph into an ``mxnet_tpu`` Symbol so models exported by the reference
(``HybridBlock.export`` → ``-symbol.json`` + ``-NNNN.params``) can be
migrated: ``mx.sym.load`` auto-detects the format, and
``gluon.SymbolBlock.imports`` composes it with a legacy param file.

Coverage is the inference op set used by exported models (dense/conv
nets, the reference model zoo); an unmapped op raises with the op name
so the gap is explicit rather than a silent mistranslation.
"""
from __future__ import annotations

import ast

from .symbol import Symbol, _Node

__all__ = ["from_nnvm_json"]


def _parse_attr(v):
    """Legacy attrs are strings: '(2, 2)', 'True', '1e-05', 'relu'."""
    if not isinstance(v, str):
        return v
    s = v.strip()
    if s in ("True", "true"):
        return True
    if s in ("False", "false"):
        return False
    if s in ("None", ""):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _attrs_of(node):
    # very old graphs used "param"; 1.x used "attrs"; some used "attr"
    raw = node.get("attrs") or node.get("attr") or node.get("param") or {}
    return {k: _parse_attr(v) for k, v in raw.items()}


# Each handler: (legacy_inputs, attrs) -> (table_op, kept_input_positions,
# node_attrs). kept_input_positions indexes into the legacy input list
# (e.g. SoftmaxOutput drops its label input at inference).
def _simple(table_op, **fixed):
    def h(inputs, attrs):
        a = dict(fixed)
        a.update(attrs)
        return table_op, list(range(len(inputs))), a
    return h


def _unary(table_op):
    def h(inputs, attrs):
        return table_op, [0], {}
    return h


def _fully_connected(inputs, attrs):
    a = {"no_bias": bool(attrs.get("no_bias", False)),
         "flatten": bool(attrs.get("flatten", True))}
    keep = [0, 1] if a["no_bias"] else [0, 1, 2]
    return "npx:fully_connected", keep, a


def _convolution(inputs, attrs):
    a = {"kernel": tuple(attrs.get("kernel") or ()),
         "stride": attrs.get("stride") or 1,
         "dilate": attrs.get("dilate") or 1,
         "pad": attrs.get("pad") or 0,
         "num_filter": attrs.get("num_filter", 1),
         "num_group": attrs.get("num_group", 1),
         "no_bias": bool(attrs.get("no_bias", False)),
         "layout": attrs.get("layout") or "NCHW"}
    keep = [0, 1] if a["no_bias"] else [0, 1, 2]
    return "npx:convolution", keep, a


def _pooling(inputs, attrs):
    a = {"kernel": tuple(attrs.get("kernel") or (1,)),
         "pool_type": attrs.get("pool_type", "max"),
         "global_pool": bool(attrs.get("global_pool", False)),
         "pooling_convention": attrs.get("pooling_convention", "valid"),
         "layout": attrs.get("layout") or "NCHW"}
    if attrs.get("stride"):
        a["stride"] = attrs["stride"]
    if attrs.get("pad"):
        a["pad"] = attrs["pad"]
    if attrs.get("count_include_pad") is not None:
        a["count_include_pad"] = bool(attrs["count_include_pad"])
    return "npx:pooling", list(range(len(inputs))), a


def _batch_norm(inputs, attrs):
    a = {"eps": attrs.get("eps", 1e-3),
         "momentum": attrs.get("momentum", 0.9),
         "fix_gamma": bool(attrs.get("fix_gamma", True)),
         "use_global_stats": bool(attrs.get("use_global_stats", False)),
         "axis": attrs.get("axis", 1)}
    # (data, gamma, beta, moving_mean, moving_var) — same order here
    return "npx:batch_norm", [0, 1, 2, 3, 4], a


def _activation(inputs, attrs):
    return "npx:activation", [0], {"act_type": attrs.get("act_type", "relu")}


def _leaky_relu(inputs, attrs):
    act = attrs.get("act_type", "leaky")
    a = {"act_type": act, "slope": attrs.get("slope", 0.25)}
    # prelu carries a learned slope as a second input
    keep = [0, 1] if act == "prelu" else [0]
    return "npx:leaky_relu", keep, a


def _softmax_output(inputs, attrs):
    # At inference SoftmaxOutput is softmax over the last axis; the
    # label input only matters for the (training-time) backward.
    return "npx:softmax", [0], {"axis": -1}


def _concat(inputs, attrs):
    return "_legacy_concat", list(range(len(inputs))), \
        {"axis": attrs.get("dim", 1)}


def _slice_channel(inputs, attrs):
    if attrs.get("squeeze_axis"):
        raise ValueError("legacy SliceChannel with squeeze_axis=1 is not "
                         "supported by the importer")
    n = attrs.get("num_outputs", 1)
    return "split", [0], {"indices_or_sections": n,
                          "axis": attrs.get("axis", 1),
                          "__num_outputs__": n}


def _reshape(inputs, attrs):
    shape = attrs.get("shape") or attrs.get("newshape")
    return "_legacy_reshape", [0], {"shape": list(shape),
                                    "reverse": bool(attrs.get("reverse",
                                                              False))}


def _cast(inputs, attrs):
    return "_astype", [0], {"dtype": str(attrs.get("dtype", "float32"))}


def _clip(inputs, attrs):
    return "clip", [0], {"a_min": attrs.get("a_min"),
                         "a_max": attrs.get("a_max")}


def _scalar_op(np_op, reverse=False):
    def h(inputs, attrs):
        return "_legacy_scalar", [0], {"op": np_op,
                                       "scalar": attrs.get("scalar", 0.0),
                                       "reverse": reverse}
    return h


def _embedding(inputs, attrs):
    return "npx:embedding", [0, 1], {}


def _transpose(inputs, attrs):
    axes = attrs.get("axes")
    return "transpose", [0], {"axes": tuple(axes) if axes else None}


def _reduce(table_op):
    def h(inputs, attrs):
        return table_op, [0], {"axis": attrs.get("axis"),
                               "keepdims": bool(attrs.get("keepdims", False))}
    return h


_HANDLERS = {
    "FullyConnected": _fully_connected,
    "Convolution": _convolution,
    "Activation": _activation,
    "LeakyReLU": _leaky_relu,
    "Pooling": _pooling,
    "BatchNorm": _batch_norm,
    "SoftmaxOutput": _softmax_output,
    "softmax": _simple("npx:softmax"),
    "log_softmax": _simple("npx:log_softmax"),
    "Softmax": _softmax_output,
    "Concat": _concat,
    "concat": _concat,
    "SliceChannel": _slice_channel,
    "Reshape": _reshape,
    "reshape": _reshape,
    "Flatten": _unary("_flatten"),
    "flatten": _unary("_flatten"),
    "Dropout": _unary("_identity"),   # inference: identity
    "identity": _unary("_identity"),
    "_copy": _unary("_identity"),
    "BlockGrad": _unary("_identity"),
    "stop_gradient": _unary("_identity"),
    "Cast": _cast,
    "cast": _cast,
    "clip": _clip,
    "transpose": _transpose,
    "Embedding": _embedding,
    "relu": _unary("npx:relu"),
    "sigmoid": _unary("npx:sigmoid"),
    "tanh": _unary("tanh"),
    "exp": _unary("exp"),
    "log": _unary("log"),
    "sqrt": _unary("sqrt"),
    "square": _unary("square"),
    "add_n": lambda inputs, attrs: ("_legacy_add_n",
                                    list(range(len(inputs))), {}),
    "ElementWiseSum": lambda inputs, attrs: ("_legacy_add_n",
                                             list(range(len(inputs))), {}),
    "elemwise_add": _simple("add"),
    "elemwise_sub": _simple("subtract"),
    "elemwise_mul": _simple("multiply"),
    "elemwise_div": _simple("divide"),
    "broadcast_add": _simple("add"),
    "broadcast_sub": _simple("subtract"),
    "broadcast_mul": _simple("multiply"),
    "broadcast_div": _simple("divide"),
    "dot": _simple("dot"),
    "batch_dot": _simple("npx:batch_dot"),
    "mean": _reduce("mean"),
    "sum": _reduce("sum"),
    "max": _reduce("max"),
    "min": _reduce("min"),
    "_plus_scalar": _scalar_op("add"),
    "_minus_scalar": _scalar_op("subtract"),
    "_rminus_scalar": _scalar_op("subtract", reverse=True),
    "_mul_scalar": _scalar_op("multiply"),
    "_div_scalar": _scalar_op("divide"),
    "_rdiv_scalar": _scalar_op("divide", reverse=True),
    "_power_scalar": _scalar_op("power"),
    # 2.x numpy-namespace exports
    "_npi_add": _simple("add"),
    "_npi_subtract": _simple("subtract"),
    "_npi_multiply": _simple("multiply"),
    "_npi_true_divide": _simple("divide"),
    "_npi_power": _simple("power"),
    "_npi_mean": _reduce("mean"),
    "_npi_sum": _reduce("sum"),
    "_npi_transpose": _transpose,
    "_npi_concatenate": _concat,
    "_npx_relu": _unary("npx:relu"),
    "_npx_sigmoid": _unary("npx:sigmoid"),
    "_npx_fully_connected": _fully_connected,
    "_npx_convolution": _convolution,
    "_npx_pooling": _pooling,
    "_npx_batch_norm": _batch_norm,
    "_npx_activation": _activation,
    "_npx_softmax": _simple("npx:softmax"),
    "_npx_log_softmax": _simple("npx:log_softmax"),
    "_npx_reshape": _reshape,
    "_npx_embedding": _embedding,
}


def from_nnvm_json(d: dict) -> Symbol:
    """Convert a parsed legacy nnvm symbol JSON dict into a Symbol.

    Reference format: nodes with ``[nid, idx, version]`` input entries
    and string attrs (see the reference's ``src/nnvm`` JSON pass and
    ``python/mxnet/symbol/symbol.py`` load path).
    """
    nodes_json = d.get("nodes", [])
    out_nodes = []
    for n in nodes_json:
        op, name = n["op"], n["name"]
        entries = [(e[0], e[1]) for e in n.get("inputs", [])]
        if op == "null":
            out_nodes.append(_Node("null", name, [], {}))
            continue
        handler = _HANDLERS.get(op)
        if handler is None:
            raise ValueError(
                f"legacy op {op!r} (node {name!r}) is not supported by "
                "the nnvm importer; supported ops: "
                f"{sorted(_HANDLERS)}")
        table_op, keep, attrs = handler(entries, _attrs_of(n))
        out_nodes.append(
            _Node(table_op, name, [entries[i] for i in keep], attrs))
    heads = [(h[0], h[1]) for h in d.get("heads", [])]
    if not heads:
        heads = [(len(out_nodes) - 1, 0)]
    return Symbol(out_nodes, heads)

"""Symbol op wrappers, generated over the mx.np / mx.npx namespaces.

The reference text-generates per-op Symbol functions from the nnvm
registry at import (python/mxnet/symbol/register.py). Here the op
table IS the numpy-API function table: a symbol node names a function
in `mx.np` (or `mx.npx` with the "npx:" prefix) and stores its static
kwargs; evaluation applies it to NDArrays (eagerly or under a jit
trace — same funnel as every other op, ops/apply_op).
"""
from __future__ import annotations

import sys

from .symbol import Symbol, _compose

# ops whose sym wrapper takes (data) or (lhs, rhs) positional Symbols;
# everything else in kwargs is a static attr recorded on the node.
_NP_OPS = [
    # elementwise unary
    "negative", "abs", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "cbrt", "square", "reciprocal", "sign", "floor", "ceil",
    "trunc", "rint", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    # binary
    "add", "subtract", "multiply", "divide", "mod", "power", "maximum",
    "minimum", "hypot", "arctan2", "copysign",
    # comparison
    "equal", "not_equal", "greater", "greater_equal", "less",
    "less_equal", "logical_and", "logical_or", "logical_xor",
    # reduce ("var" deliberately absent: mx.sym.var is the Variable
    # constructor, as in the reference)
    "sum", "mean", "prod", "max", "min", "argmax", "argmin", "std",
    "norm",
    # linalg / contraction
    "dot", "matmul", "tensordot", "einsum",
    # shape
    "reshape", "transpose", "swapaxes", "expand_dims", "squeeze",
    "concatenate", "stack", "split", "flip", "tile", "repeat",
    "broadcast_to", "where", "clip", "take", "ravel",
    # misc
    "round", "floor_divide", "fmod", "absolute",
]

_NPX_OPS = [
    "relu", "sigmoid", "log_sigmoid", "softmax", "log_softmax",
    "leaky_relu", "activation", "fully_connected", "convolution",
    "pooling", "batch_norm", "layer_norm", "dropout", "one_hot",
    "pick", "topk", "batch_dot", "embedding", "rnn", "sequence_mask",
    "gamma", "erf", "erfinv",
]


def _make_np(opname):
    def wrapper(*inputs, name=None, **attrs):
        syms = [x for x in inputs]
        return _compose(opname, tuple(syms), name=name, **attrs)
    wrapper.__name__ = opname
    wrapper.__qualname__ = opname
    wrapper.__doc__ = f"Symbolic version of mx.np.{opname}."
    return wrapper


def _make_npx(opname):
    key = f"npx:{opname}"

    def wrapper(*inputs, name=None, **attrs):
        return _compose(key, tuple(inputs), name=name, **attrs)
    wrapper.__name__ = opname
    wrapper.__qualname__ = opname
    wrapper.__doc__ = f"Symbolic version of mx.npx.{opname}."
    return wrapper


_this = sys.modules[__name__]
__all__ = []
for _op in _NP_OPS:
    setattr(_this, _op, _make_np(_op))
    __all__.append(_op)
for _op in _NPX_OPS:
    if not hasattr(_this, _op):
        setattr(_this, _op, _make_npx(_op))
        __all__.append(_op)

_TABLE = None


def op_table():
    """name → callable over NDArrays (resolved lazily to avoid import
    cycles; unknown names fail loudly at eval time)."""
    global _TABLE
    if _TABLE is None:
        import mxnet_tpu as mx

        table = {}
        for op in _NP_OPS:
            fn = getattr(mx.np, op, None)
            if fn is None:
                fn = getattr(mx.npx, op, None)
            if fn is not None:
                table[op] = fn
        for op in _NPX_OPS:
            fn = getattr(mx.npx, op, None)
            if fn is not None:
                table[f"npx:{op}"] = fn
        table["_scalar"] = lambda value=None: value
        table["_astype"] = lambda x, dtype=None: x.astype(dtype)
        table["_flatten"] = lambda x: x.reshape((x.shape[0], -1)) \
            if x.ndim > 1 else x
        table["reshape"] = lambda x, newshape=None: x.reshape(
            tuple(newshape))
        table["zeros"] = lambda shape=None, dtype=None: mx.np.zeros(
            tuple(shape), dtype=dtype)
        table["ones"] = lambda shape=None, dtype=None: mx.np.ones(
            tuple(shape), dtype=dtype)
        table["full"] = lambda shape=None, value=None, dtype=None: \
            mx.np.full(tuple(shape), value, dtype=dtype)
        _TABLE = table
    return _TABLE

"""Symbol op wrappers, GENERATED over the mx.np / mx.npx namespaces.

The reference text-generates per-op Symbol functions from the nnvm
registry at import (python/mxnet/symbol/register.py:115-277). Here the
op table IS the numpy-API function table: every public callable in
`mx.np`, `mx.npx`, `mx.np.linalg`, `mx.np.random` and `mx.np.fft`
gets a symbol wrapper on first attribute access (PEP 562 module
__getattr__ — the lazy equivalent of the reference's import-time
codegen). A symbol node names the function (with a namespace prefix
for non-np tables) and stores its static kwargs; evaluation applies it
to NDArrays (eagerly or under a jit trace — the same funnel as every
other op, ops/apply_op).

Ops that cannot be graph nodes are listed in EXCLUDED with a reason;
accessing them raises AttributeError carrying that reason.
"""
from __future__ import annotations

import sys
import types

from .symbol import Symbol, _compose

# ---------------------------------------------------------------------
# Ops that are deliberately NOT symbolizable. Keys are opperf-style
# qualified names ("np.var", "random.seed"). The sweep test
# (tests/test_symbol_gen.py) enforces that every public op either
# symbol-round-trips or appears here.
EXCLUDED = {
    # name collision with the Variable constructor (reference parity:
    # mx.sym.var is Variable there too)
    "np.var": "mx.sym.var is the Variable constructor; compute "
              "variance via mx.sym.std(x)**2 or mean((x-mean)^2)",
    # host-data constructors — a graph leaf is mx.sym.var (or
    # zeros/ones/full for constants), not python data
    "np.array": "host-data constructor; use mx.sym.var",
    "np.asarray": "host-data constructor; use mx.sym.var",
    "np.fromiter": "consumes a python iterator; not a graph op",
    "np.genfromtxt": "reads a file; not a graph op",
    # python-value (non-array) results — graph outputs are arrays
    "np.ndim": "returns a python int; use npx.shape_array",
    "np.shape": "returns a python tuple; use npx.shape_array",
    "np.size": "returns a python int; use npx.shape_array",
    "np.get_printoptions": "printing config, not a tensor op",
    "np.set_printoptions": "printing config, not a tensor op",
    "np.get_include": "build-system helper, not a tensor op",
    "np.may_share_memory": "aliasing introspection on live buffers",
    "np.shares_memory": "aliasing introspection on live buffers",
    "np.can_cast": "dtype predicate (python bool), not a tensor op",
    "np.promote_types": "returns a dtype object, not a tensor op",
    "np.result_type": "returns a dtype object, not a tensor op",
    "np.narrow_dtype": "dtype helper, not a tensor op",
    "np.resolve_dtype": "dtype helper, not a tensor op",
    # IO / runtime state
    "np.save": "file IO side effect, not a graph op",
    "np.savez": "file IO side effect, not a graph op",
    "np.load": "file IO; not a graph op",
    "np.current_context": "runtime introspection",
    "npx.save": "file IO side effect, not a graph op",
    "npx.load": "file IO; not a graph op",
    "npx.waitall": "engine sync, not a graph op",
    "npx.set_np": "global mode switch",
    "npx.reset_np": "global mode switch",
    "npx.is_np_array": "global mode introspection",
    "npx.is_np_shape": "global mode introspection",
    "npx.current_device": "runtime introspection",
    "npx.num_gpus": "runtime introspection",
    "npx.next_key": "PRNG key state, not a graph op",
    # dispatch funnel itself (exported in every op namespace)
    "np.apply_op": "the dispatch funnel itself",
    "npx.apply_op": "the dispatch funnel itself",
    "linalg.apply_op": "the dispatch funnel itself",
    "random.apply_op": "the dispatch funnel itself",
    "fft.apply_op": "the dispatch funnel itself",
    # control flow takes python callables — not JSON-serializable;
    # the hybridize path captures python control flow by tracing
    "npx.cond": "takes python callables; hybridize traces these",
    "npx.foreach": "takes python callables; hybridize traces these",
    "npx.while_loop": "takes python callables; hybridize traces these",
    "random.seed": "global PRNG state, not a graph op",
    "random.next_key": "PRNG key state, not a graph op",
    "random.current_context": "runtime introspection",
    "random.resolve_dtype": "dtype helper, not a tensor op",
}

# Ops whose first argument is a *sequence* of arrays: the wrapper
# accepts either a sequence or varargs of Symbols, and the node records
# __pack__ so _eval re-packs the inputs into one list argument.
_SEQ_OPS = {
    "concatenate", "concat", "stack", "vstack", "hstack", "dstack",
    "column_stack", "row_stack", "lexsort", "array_equal", "block",
    "multi_dot", "multi_all_finite", "multi_sum_sq", "all_finite",
}
# but these two take a plain (non-packed) first array too — keep the
# generic calling convention for single-array use; varargs-of-arrays
# ops below take *args natively (no packing needed):
_SEQ_OPS -= {"array_equal", "all_finite"}

# Static output arity for multi-output ops: int, or callable
# (args, attrs) -> int. Everything absent defaults to 1 output.
_MULTI_OUT = {
    "modf": 2, "frexp": 2, "divmod": 2, "histogram": 2,
    "tril_indices_from": 2, "triu_indices_from": 2,
    "diag_indices_from": 2,
    "linalg.qr": 2, "linalg.eig": 2, "linalg.eigh": 2,
    "linalg.slogdet": 2, "linalg.lstsq": 4,
    "linalg.svd": lambda args, attrs: 3
    if attrs.get("compute_uv", True) else 1,
    "unique": lambda args, attrs: 1 + sum(
        bool(attrs.get(k)) for k in
        ("return_index", "return_inverse", "return_counts")),
    "meshgrid": lambda args, attrs: max(len(args), 1),
    "broadcast_arrays": lambda args, attrs: max(len(args), 1),
}


def _namespaces():
    import mxnet_tpu as mx
    return {
        "np": mx.np, "npx": mx.npx, "linalg": mx.np.linalg,
        "random": mx.np.random, "fft": mx.np.fft,
    }


def _table_key(prefix, name):
    return name if prefix == "np" else f"{prefix}:{name}"


def _make(prefix, name):
    """Build the generic symbol wrapper for one op."""
    key = _table_key(prefix, name)
    qual = f"{prefix}.{name}"
    pack = name in _SEQ_OPS
    n_out = _MULTI_OUT.get(qual, _MULTI_OUT.get(name))

    def wrapper(*args, name=None, **attrs):
        extra = {}
        if pack:
            if len(args) >= 1 and isinstance(args[0], (tuple, list)):
                # sequence form: pack exactly the sequence elements;
                # trailing positionals (e.g. an axis) stay scalar args
                seq = tuple(args[0])
                extra["__pack__"] = len(seq)
                args = seq + tuple(args[1:])
            else:
                # varargs form: symbols form the sequence, the scalar
                # tail (axis etc.) stays outside the pack
                n_sym = len(args)
                while n_sym and not isinstance(args[n_sym - 1], Symbol):
                    n_sym -= 1
                extra["__pack__"] = n_sym
        n = n_out(args, attrs) if callable(n_out) else n_out
        if n is not None and n > 1:
            extra["__num_outputs__"] = n
        return _compose(key, args, name=name, **extra, **attrs)

    wrapper.__name__ = name
    wrapper.__qualname__ = name
    wrapper.__doc__ = (f"Symbolic version of mx.{qual} "
                       f"(generated wrapper).")
    return wrapper


# wrapper cache, keyed (prefix-or-None, name). A dict — NOT setattr on
# the module — so generated names can never shadow builtins referenced
# by this module's own code (sum/max/abs/...).
_CACHE = {}


def _generate(prefix, name):
    """Resolve `name` in the op namespace(s) → symbol wrapper.

    For the top level (prefix None) the lookup order is np then npx —
    the same order op_table() resolves node names in.
    """
    if (prefix, name) in _CACHE:
        return _CACHE[(prefix, name)]
    tries = [(prefix, name)] if prefix else [("np", name), ("npx", name)]
    ns = _namespaces()
    for pre, n in tries:
        qual = f"{pre}.{n}"
        if qual in EXCLUDED:
            raise AttributeError(
                f"mx.sym has no op {n!r}: {EXCLUDED[qual]}")
        fn = getattr(ns[pre], n, None)
        if callable(fn) and not isinstance(fn, type):
            w = _make(pre, n)
            _CACHE[(prefix, name)] = w
            return w
    raise AttributeError(f"no op {name!r} in "
                         + "/".join(f"mx.{p}" for p, _ in tries))


class _SubNS(types.ModuleType):
    """mx.sym.linalg / mx.sym.random / mx.sym.fft — generated lazily."""

    def __init__(self, prefix):
        super().__init__(f"{__name__}.{prefix}")
        self._prefix = prefix
        self.__doc__ = (f"Symbolic wrappers over mx.np.{prefix} "
                        f"(generated; see symbol/_ops.py).")

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        return _generate(self._prefix, name)

    def __dir__(self):
        ns = _namespaces()[self._prefix]
        return sorted(
            n for n in dir(ns) if not n.startswith("_")
            and f"{self._prefix}.{n}" not in EXCLUDED
            and callable(getattr(ns, n, None)))


linalg = _SubNS("linalg")
random = _SubNS("random")
fft = _SubNS("fft")
sys.modules[linalg.__name__] = linalg
sys.modules[random.__name__] = random
sys.modules[fft.__name__] = fft


def __getattr__(name):  # PEP 562: top-level generated wrappers
    if name.startswith("__"):
        raise AttributeError(name)
    return _generate(None, name)


def __dir__():
    ns = _namespaces()
    names = set(globals())
    for pre in ("np", "npx"):
        names.update(
            n for n in dir(ns[pre]) if not n.startswith("_")
            and f"{pre}.{n}" not in EXCLUDED
            and callable(getattr(ns[pre], n, None))
            and not isinstance(getattr(ns[pre], n, None), type))
    names.discard("var")  # mx.sym.var is the Variable constructor
    return sorted(names)


# -- hand-written wrappers (signatures the generic form can't carry) --

def split(data, indices_or_sections, axis=0, name=None):
    """Symbolic mx.np.split — a true multi-output Symbol.

    Output arity is static (sections count or len(indices)+1), so the
    node records it via __num_outputs__ and iteration/indexing sees all
    pieces (parity: the reference's split yields N outputs).
    """
    if isinstance(indices_or_sections, int):
        n_out = indices_or_sections
        ios = indices_or_sections
    else:
        ios = list(indices_or_sections)
        n_out = len(ios) + 1
    return _compose("split", (data,), name=name,
                    indices_or_sections=ios, axis=axis,
                    __num_outputs__=n_out)


def topk(data, k=1, axis=-1, ret_typ="indices", name=None, **attrs):
    """Symbolic mx.npx.topk; ret_typ='both' yields (values, indices)."""
    n_out = 2 if ret_typ == "both" else 1
    return _compose("npx:topk", (data,), name=name, k=k, axis=axis,
                    ret_typ=ret_typ, __num_outputs__=n_out, **attrs)


__all__ = ["split", "topk", "linalg", "random", "fft", "EXCLUDED"]


def _sum_args(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def _legacy_scalar(x, op=None, scalar=0.0, reverse=False):
    """Legacy *_scalar ops (_mul_scalar, _rminus_scalar, ...)."""
    import mxnet_tpu as mx
    fn = getattr(mx.np, op)
    return fn(scalar, x) if reverse else fn(x, scalar)


def _legacy_reshape(x, shape=None, reverse=False):
    """Legacy Reshape with the reference's full special-code set
    (0/-1/-2/-3/-4, src/operator/tensor/matrix_op-inl.h
    InferReshapeShape — decoded by base.legacy_reshape_shape)."""
    from ..base import legacy_reshape_shape
    return x.reshape(legacy_reshape_shape(x.shape, shape,
                                          reverse=reverse))


def _subgraph_eval(*ins, json=None):
    """Evaluate a partitioner-folded subgraph node: the embedded DAG
    runs with its `__sg_in_k` placeholder vars bound to the node's
    inputs (see library.partition / _fold_group)."""
    from .symbol import load_json
    sub = load_json(json)
    return sub._eval({f"__sg_in_{k}": v for k, v in enumerate(ins)})[0]


class _LazyTable(dict):
    """node-op name → callable, resolved against the live namespaces on
    first miss (so ANY generated wrapper's node evals without a
    hand-kept list)."""

    def __missing__(self, key):
        import mxnet_tpu as mx
        ns = _namespaces()
        if ":" in key:
            prefix, name = key.split(":", 1)
            fn = getattr(ns.get(prefix, mx.npx), name, None)
        else:
            fn = getattr(mx.np, key, None)
            if fn is None or isinstance(fn, type):
                fn = getattr(mx.npx, key, None)
        if not callable(fn):
            raise KeyError(f"symbol op table has no entry for {key!r}")
        self[key] = fn
        return fn


_TABLE = None


def op_table():
    """name → callable over NDArrays (resolved lazily against the
    np/npx namespaces; unknown names fail loudly at eval time)."""
    global _TABLE
    if _TABLE is None:
        import mxnet_tpu as mx

        table = _LazyTable()
        table["split"] = mx.np.split
        table["_subgraph"] = _subgraph_eval
        table["_scalar"] = lambda value=None: value
        # adapters emitted by the legacy nnvm importer (legacy_json.py)
        table["_identity"] = lambda x: x
        table["_legacy_concat"] = \
            lambda *xs, axis=1: mx.np.concatenate(xs, axis=axis)
        table["_legacy_add_n"] = lambda *xs: _sum_args(xs)
        table["_legacy_scalar"] = _legacy_scalar
        table["_legacy_reshape"] = _legacy_reshape
        table["_astype"] = lambda x, dtype=None: x.astype(dtype)
        table["_flatten"] = lambda x: x.reshape((x.shape[0], -1)) \
            if x.ndim > 1 else x
        table["reshape"] = lambda x, newshape=None: x.reshape(
            tuple(newshape))
        table["zeros"] = lambda shape=None, dtype=None: mx.np.zeros(
            tuple(shape), dtype=dtype)
        table["ones"] = lambda shape=None, dtype=None: mx.np.ones(
            tuple(shape), dtype=dtype)
        table["full"] = lambda shape=None, value=None, dtype=None: \
            mx.np.full(tuple(shape), value, dtype=dtype)
        _TABLE = table
    return _TABLE


def split_v2(data, indices_or_sections, axis=0, squeeze_axis=False,
             name=None):
    """Parity: sym.split_v2 — np.split semantics plus squeeze_axis
    (each section of size 1 drops the split axis)."""
    out = split(data, indices_or_sections, axis=axis, name=name)
    if squeeze_axis:
        from .symbol import Group
        # split returns a multi-output Symbol: squeeze EVERY section
        return Group([o.squeeze(axis=axis) for o in out])
    return out

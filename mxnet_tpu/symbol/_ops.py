"""Symbol op wrappers, generated over the mx.np / mx.npx namespaces.

The reference text-generates per-op Symbol functions from the nnvm
registry at import (python/mxnet/symbol/register.py). Here the op
table IS the numpy-API function table: a symbol node names a function
in `mx.np` (or `mx.npx` with the "npx:" prefix) and stores its static
kwargs; evaluation applies it to NDArrays (eagerly or under a jit
trace — same funnel as every other op, ops/apply_op).
"""
from __future__ import annotations

import sys

from .symbol import Symbol, _compose

# ops whose sym wrapper takes (data) or (lhs, rhs) positional Symbols;
# everything else in kwargs is a static attr recorded on the node.
_NP_OPS = [
    # elementwise unary
    "negative", "abs", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "cbrt", "square", "reciprocal", "sign", "floor", "ceil",
    "trunc", "rint", "sin", "cos", "tan", "arcsin", "arccos", "arctan",
    "sinh", "cosh", "tanh", "arcsinh", "arccosh", "arctanh",
    # binary
    "add", "subtract", "multiply", "divide", "mod", "power", "maximum",
    "minimum", "hypot", "arctan2", "copysign",
    # comparison
    "equal", "not_equal", "greater", "greater_equal", "less",
    "less_equal", "logical_and", "logical_or", "logical_xor",
    # reduce ("var" deliberately absent: mx.sym.var is the Variable
    # constructor, as in the reference)
    "sum", "mean", "prod", "max", "min", "argmax", "argmin", "std",
    "norm",
    # linalg / contraction
    "dot", "matmul", "tensordot", "einsum",
    # shape ("split" gets a custom multi-output wrapper below)
    "reshape", "transpose", "swapaxes", "expand_dims", "squeeze",
    "concatenate", "stack", "flip", "tile", "repeat",
    "broadcast_to", "where", "clip", "take", "ravel",
    # misc
    "round", "floor_divide", "fmod", "absolute",
    # widened table (round-3: the reference's symbol surface covers the
    # full op registry; anything with Symbol-positional + static-kwarg
    # form lowers through the same mx.np table)
    "degrees", "radians", "deg2rad",
    "rad2deg", "exp2", "fabs", "positive", "invert",
    "isnan", "isinf", "isfinite", "isneginf", "isposinf",
    "logaddexp", "logaddexp2", "ldexp", "gcd", "lcm",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "left_shift", "right_shift",
    "true_divide", "remainder", "float_power", "heaviside",
    "nanmax", "nanmin", "nansum", "nanprod", "nanmean", "nanstd",
    "nanvar", "median", "quantile", "percentile", "average", "ptp",
    "cumsum", "cumprod", "nancumsum", "nancumprod",
    "all", "any", "count_nonzero",
    "sort", "argsort", "partition", "argpartition", "msort",
    "unique", "diff", "ediff1d", "searchsorted", "digitize",
    "trapz", "interp", "cross", "kron", "outer", "inner", "vdot",
    "trace", "diagonal", "diag", "diagflat", "tril", "triu",
    "atleast_1d", "atleast_2d", "atleast_3d",
    "vstack", "hstack", "dstack", "column_stack", "row_stack",
    "moveaxis", "rollaxis", "roll", "rot90", "fliplr", "flipud",
    "pad", "insert", "delete", "append", "resize",
    "nonzero", "flatnonzero", "argwhere", "extract", "compress",
    "take_along_axis", "sign", "signbit", "copysign", "nextafter",
    "spacing", "modf", "frexp", "trunc", "rint", "fix", "around",
    "real", "imag", "conj", "conjugate", "angle",
    "sinc", "i0", "nan_to_num", "unwrap", "gradient", "convolve",
    "correlate", "histogram", "bincount", "corrcoef", "cov",
    "polyval", "meshgrid", "indices", "unravel_index",
    "maximum", "minimum", "fmax", "fmin", "hypot",
    "greater", "greater_equal", "less", "less_equal", "not_equal",
    "equal", "logical_not", "isclose", "array_equal",
]

_NPX_OPS = [
    "relu", "sigmoid", "log_sigmoid", "softmax", "log_softmax",
    "leaky_relu", "activation", "fully_connected", "convolution",
    "pooling", "batch_norm", "layer_norm", "dropout", "one_hot",
    "pick", "topk", "batch_dot", "embedding", "rnn", "sequence_mask",
    "gamma", "erf", "erfinv",
    # widened npx table (round-3)
    "softplus", "softsign", "mish", "gelu", "silu", "hard_sigmoid",
    "hard_swish", "softmin", "masked_softmax", "masked_log_softmax",
    "deconvolution", "group_norm", "instance_norm", "rms_norm",
    "l2_normalization", "sequence_last", "sequence_reverse",
    "gather_nd", "scatter_nd", "index_add", "index_update",
    "shape_array", "reshape_like", "broadcast_like", "arange_like",
    "slice_axis", "slice_like", "boolean_mask", "one_hot",
    "ctc_loss", "multibox_prior", "roi_pooling", "flash_attention",
    "digamma", "gammaln", "rsqrt", "rcbrt",
]


def _make_np(opname):
    def wrapper(*inputs, name=None, **attrs):
        syms = [x for x in inputs]
        return _compose(opname, tuple(syms), name=name, **attrs)
    wrapper.__name__ = opname
    wrapper.__qualname__ = opname
    wrapper.__doc__ = f"Symbolic version of mx.np.{opname}."
    return wrapper


def _make_npx(opname):
    key = f"npx:{opname}"

    def wrapper(*inputs, name=None, **attrs):
        return _compose(key, tuple(inputs), name=name, **attrs)
    wrapper.__name__ = opname
    wrapper.__qualname__ = opname
    wrapper.__doc__ = f"Symbolic version of mx.npx.{opname}."
    return wrapper


def split(data, indices_or_sections, axis=0, name=None):
    """Symbolic mx.np.split — a true multi-output Symbol.

    Output arity is static (sections count or len(indices)+1), so the
    node records it via __num_outputs__ and iteration/indexing sees all
    pieces (parity: the reference's split yields N outputs).
    """
    if isinstance(indices_or_sections, int):
        n_out = indices_or_sections
        ios = indices_or_sections
    else:
        ios = list(indices_or_sections)
        n_out = len(ios) + 1
    return _compose("split", (data,), name=name,
                    indices_or_sections=ios, axis=axis,
                    __num_outputs__=n_out)


def topk(data, k=1, axis=-1, ret_typ="indices", name=None, **attrs):
    """Symbolic mx.npx.topk; ret_typ='both' yields (values, indices)."""
    n_out = 2 if ret_typ == "both" else 1
    return _compose("npx:topk", (data,), name=name, k=k, axis=axis,
                    ret_typ=ret_typ, __num_outputs__=n_out, **attrs)


_this = sys.modules[__name__]
__all__ = ["split", "topk"]
for _op in dict.fromkeys(_NP_OPS):   # de-duplicated, order-preserving
    if not hasattr(_this, _op):
        setattr(_this, _op, _make_np(_op))
        __all__.append(_op)
for _op in dict.fromkeys(_NPX_OPS):
    if not hasattr(_this, _op):
        setattr(_this, _op, _make_npx(_op))
        __all__.append(_op)

def _sum_args(xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


def _legacy_scalar(x, op=None, scalar=0.0, reverse=False):
    """Legacy *_scalar ops (_mul_scalar, _rminus_scalar, ...)."""
    import mxnet_tpu as mx
    fn = getattr(mx.np, op)
    return fn(scalar, x) if reverse else fn(x, scalar)


def _legacy_reshape(x, shape=None):
    """Legacy Reshape with the reference's special codes: 0 copies the
    input dim, -1 infers one dim (src/operator/tensor/matrix_op-inl.h
    reshape semantics; -2/-3/-4 are not supported)."""
    out = []
    for i, s in enumerate(shape):
        if s == 0:
            out.append(x.shape[i])
        elif s in (-2, -3, -4):
            raise ValueError(f"legacy reshape code {s} not supported")
        else:
            out.append(s)
    return x.reshape(tuple(out))


_TABLE = None


def op_table():
    """name → callable over NDArrays (resolved lazily to avoid import
    cycles; unknown names fail loudly at eval time)."""
    global _TABLE
    if _TABLE is None:
        import mxnet_tpu as mx

        table = {}
        for op in _NP_OPS:
            fn = getattr(mx.np, op, None)
            if fn is None:
                fn = getattr(mx.npx, op, None)
            if fn is not None:
                table[op] = fn
        for op in _NPX_OPS:
            fn = getattr(mx.npx, op, None)
            if fn is not None:
                table[f"npx:{op}"] = fn
        table["split"] = mx.np.split
        table["_scalar"] = lambda value=None: value
        # adapters emitted by the legacy nnvm importer (legacy_json.py)
        table["_identity"] = lambda x: x
        table["_legacy_concat"] = \
            lambda *xs, axis=1: mx.np.concatenate(xs, axis=axis)
        table["_legacy_add_n"] = lambda *xs: _sum_args(xs)
        table["_legacy_scalar"] = _legacy_scalar
        table["_legacy_reshape"] = _legacy_reshape
        table["_astype"] = lambda x, dtype=None: x.astype(dtype)
        table["_flatten"] = lambda x: x.reshape((x.shape[0], -1)) \
            if x.ndim > 1 else x
        table["reshape"] = lambda x, newshape=None: x.reshape(
            tuple(newshape))
        table["zeros"] = lambda shape=None, dtype=None: mx.np.zeros(
            tuple(shape), dtype=dtype)
        table["ones"] = lambda shape=None, dtype=None: mx.np.ones(
            tuple(shape), dtype=dtype)
        table["full"] = lambda shape=None, value=None, dtype=None: \
            mx.np.full(tuple(shape), value, dtype=dtype)
        _TABLE = table
    return _TABLE

"""Symbol core: serializable op DAG + shape/type inference + Executor.

Parity map (reference: python/mxnet/symbol/symbol.py over nnvm):
- `Symbol` node DAG w/ named variables          symbol.py:60 (nnvm graph)
- compose by calling op wrappers                 generated op modules
- `infer_shape` / `infer_type`                   symbol.py:1132,1222 — here
  via `jax.eval_shape` over the DAG (no FLOPs)
- `tojson` / `load` round-trip                   symbol.py:1310 (nnvm JSON)
- `bind/simple_bind` → Executor                  python/mxnet/executor.py —
  forward is one jitted XLA program; backward via mx autograd
"""
from __future__ import annotations

import json

import numpy as onp

_SYM_VERSION = 1


class _Node:
    __slots__ = ("op", "name", "inputs", "attrs")

    def __init__(self, op, name, inputs, attrs):
        self.op = op          # "null" for variables, else op-table name
        self.name = name
        self.inputs = inputs  # list of (node_id, out_index)
        self.attrs = attrs    # JSON-serializable static kwargs

    def to_dict(self):
        return {"op": self.op, "name": self.name,
                "inputs": [list(i) for i in self.inputs],
                "attrs": self.attrs}


class Symbol:
    """An output (or group of outputs) of a serializable op DAG."""

    def __init__(self, nodes, outputs):
        self._nodes = nodes            # list[_Node]; topo order
        self._outputs = list(outputs)  # list[(node_id, out_index)]

    # -- introspection -------------------------------------------------
    @property
    def name(self):
        nid, idx = self._outputs[0]
        return self._nodes[nid].name

    def attr(self, key):
        """This output node's user attribute, falling back to the
        node's reserved/op attributes (__shape__ etc.) like the
        reference's single attr namespace; None if absent."""
        nid, _ = self._outputs[0]
        node = self._nodes[nid]
        ua = node.attrs.get("__uattr__", {})
        if key in ua:
            return ua[key]
        return node.attrs.get(key)

    def list_attr(self):
        """User attributes of this output node (parity: list_attr)."""
        nid, _ = self._outputs[0]
        return dict(self._nodes[nid].attrs.get("__uattr__", {}))

    def attr_dict(self):
        """name -> user-attribute dict for every reachable node
        (parity: symbol.py attr_dict)."""
        out = {}
        for n in self._reachable():
            node = self._nodes[n]
            ua = node.attrs.get("__uattr__")
            if ua:
                out[node.name] = dict(ua)
        return out

    def list_arguments(self):
        seen, out = set(), []
        for n in self._reachable():
            node = self._nodes[n]
            if node.op == "null" and node.name not in seen:
                seen.add(node.name)
                out.append(node.name)
        return out

    def list_inputs(self):
        return self.list_arguments()

    def list_outputs(self):
        counts = {}
        for nid, _ in self._outputs:
            counts[nid] = counts.get(nid, 0) + 1
        return [f"{self._nodes[nid].name}_output{idx}" if counts[nid] > 1
                else f"{self._nodes[nid].name}_output"
                for nid, idx in self._outputs]

    def list_auxiliary_states(self):
        return []

    def get_internals(self):
        outs = [(i, 0) for i, n in enumerate(self._nodes)]
        return Symbol(self._nodes, outs)

    def __getitem__(self, idx):
        if isinstance(idx, str):
            # exact list_outputs() names resolve to their own entry
            # (incl. indexed names of multi-output nodes)
            for pos, name in enumerate(self.list_outputs()):
                if name == idx:
                    return Symbol(self._nodes, [self._outputs[pos]])
            for i, n in enumerate(self._nodes):
                if n.name == idx or f"{n.name}_output" == idx:
                    return Symbol(self._nodes, [(i, 0)])
            raise ValueError(f"no output named {idx!r}")
        return Symbol(self._nodes, [self._outputs[idx]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    def _reachable(self):
        stack = [nid for nid, _ in self._outputs]
        seen = []
        visited = set()
        while stack:
            nid = stack.pop()
            if nid in visited:
                continue
            visited.add(nid)
            seen.append(nid)
            stack.extend(i for i, _ in self._nodes[nid].inputs)
        return sorted(seen)

    def __repr__(self):
        return (f"<Symbol {self.name} "
                f"args={self.list_arguments()}>")

    # -- composition ---------------------------------------------------
    # (user attributes: see attr/list_attr/attr_dict above — op
    # kwargs under plain keys are internal and read via _nodes)

    # arithmetic sugar (maps onto op-table entries)
    def __add__(self, other):
        return _compose("add", (self, other))

    def __radd__(self, other):
        return _compose("add", (other, self))

    def __sub__(self, other):
        return _compose("subtract", (self, other))

    def __rsub__(self, other):
        return _compose("subtract", (other, self))

    def __mul__(self, other):
        return _compose("multiply", (self, other))

    def __rmul__(self, other):
        return _compose("multiply", (other, self))

    def __truediv__(self, other):
        return _compose("divide", (self, other))

    def __rtruediv__(self, other):
        return _compose("divide", (other, self))

    def __pow__(self, other):
        return _compose("power", (self, other))

    def __neg__(self):
        return _compose("negative", (self,))

    # method sugar mirroring NDArray methods
    def sum(self, axis=None, keepdims=False):
        return _compose("sum", (self,), axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False):
        return _compose("mean", (self,), axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return _compose("max", (self,), axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return _compose("min", (self,), axis=axis, keepdims=keepdims)

    def reshape(self, shape):
        return _compose("reshape", (self,), newshape=list(shape))

    def transpose(self, axes=None):
        return _compose("transpose", (self,), axes=axes)

    def squeeze(self, axis=None):
        return _compose("squeeze", (self,), axis=axis)

    def astype(self, dtype):
        return _compose("_astype", (self,), dtype=str(onp.dtype(dtype)))

    def flatten(self):
        return _compose("_flatten", (self,))

    def dot(self, other):
        return _compose("dot", (self, other))

    # -- evaluation ----------------------------------------------------
    def _eval(self, arg_arrays):
        """Walk the DAG over NDArray inputs; returns list of NDArray."""
        from . import _ops
        vals = {}
        for nid in self._topo():
            node = self._nodes[nid]
            if node.op == "null":
                if node.name not in arg_arrays:
                    raise ValueError(
                        f"missing binding for argument {node.name!r}")
                vals[nid] = (arg_arrays[node.name],)
            else:
                fn = _ops.op_table()[node.op]
                ins = [vals[i][idx] for i, idx in node.inputs]
                pack = node.attrs.get("__pack__")
                if pack:  # first `pack` inputs form one sequence arg
                    ins = [ins[:pack]] + ins[pack:]
                attrs = {k: v for k, v in node.attrs.items()
                         if not k.startswith("__")}
                out = fn(*ins, **attrs)
                vals[nid] = tuple(out) if isinstance(out, (tuple, list)) \
                    else (out,)
        return [vals[nid][idx] for nid, idx in self._outputs]

    def _topo(self):
        order, visited = [], set()

        def visit(nid):
            if nid in visited:
                return
            visited.add(nid)
            for i, _ in self._nodes[nid].inputs:
                visit(i)
            order.append(nid)

        for nid, _ in self._outputs:
            visit(nid)
        return order

    def eval(self, ctx=None, **kwargs):
        return self._eval(kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError("Symbol is not callable; use bind/eval or "
                        "gluon.SymbolBlock")

    # -- inference -----------------------------------------------------
    def infer_shape(self, **kwarg_shapes):
        arg_s, out_s, _ = self._infer(kwarg_shapes, want="shape")
        return arg_s, out_s, []

    def infer_shape_partial(self, **kwarg_shapes):
        try:
            return self.infer_shape(**kwarg_shapes)
        except Exception:
            return None, None, None

    def infer_type(self, **kwarg_dtypes):
        arg_t, out_t, _ = self._infer(kwarg_dtypes, want="dtype")
        return arg_t, out_t, []

    def _arg_decls(self):
        """Declared per-variable shape/dtype attrs (var(shape=, dtype=))."""
        decls = {}
        for n in self._nodes:
            if n.op == "null":
                decls[n.name] = (n.attrs.get("__shape__"),
                                 n.attrs.get("__dtype__"))
        return decls

    def _infer(self, kwargs, want):
        import jax
        args = self.list_arguments()
        decls = self._arg_decls()
        specs = {}
        for a in args:
            v = kwargs.get(a)
            dshape, ddtype = decls.get(a, (None, None))
            if want == "shape":
                shape = tuple(v) if v is not None else (
                    tuple(dshape) if dshape else None)
                if shape is None:
                    raise ValueError(f"shape of argument {a!r} unknown; "
                                     f"pass {a}=<shape> or declare it on "
                                     "the variable")
                dt = onp.dtype(ddtype) if ddtype else onp.float32
                specs[a] = jax.ShapeDtypeStruct(shape, dt)
            else:
                # type inference still evaluates abstractly, so shapes
                # must come from var declarations for shape-sensitive
                # graphs (the reference infers types shape-free; here
                # XLA abstract eval needs real ranks)
                shape = tuple(dshape) if dshape else (1,)
                specs[a] = jax.ShapeDtypeStruct(
                    shape, onp.dtype(v) if v is not None else (
                        onp.dtype(ddtype) if ddtype else onp.float32))

        from ..ndarray.ndarray import NDArray

        names = list(specs.keys())

        def raw(*datas):
            nd_args = {n: NDArray(d) for n, d in zip(names, datas)}
            outs = self._eval(nd_args)
            return tuple(o._data for o in outs)

        out_abs = jax.eval_shape(raw, *[specs[n] for n in names])
        if want == "shape":
            return ([tuple(specs[n].shape) for n in names],
                    [tuple(o.shape) for o in out_abs], None)
        return ([specs[n].dtype for n in names],
                [o.dtype for o in out_abs], None)

    # -- serialization -------------------------------------------------
    def tojson(self):
        reach = self._reachable()
        remap = {nid: i for i, nid in enumerate(reach)}
        nodes = []
        for nid in reach:
            n = self._nodes[nid]
            d = n.to_dict()
            d["inputs"] = [[remap[i], idx] for i, idx in n.inputs]
            nodes.append(d)
        return json.dumps({
            "mxnet_tpu_symbol_version": _SYM_VERSION,
            "nodes": nodes,
            "arg_nodes": [remap[nid] for nid in reach
                          if self._nodes[nid].op == "null"],
            "heads": [[remap[nid], idx] for nid, idx in self._outputs],
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- executor ------------------------------------------------------
    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from .executor import Executor
        return Executor(self, ctx, args or {}, args_grad, grad_req)

    def simple_bind(self, ctx=None, grad_req="write", **kwarg_shapes):
        import mxnet_tpu as mx
        arg_shapes, _, _ = self.infer_shape(**kwarg_shapes)
        names = self.list_arguments()
        args = {n: mx.np.zeros(s) for n, s in zip(names, arg_shapes)}
        grads = {n: mx.np.zeros(s) for n, s in zip(names, arg_shapes)} \
            if grad_req != "null" else None
        return self.bind(ctx, args, grads, grad_req)


# ---------------------------------------------------------------------------
# construction helpers
# ---------------------------------------------------------------------------
_name_counter = {}


def _auto_name(op):
    c = _name_counter.get(op, 0)
    _name_counter[op] = c + 1
    return f"{op}{c}"


def var(name, shape=None, dtype=None, init=None, attr=None,
        lr_mult=None, wd_mult=None, **kwargs):
    """Create a symbolic variable (parity: mx.sym.var/Variable).

    ``attr`` plus the enclosing AttrScope's attributes are stored on
    the node under the reserved ``__uattr__`` key (JSON round-trips;
    execution ignores ``__``-prefixed attrs). Like the reference,
    extra kwargs must use the dunder spelling (``__k__``); anything
    else is a ValueError, not a silently-persisted typo."""
    from .. import attribute as _attribute
    attrs = {}
    if shape is not None:
        attrs["__shape__"] = list(shape)
    if dtype is not None:
        attrs["__dtype__"] = str(onp.dtype(dtype))
    # copy: AttrScope.get may return the caller's dict by reference
    uattr = dict(_attribute.current().get(attr))
    if lr_mult is not None:
        uattr["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        uattr["__wd_mult__"] = str(wd_mult)
    for k, v in kwargs.items():
        if not (k.startswith("__") and k.endswith("__")):
            raise ValueError(
                f"Attribute name={k} is not supported. Additional "
                "attributes must start and end with double "
                "underscores, e.g. __yourattr__")
        uattr[k] = str(v)
    if uattr:
        attrs["__uattr__"] = uattr
    node = _Node("null", name, [], attrs)
    return Symbol([node], [(0, 0)])


Variable = var


def _compose(op, inputs, name=None, **attrs):
    """Build a new Symbol applying `op` to `inputs` (Symbols/scalars).

    The reserved attr `__num_outputs__` declares the op's output arity
    (default 1); multi-output ops (split, topk ret_typ='both', …) set
    it so the resulting Symbol exposes all N outputs instead of
    silently truncating to the first.
    """
    nodes = []
    in_entries = []
    remap_cache = {}

    def merge(sym):
        key = id(sym._nodes)
        if key not in remap_cache:
            base = len(nodes)
            nodes.extend(sym._nodes)
            remap = {}
            for i in range(len(sym._nodes)):
                n = nodes[base + i]
                nodes[base + i] = _Node(
                    n.op, n.name,
                    [(base + j, idx) for j, idx in n.inputs], n.attrs)
                remap[i] = base + i
            remap_cache[key] = remap
        return remap_cache[key]

    # merge by name for variables: two graphs both using var('x') must
    # share the leaf after composition
    for x in inputs:
        if isinstance(x, Symbol):
            remap = merge(x)
            nid, idx = x._outputs[0]
            in_entries.append((remap[nid], idx))
        else:
            # scalar literal → attr-carrying constant node
            cnode = _Node("_scalar", _auto_name("scalar"), [],
                          {"value": x})
            nodes.append(cnode)
            in_entries.append((len(nodes) - 1, 0))

    # unify variable leaves with identical names
    by_name = {}
    alias = {}
    for i, n in enumerate(nodes):
        if n.op == "null":
            if n.name in by_name:
                alias[i] = by_name[n.name]
            else:
                by_name[n.name] = i
    if alias:
        def fix(e):
            return (alias.get(e[0], e[0]), e[1])
        nodes = [_Node(n.op, n.name, [fix(e) for e in n.inputs], n.attrs)
                 for n in nodes]
        in_entries = [fix(e) for e in in_entries]

    from .. import attribute as _attribute
    _explicit_attr = attrs.pop("attr", None)
    _scope_attrs = dict(_attribute.current().get(_explicit_attr))
    if _scope_attrs:
        attrs = {**attrs, "__uattr__": _scope_attrs}
    node = _Node(op, name or _auto_name(op), in_entries, attrs)
    nodes = nodes + [node]
    n_out = attrs.get("__num_outputs__", 1)
    return Symbol(nodes, [(len(nodes) - 1, i) for i in range(n_out)])


def Group(symbols):
    outs = []
    nodes = []
    for s in symbols:
        base = len(nodes)
        nodes.extend(_Node(n.op, n.name,
                           [(base + i, idx) for i, idx in n.inputs],
                           n.attrs) for n in s._nodes)
        outs.extend((base + nid, idx) for nid, idx in s._outputs)
    return Symbol(nodes, outs)


def fromjson(text):
    d = json.loads(text)
    version = d.get("mxnet_tpu_symbol_version")
    if version is None:
        # Reference nnvm -symbol.json: 3-element input/head entries,
        # node_row_ptr, string-valued attrs. Route to the legacy
        # importer rather than failing with an opaque unpack error.
        if "node_row_ptr" in d or any(
                len(i) == 3 for n in d.get("nodes", [])
                for i in n.get("inputs", [])):
            from .legacy_json import from_nnvm_json
            return from_nnvm_json(d)
        raise ValueError(
            "not an mxnet_tpu symbol JSON (missing "
            "mxnet_tpu_symbol_version) and not a recognizable legacy "
            "nnvm -symbol.json")
    if version > _SYM_VERSION:
        raise ValueError(f"symbol JSON version {version} is newer than "
                         f"this build supports ({_SYM_VERSION})")
    nodes = [_Node(n["op"], n["name"],
                   [tuple(i) for i in n["inputs"]], n.get("attrs", {}))
             for n in d["nodes"]]
    return Symbol(nodes, [tuple(h) for h in d["heads"]])


load_json = fromjson


def load(fname):
    with open(fname) as f:
        return fromjson(f.read())


def zeros(shape, dtype=None, **kwargs):
    return _compose("zeros", (), shape=list(shape),
                    dtype=str(onp.dtype(dtype or onp.float32)))


def ones(shape, dtype=None, **kwargs):
    return _compose("ones", (), shape=list(shape),
                    dtype=str(onp.dtype(dtype or onp.float32)))


def full(shape, val, dtype=None, **kwargs):
    return _compose("full", (), shape=list(shape), value=val,
                    dtype=str(onp.dtype(dtype or onp.float32)))

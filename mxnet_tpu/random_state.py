"""Global RNG state.

The reference threads RNG through per-device Resource random generators
(mshadow Random, requested via ResourceRequest::kRandom). JAX is
functional: randomness is an explicit PRNG key. For the *imperative* API
(mx.np.random.*) we keep a global key that is split per call —
user-visible behavior matches the reference's stateful
`mx.np.random.seed(n)` semantics.

When a model is being traced for hybridize (see gluon/block.py), random
ops must NOT bake a concrete key into the graph (every call would replay
the same mask). The tracer installs a *trace key* here; `next_key()`
then folds a per-call counter into that traced key so each random op in
the graph gets a distinct, run-time-fresh subkey.
"""
from __future__ import annotations

import threading

import jax


class _RngState(threading.local):
    def __init__(self):
        # Lazy: PRNGKey creation initializes the JAX backend; importing
        # the library must not (callers may still select a platform).
        self.key = None
        self.trace_key = None   # set while tracing a CachedOp
        self.trace_counter = 0


_state = _RngState()
_lock = threading.Lock()

_split2_cache = None


def _split2(key):
    """Jitted key split returning an unpackable 2-tuple in ONE
    dispatch (lazy so importing never initializes a backend)."""
    global _split2_cache
    if _split2_cache is None:
        _split2_cache = jax.jit(
            lambda k: (lambda ks: (ks[0], ks[1]))(jax.random.split(k)))
    return _split2_cache(key)


def seed(seed_value: int):
    """Seed the global generator (parity: mx.np.random.seed)."""
    _state.key = jax.random.PRNGKey(seed_value)
    _state.trace_counter = 0


def get_state():
    """Host-side snapshot of the global generator: ``(key, counter)``
    where ``key`` is the raw PRNG key data as host numpy (or ``None``
    if the generator was never touched) — the piece the checkpoint
    subsystem persists so a resumed run replays the exact key stream
    (docs/CHECKPOINT.md)."""
    import numpy as onp
    key = _state.key
    return (None if key is None else onp.asarray(key),
            _state.trace_counter)


def set_state(key, trace_counter: int = 0):
    """Restore a :func:`get_state` snapshot (checkpoint resume)."""
    import jax.numpy as jnp
    _state.key = None if key is None \
        else jnp.asarray(key, jnp.uint32)
    _state.trace_counter = int(trace_counter)


def request_key(seed_value: int):
    """Raw PRNG key data for an explicit PER-REQUEST seed: host numpy
    ``(2,)`` uint32, the per-slot sampling-key format the serving
    engine threads through its jitted sampling/verify programs
    (serving/generate.py ``submit(seed=...)``). Independent of the
    global generator — two requests with the same seed draw the same
    stream no matter what else the process sampled."""
    import numpy as onp
    return onp.asarray(jax.random.PRNGKey(int(seed_value)),
                       dtype=onp.uint32)


def next_key():
    """A fresh PRNG key; trace-aware (see module docstring)."""
    if _state.trace_key is not None:
        _state.trace_counter += 1
        return jax.random.fold_in(_state.trace_key, _state.trace_counter)
    with _lock:
        if _state.key is None:
            _state.key = jax.random.PRNGKey(0)
        # one jitted call returning a 2-tuple: tuple-unpacking the raw
        # (2,2) split array would iterate it through the HOST
        # (Array.__iter__ materializes values — a silent full sync per
        # train step on remote backends), and indexing it eagerly
        # would cost three dispatches instead of one
        _state.key, sub = _split2(_state.key)
    return sub


class trace_rng:
    """Scope used by the hybridize tracer: random ops derive keys from
    the given (traced) key instead of the global concrete state."""

    def __init__(self, key):
        self._key = key
        self._saved = None

    def __enter__(self):
        self._saved = (_state.trace_key, _state.trace_counter)
        _state.trace_key = self._key
        _state.trace_counter = 0
        return self

    def __exit__(self, *exc):
        _state.trace_key, _state.trace_counter = self._saved
        return False

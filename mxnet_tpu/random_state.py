"""Global RNG state.

The reference threads RNG through per-device Resource random generators
(mshadow Random, requested via ResourceRequest::kRandom). JAX is
functional: randomness is an explicit PRNG key. For the *imperative* API
(mx.np.random.*) we keep a global key that is split per call —
user-visible behavior matches the reference's stateful
`mx.np.random.seed(n)` semantics.

When a model is being traced for hybridize (see gluon/block.py), random
ops must NOT bake a concrete key into the graph (every call would replay
the same mask). The tracer installs a *trace key* here; `next_key()`
then folds a per-call counter into that traced key so each random op in
the graph gets a distinct, run-time-fresh subkey.
"""
from __future__ import annotations

import threading

import jax


class _RngState(threading.local):
    def __init__(self):
        # Lazy: PRNGKey creation initializes the JAX backend; importing
        # the library must not (callers may still select a platform).
        self.key = None
        self.trace_key = None   # set while tracing a CachedOp
        self.trace_counter = 0


_state = _RngState()
_lock = threading.Lock()


def seed(seed_value: int):
    """Seed the global generator (parity: mx.np.random.seed)."""
    _state.key = jax.random.PRNGKey(seed_value)
    _state.trace_counter = 0


def next_key():
    """A fresh PRNG key; trace-aware (see module docstring)."""
    if _state.trace_key is not None:
        _state.trace_counter += 1
        return jax.random.fold_in(_state.trace_key, _state.trace_counter)
    with _lock:
        if _state.key is None:
            _state.key = jax.random.PRNGKey(0)
        _state.key, sub = jax.random.split(_state.key)
    return sub


class trace_rng:
    """Scope used by the hybridize tracer: random ops derive keys from
    the given (traced) key instead of the global concrete state."""

    def __init__(self, key):
        self._key = key
        self._saved = None

    def __enter__(self):
        self._saved = (_state.trace_key, _state.trace_counter)
        _state.trace_key = self._key
        _state.trace_counter = 0
        return self

    def __exit__(self, *exc):
        _state.trace_key, _state.trace_counter = self._saved
        return False

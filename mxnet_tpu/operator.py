"""Python custom-operator bridge.

Parity target: the reference's ``python/mxnet/operator.py`` (CustomOp
``operator.py:434``, CustomOpProp ``operator.py:487``, ``register``
``operator.py:710``) backed by the C++ trampoline
``src/operator/custom/custom-inl.h:52`` that runs Python callbacks on
dedicated threads and pushes them as async engine ops.

TPU-native redesign: there is no callback trampoline to cross — the
Python host *is* the frontend process, and JAX eager dispatch already
gives async semantics. A registered CustomOp executes inline on the
host thread: ``forward`` receives real NDArrays (device-backed,
asynchronous), writes its outputs through the reference's ``req``
assignment discipline, and — when autograd is recording — a tape node
is installed whose VJP replays ``backward``. This preserves the
reference contract (imperative NDArray in/out, req lists, aux states,
shape/type inference at invoke time) without the dedicated-thread
machinery the GIL-bound CUDA design needed.

Custom ops run eagerly only; inside a hybridized trace they act as a
graph break (the reference has the same property: custom ops execute
via callback even under CachedOp). For jit-compilable user kernels use
``mxnet_tpu.rtc`` (Pallas) or ``autograd.Function``.
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as onp

__all__ = [
    "CustomOp", "CustomOpProp", "register", "custom",
    "get_all_registered_operators", "get_all_registered_operators_grouped",
    "get_operator_arguments",
    "PythonOp", "NumpyOp", "NDArrayOp",
]


class CustomOp:
    """Base class for operators implemented in Python.

    Subclass and override ``forward`` / ``backward``; both receive
    lists of NDArrays and a ``req`` list ('null'|'write'|'add'|
    'inplace') consumed through :meth:`assign`.
    """

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        # default: zero gradients (parity with a no-op backward)
        for i, g in enumerate(in_grad):
            self.assign(g, req[i], g * 0)

    @staticmethod
    def assign(dst, req, src):
        """Write ``src`` into ``dst`` honoring the grad-req discipline."""
        if req == "null":
            return
        if req == "add":
            dst[()] = dst + src
        else:  # write / inplace
            dst[()] = src


class CustomOpProp:
    """Operator property: names, shapes, dtypes, and the factory.

    Mirrors the reference surface (``operator.py:487``): override
    ``list_arguments`` / ``list_outputs`` / ``list_auxiliary_states``,
    ``infer_shape`` / ``infer_type``, ``declare_backward_dependency``
    and ``create_operator``.
    """

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = bool(need_top_grad)

    # --- declarations ----------------------------------------------------
    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    # --- inference -------------------------------------------------------
    def infer_shape(self, in_shape):
        return in_shape, (in_shape[0],) * len(self.list_outputs()), ()

    def infer_type(self, in_type):
        return (in_type,
                [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    # --- factory ---------------------------------------------------------
    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


class PythonOp:
    """Deprecated pre-CustomOp interface (reference ``operator.py:46``
    — already deprecated there). Kept for import compatibility; raises
    with migration guidance on use."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            f"{type(self).__name__} is the deprecated pre-1.0 custom-op "
            "interface; subclass mxnet_tpu.operator.CustomOp / "
            "CustomOpProp and register() it instead")


class NumpyOp(PythonOp):
    """Deprecated (reference ``operator.py:155``)."""


class NDArrayOp(PythonOp):
    """Deprecated (reference ``operator.py:260``)."""


_registry: "OrderedDict[str, type]" = OrderedDict()


def register(reg_name):
    """Class decorator registering a :class:`CustomOpProp` under a name
    (parity: ``mx.operator.register``, reference ``operator.py:710``).
    """

    def do_register(prop_cls):
        if not (isinstance(prop_cls, type)
                and issubclass(prop_cls, CustomOpProp)):
            raise TypeError("register() expects a CustomOpProp subclass")
        _registry[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered_operators():
    """Names of all registered custom operators."""
    return list(_registry)


def get_all_registered_operators_grouped():
    """Custom ops have no backward-node aliases here; one group each."""
    return {name: [name] for name in _registry}


def get_operator_arguments(op_name):
    """Introspect a registered prop's declared argument names."""
    prop = _registry[op_name]()
    return {"names": prop.list_arguments(),
            "types": ["NDArray"] * len(prop.list_arguments()),
            "narg": len(prop.list_arguments())}


def _as_ndarray(x, ctx=None):
    from .ndarray.ndarray import NDArray
    from . import numpy as _np
    if isinstance(x, NDArray):
        return x
    return _np.array(x, ctx=ctx)


def custom(*data, op_type, **kwargs):
    """Invoke a registered custom op imperatively
    (parity: ``mx.nd.Custom(*data, op_type=...)``).

    ``data`` supplies the declared arguments followed by the declared
    auxiliary states (the reference's Custom op uses the same packing).
    Extra keyword arguments are forwarded to the prop constructor.
    """
    from . import autograd
    from .ndarray.ndarray import NDArray
    from . import numpy as _np

    if op_type not in _registry:
        raise KeyError(
            f"custom op {op_type!r} is not registered; known: "
            f"{list(_registry)}")
    prop = _registry[op_type](**kwargs)

    arg_names = prop.list_arguments()
    aux_names = prop.list_auxiliary_states()
    n_args, n_aux = len(arg_names), len(aux_names)
    if len(data) != n_args + n_aux:
        raise ValueError(
            f"custom op {op_type!r} declares {n_args} arguments + "
            f"{n_aux} aux states but got {len(data)} inputs")

    in_data = [_as_ndarray(d) for d in data[:n_args]]
    aux = [_as_ndarray(d) for d in data[n_args:]]

    in_shapes = [tuple(d.shape) for d in in_data]
    _, out_shapes, _ = prop.infer_shape(in_shapes)
    in_types = [onp.dtype(d.dtype) for d in in_data]
    _, out_types, _ = prop.infer_type(in_types)

    ctx = in_data[0].ctx if in_data else None
    op = prop.create_operator(ctx, in_shapes, in_types)

    with autograd.pause():
        out_data = [_np.zeros(s, dtype=t, ctx=ctx)
                    for s, t in zip(out_shapes, out_types)]
        op.forward(is_train=autograd.is_training() or autograd.is_recording(),
                   req=["write"] * len(out_data),
                   in_data=in_data, out_data=out_data, aux=aux)

    if autograd.is_recording() and any(
            autograd._on_tape(d) for d in in_data):
        fwd_ins, fwd_outs = list(in_data), list(out_data)

        def vjp_fn(cotangents):
            with autograd.pause():
                out_grad = [NDArray(c) for c in cotangents]
                in_grad = [_np.zeros(d.shape, dtype=d.dtype, ctx=ctx)
                           for d in fwd_ins]
                op.backward(req=["write"] * len(in_grad),
                            out_grad=out_grad, in_data=fwd_ins,
                            out_data=fwd_outs, in_grad=in_grad, aux=aux)
            return tuple(g._data for g in in_grad)

        autograd._record(f"Custom[{op_type}]", None, vjp_fn,
                         fwd_ins, fwd_outs)

    return out_data[0] if len(out_data) == 1 else tuple(out_data)

"""Generic class registry helpers.

Parity target: ``python/mxnet/registry.py`` (``get_register_func``
``registry.py:48``, ``get_alias_func`` ``registry.py:87``,
``get_create_func`` ``registry.py:114``). Used by optimizer/initializer
registries; exposed so user code can build its own plug-in registries
the same way.
"""
from __future__ import annotations

import json
import warnings

__all__ = ["get_registry", "get_register_func", "get_alias_func",
           "get_create_func"]

_REGISTRIES = {}


def get_registry(base_class):
    """A copy of the name→class registry for ``base_class``."""
    return dict(_REGISTRIES.get(base_class, {}))


def get_register_func(base_class, nickname):
    """Build a ``register(klass)`` decorator for ``base_class``."""
    if base_class not in _REGISTRIES:
        _REGISTRIES[base_class] = {}
    registry = _REGISTRIES[base_class]

    def register(klass, name=None):
        if not issubclass(klass, base_class):
            raise TypeError(
                f"can only register subclasses of {base_class.__name__}")
        if name is None:
            name = klass.__name__
        name = name.lower()
        if name in registry and registry[name] is not klass:
            warnings.warn(
                f"new {nickname} {klass.__name__} registered with name "
                f"{name} is overriding existing {nickname} "
                f"{registry[name].__name__}")
        registry[name] = klass
        return klass

    register.__doc__ = f"Register {nickname} to the {nickname} factory."
    return register


def get_alias_func(base_class, nickname):
    """Build an ``alias(*names)`` class decorator for ``base_class``."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg

    alias.__doc__ = f"Register {nickname} under alias names."
    return alias


def get_create_func(base_class, nickname):
    """Build a ``create(spec, **kwargs)`` factory for ``base_class``.

    ``spec`` may be an instance (returned as-is), a registered name, or
    a ``name`` / ``json-dict-string`` pair the reference accepts.
    """
    if base_class not in _REGISTRIES:
        _REGISTRIES[base_class] = {}
    registry = _REGISTRIES[base_class]

    def create(*args, **kwargs):
        if args and isinstance(args[0], base_class):
            if len(args) > 1 or kwargs:
                raise ValueError(
                    f"{nickname} is already an instance; "
                    "cannot take additional arguments")
            return args[0]
        if not args:
            raise ValueError(f"{nickname} name is required")
        name, args = args[0], args[1:]
        if isinstance(name, str) and name.startswith("{"):
            spec = json.loads(name)
            name = spec.pop("__name__" if "__name__" in spec else "name")
            kwargs = {**spec, **kwargs}
        name = name.lower()
        if name not in registry:
            raise ValueError(
                f"{name} is not a registered {nickname}; known: "
                f"{sorted(registry)}")
        return registry[name](*args, **kwargs)

    create.__doc__ = f"Create a {nickname} instance by name or spec."
    return create

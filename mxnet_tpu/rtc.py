"""mx.rtc — runtime-compiled user kernels (parity: python/mxnet/rtc.py).

The reference compiles user-supplied CUDA C strings with NVRTC at
runtime (`CudaModule(source).get_kernel(name, signature)` →
`kernel.launch(args, ctx, grid, block)`, python/mxnet/rtc.py:230 and
src/common/rtc.cc). The TPU-native equivalent of "hand me kernel
source at runtime" is Pallas: `PallasModule` accepts Python source
defining Pallas kernel functions (or the functions directly), and
`get_kernel(...)` wraps them in `pl.pallas_call` so they run on the
MXU/VPU — interpreted on CPU backends so user kernels are testable
off-TPU.

Example::

    src = '''
    def scale_add(x_ref, y_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0 + y_ref[...]
    '''
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("scale_add", out_like=0)   # out shaped like arg 0
    z = k.launch(x, y)                            # NDArray in, NDArray out

Autograd: kernels are opaque to the tape by default (like the
reference's rtc kernels). Pass ``grad=my_vjp`` to make a kernel
differentiable: ``my_vjp(cotangent, *inputs) -> tuple(grads)``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["PallasModule", "Kernel", "CudaModule"]


def _interpret_default():
    return jax.default_backend() == "cpu"


class Kernel:
    """A launchable Pallas kernel (parity: rtc.CudaKernel)."""

    def __init__(self, fn, name, out_like=0, out_shape=None,
                 out_dtype=None, grid=None, in_specs=None,
                 out_specs=None, interpret=None, grad=None):
        self._fn = fn
        self.name = name
        self._out_like = out_like
        self._out_shape = out_shape
        self._out_dtype = out_dtype
        self._grid = grid
        self._in_specs = in_specs
        self._out_specs = out_specs
        self._interpret = interpret
        self._grad = grad

    def _build_call(self, arg_datas):
        import jax.experimental.pallas as pl

        if self._out_shape is not None:
            shape = tuple(self._out_shape)
            dtype = self._out_dtype or arg_datas[0].dtype
        else:
            ref = arg_datas[self._out_like]
            shape, dtype = ref.shape, self._out_dtype or ref.dtype
        interp = self._interpret
        if interp is None:
            interp = _interpret_default()
        kwargs = {}
        if self._grid is not None:
            kwargs["grid"] = self._grid
        if self._in_specs is not None:
            kwargs["in_specs"] = self._in_specs
        if self._out_specs is not None:
            kwargs["out_specs"] = self._out_specs
        return pl.pallas_call(
            self._fn,
            out_shape=jax.ShapeDtypeStruct(shape, dtype),
            interpret=interp, **kwargs)

    def launch(self, *args):
        """Run the kernel over NDArray (or raw) operands; returns an
        NDArray. (The reference's launch takes explicit grid/block
        dims; here the grid is baked at get_kernel time and XLA/Mosaic
        handles placement.)"""
        from .ops import apply_op
        from .ndarray.ndarray import NDArray
        from . import engine

        datas = [a._data if isinstance(a, NDArray) else jnp.asarray(a)
                 for a in args]
        call = self._build_call(datas)

        if self._grad is not None:
            user_grad = self._grad

            @jax.custom_vjp
            def op(*xs):
                return call(*xs)

            def fwd(*xs):
                return call(*xs), xs

            def bwd(res, ct):
                return tuple(user_grad(ct, *res))

            op.defvjp(fwd, bwd)
            fn = op
        else:
            # opaque to autograd: sever inputs BEFORE the kernel so
            # jax.vjp never tries to linearize through pallas_call
            def fn(*xs):
                return call(*[jax.lax.stop_gradient(x) for x in xs])

        nd_args = [a if isinstance(a, NDArray)
                   else NDArray(engine.track(jnp.asarray(a)))
                   for a in args]
        return apply_op(fn, *nd_args, name=f"rtc_{self.name}")

    __call__ = launch


class PallasModule:
    """A module of runtime-supplied Pallas kernels (parity:
    rtc.CudaModule over NVRTC)."""

    def __init__(self, source=None, exports=None):
        self._fns = {}
        if callable(source):
            self._fns[source.__name__] = source
        elif isinstance(source, dict):
            self._fns.update(source)
        elif isinstance(source, str):
            import jax.experimental.pallas as pl
            namespace = {"pl": pl, "jnp": jnp, "jax": jax}
            exec(compile(source, "<rtc-source>", "exec"), namespace)
            for k, v in namespace.items():
                if callable(v) and not k.startswith("_") and \
                        k not in ("pl", "jnp", "jax"):
                    self._fns[k] = v
        elif source is not None:
            raise TypeError("source must be str, callable, or dict")
        if exports is not None:
            missing = set(exports) - set(self._fns)
            if missing:
                raise ValueError(f"source does not define {sorted(missing)}")
            self._fns = {k: self._fns[k] for k in exports}

    def list_kernels(self):
        return sorted(self._fns)

    def get_kernel(self, name, **kwargs):
        if name not in self._fns:
            raise ValueError(f"no kernel {name!r}; module defines "
                             f"{self.list_kernels()}")
        return Kernel(self._fns[name], name, **kwargs)


def CudaModule(*args, **kwargs):
    """The reference's NVRTC entry point; CUDA C cannot run on TPU."""
    raise NotImplementedError(
        "CudaModule compiles CUDA C, which has no TPU backend; write "
        "the kernel as a Pallas function and use mx.rtc.PallasModule "
        "(same runtime-compilation workflow, MXU/VPU execution)")

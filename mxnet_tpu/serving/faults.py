"""FaultInjector — the serving fleet's deterministic chaos seam.

The checkpoint subsystem proved a discipline in PR 6: route every
fallible effect through ONE seam (`checkpoint/_fs.py` LocalFS), and
fault-injection tests become deterministic wrappers instead of global
monkeypatching. This module is the serving analog. The
:class:`~mxnet_tpu.serving.Router` calls
``injector.on_dispatch(replica_idx, engine)`` immediately before every
replica dispatch; a seeded :class:`FaultInjector` turns that call into
reproducible production pathology:

- ``error``  — raise :class:`InjectedFault` from the dispatch (a
  transport/submit failure the Router must fail over);
- ``crash``  — kill the replica's worker the way a real crash does
  (``engine._fail_all``): every in-flight stream fails with
  :class:`~mxnet_tpu.serving.ReplicaFailedError`, later submits are
  rejected as a FAILED (not closed) replica;
- ``stall``  — sleep ``duration_ms`` once (a GC pause / page-in);
- ``slow``   — sleep ``duration_ms`` on every matching dispatch (a
  degraded replica).

Rules fire deterministically: ``after_n`` triggers on exactly the n-th
dispatch of the matching replica (each rule at most once), ``rate``
draws from the injector's own seeded RNG. Tests and benches may also
call :meth:`FaultInjector.crash` directly to kill a replica at a
scripted moment (``bench.py --router`` kills one mid-window).
"""
from __future__ import annotations

import random
import threading
import time

from .. import telemetry, tracing

__all__ = ["FaultInjector", "FaultRule", "InjectedFault"]

_KINDS = ("error", "crash", "stall", "slow")


class InjectedFault(RuntimeError):
    """A deterministic, injector-originated failure. Distinct from the
    organic serving errors so tests can assert provenance."""


class FaultRule:
    """One fault specification.

    Parameters
    ----------
    kind : {"error", "crash", "stall", "slow"}
    replica : int, optional
        Target replica index; ``None`` matches every replica.
    after_n : int, optional
        Fire on exactly the ``after_n``-th dispatch of a matching
        replica (1-based, counted per replica); the rule then retires.
    rate : float, optional
        Per-dispatch firing probability from the injector's seeded RNG
        (mutually exclusive with ``after_n``).
    duration_ms : float
        Sleep length for ``stall``/``slow``.
    """

    __slots__ = ("kind", "replica", "after_n", "rate", "duration_ms")

    def __init__(self, kind, replica=None, after_n=None, rate=None,
                 duration_ms=0.0):
        if kind not in _KINDS:
            raise ValueError(f"fault kind must be one of {_KINDS}, "
                             f"got {kind!r}")
        if (after_n is None) == (rate is None):
            raise ValueError("exactly one of after_n / rate is required")
        if kind in ("stall", "slow") and duration_ms <= 0:
            raise ValueError(f"{kind} fault needs duration_ms > 0")
        self.kind = kind
        self.replica = replica
        self.after_n = None if after_n is None else int(after_n)
        self.rate = None if rate is None else float(rate)
        self.duration_ms = float(duration_ms)

    def __repr__(self):
        where = "any" if self.replica is None else self.replica
        when = f"after_n={self.after_n}" if self.after_n is not None \
            else f"rate={self.rate}"
        return f"FaultRule({self.kind}, replica={where}, {when})"


class FaultInjector:
    """Seeded, deterministic dispatch-path fault source.

    Thread-safe: rule matching and the RNG draw happen under one lock;
    the injected effect (sleep, crash, raise) runs outside it so a
    stall on one replica cannot serialize the whole fleet's dispatch.
    """

    def __init__(self, rules=(), seed: int = 0):
        self._rules = list(rules)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._counts: dict = {}    # replica idx -> dispatch count
        self._retired: set = set()  # ids of fired after_n rules

    def add_rule(self, rule: FaultRule):
        with self._lock:
            self._rules.append(rule)
        return rule

    def clear(self):
        """Drop every rule (a chaos window ending; retired state and
        dispatch counts are kept so determinism is preserved)."""
        with self._lock:
            self._rules = []

    def dispatches(self, replica_idx: int) -> int:
        with self._lock:
            return self._counts.get(replica_idx, 0)

    # -- the seam -------------------------------------------------------
    def on_dispatch(self, replica_idx: int, engine):
        """Called by the Router immediately before dispatching to
        ``engine`` (replica ``replica_idx``). May sleep, crash the
        replica, or raise :class:`InjectedFault`."""
        sleep_ms = 0.0
        crash = False
        error = False
        with self._lock:
            n = self._counts.get(replica_idx, 0) + 1
            self._counts[replica_idx] = n
            for rule in self._rules:
                if rule.replica is not None and rule.replica != replica_idx:
                    continue
                if rule.after_n is not None:
                    if n != rule.after_n or id(rule) in self._retired:
                        continue
                    self._retired.add(id(rule))
                elif not (self._rng.random() < rule.rate):
                    continue
                if rule.kind in ("stall", "slow"):
                    sleep_ms += rule.duration_ms
                elif rule.kind == "crash":
                    crash = True
                else:
                    error = True
        if sleep_ms:
            telemetry.counter("serving.faults.stalls")
            tracing.flight.record("fault.stall", replica=replica_idx,
                                  sleep_ms=sleep_ms)
            time.sleep(sleep_ms / 1e3)
        if crash:
            self.crash(engine)
        if error:
            telemetry.counter("serving.faults.errors")
            tracing.flight.record("fault.error", replica=replica_idx)
            raise InjectedFault(
                f"injected dispatch error on replica {replica_idx}")

    def crash(self, engine):
        """Kill ``engine`` the way an organic worker crash does: every
        in-flight stream/future fails with ``ReplicaFailedError``
        (cause: :class:`InjectedFault`) and later submits are rejected
        as a FAILED replica. Serialized on the engine's generation lock
        when it has one, so the kill lands at a decode-step boundary —
        deterministic, never mid-XLA-dispatch."""
        telemetry.counter("serving.faults.crashes")
        tracing.flight.record("fault.crash")
        exc = InjectedFault("injected replica crash")
        exclusive = getattr(engine, "_gen_exclusive", None)
        if exclusive is not None:
            # registered-waiter acquisition: the engine's step loop
            # yields between decode steps, so the kill lands within
            # one step even under continuous traffic
            with exclusive():
                engine._fail_all(exc)
        else:
            engine._fail_all(exc)

"""InferenceEngine — dynamic micro-batching over CachedOp.

The serving-side analog of the training-path fusion work: after PRs
1-3 every dispatch-path win (shape bucketing, AOT warmup, persistent
compile cache) still serves inference one ``CachedOp`` call per
caller, per request. Under concurrent traffic that leaves the
accelerator running width-1 programs back to back while requests
queue in the GIL. Adaptive micro-batching (Clipper, NSDI'17; the
batch-coalescing half of Orca's continuous batching, OSDI'22) trades
a bounded queueing delay for multiplied throughput: concurrent
requests are coalesced into ONE padded forward on an AOT-warmed
executable and sliced back per request.

Architecture::

    caller threads ── submit() ──► bounded request queue
                                        │ (admission control:
                                        │  queue_limit, per-request
                                        │  timeout, closed-engine
                                        ▼  rejection)
                                   batcher thread
                          coalesce ≤ max_batch_size rows or
                          max_queue_ms deadline, pad to the
                          BucketingPolicy bucket, ONE
                          block.infer() dispatch, slice rows
                          back into per-request futures

Bit-identity: results depend only on the compiled width a request is
dispatched at — rows of one XLA forward are bit-independent of each
other, but a width-1 and a width-32 program may differ in the last
ulp. The engine therefore defaults to ONE fixed bucket
(``max_batch_size``), so every engine result is bit-identical to
per-request ``block(x)`` under the same bucketing policy (which pads
each lone request to the same width), regardless of how requests were
coalesced. A multi-bucket policy (``bucketing=``) trades that
width-determinism for less padded compute at low occupancy.

``MXTPU_SERVING=0`` is the escape hatch: the engine degrades to
synchronous per-request dispatch (no thread, futures arrive already
resolved) so a serving stack can be A/B'd or debugged without
restructuring callers.

Telemetry (docs/OBSERVABILITY.md): ``serving.request.latency`` /
``serving.queue.wait`` (histograms — p50/p95/p99 in
``profiler.dumps()``), ``serving.batch.occupancy``,
``serving.queue.depth`` (gauge+peak), ``serving.dispatch`` (duration),
counters ``serving.requests`` / ``batches`` / ``batch.pad`` /
``rejected_full`` / ``rejected_closed`` / ``timeouts`` / ``errors``.
"""
from __future__ import annotations

import atexit
import os
import queue
import threading
import time
import weakref
from concurrent.futures import Future

from .. import engine as _engine
from .. import telemetry
from .._bounded_worker import BoundedQueueWorker
from ..bucketing import BucketingPolicy, as_policy, pad_leaves
from ..ndarray.ndarray import NDArray

__all__ = ["InferenceEngine", "ServingError", "EngineClosedError",
           "QueueFullError", "RequestTimeoutError", "ReplicaFailedError"]


class ServingError(RuntimeError):
    """Base class for serving-layer rejections."""


class EngineClosedError(ServingError):
    """The engine was closed before (or while) the request was queued."""


class ReplicaFailedError(EngineClosedError):
    """The engine's worker/batcher thread DIED from an unexpected error
    — the replica is broken, which is categorically different from a
    deliberate ``close()``: a router (or caller) may safely retry the
    request on another replica, whereas a closed engine means shutdown.
    ``cause`` carries the original exception."""

    def __init__(self, msg, cause=None):
        super().__init__(msg)
        self.cause = cause


class QueueFullError(ServingError):
    """Admission control: the bounded request queue is at
    ``queue_limit`` — shed load at the caller instead of queueing
    unboundedly."""


class RequestTimeoutError(ServingError):
    """The request spent longer than its ``timeout_ms`` in the queue
    and was rejected instead of dispatched."""


class _Request:
    __slots__ = ("leaves", "n", "future", "t_submit", "deadline")

    def __init__(self, leaves, n, future, t_submit, deadline):
        self.leaves = leaves
        self.n = n
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline


class _Batcher(BoundedQueueWorker):
    """Consumer side of the request queue: coalesce-and-dispatch.

    Reuses the ``BoundedQueueWorker`` shutdown contract the DataLoader
    prefetcher and DeviceFeed share — plus a *graceful* phase
    (``_draining``): stop admitting, finish everything already queued,
    exit when the queue is empty. ``stop()`` stays the hard deadline;
    its drain rejects leftover requests through ``_drained`` so no
    future is ever left hanging."""

    def __init__(self, engine: "InferenceEngine", queue_limit: int):
        super().__init__(queue_limit, name="InferenceEngine.batcher")
        # the engine owns the batcher; going through a weakref here
        # lets an abandoned (un-closed) engine be collected
        self._engine = weakref.ref(engine)
        self._max_batch = engine.max_batch_size
        self._window_s = engine.max_queue_ms / 1e3
        self._draining = False
        self._carry = None
        self._inhand = None
        self.start()

    def run(self):
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — a dead batcher must not
            # strand queued futures: mark the engine FAILED (so later
            # submits see ReplicaFailedError, not a plain closed), and
            # reject everything queued, in hand, or carried
            telemetry.counter("serving.errors")
            engine = self._engine()
            if engine is not None:
                engine._fail_all(e)
                failure = engine._failure
            else:
                failure = ReplicaFailedError(
                    f"inference batcher died: {type(e).__name__}: {e}",
                    cause=e)
            inhand, self._inhand = self._inhand, None
            carry, self._carry = self._carry, None
            for r in (inhand or []) + ([carry] if carry else []):
                _reject(r.future, failure)

    def _run(self):
        while True:
            batch = self._collect()
            if batch is None:
                return
            engine = self._engine()
            if engine is None:
                for r in batch:
                    _reject(r.future, EngineClosedError(
                        "engine was garbage-collected"))
                return
            # _inhand makes the batch reachable from the crash handler:
            # a popped-but-undispatched batch must be rejected, never
            # silently dropped with hung waiters
            self._inhand = batch
            engine._dispatch(batch)
            self._inhand = None

    # -- coalescing ----------------------------------------------------
    def _expired(self, req) -> bool:
        if req.deadline is not None and time.monotonic() > req.deadline:
            telemetry.counter("serving.timeouts")
            _reject(req.future, RequestTimeoutError(
                "request expired in queue before dispatch"))
            return True
        return False

    def _collect(self):
        q = self._queue
        batch, total = [], 0
        if self._carry is not None:
            batch.append(self._carry)
            total = self._carry.n
            self._carry = None
        while not batch:
            if self._stopped:
                return None
            try:
                r = q.get(timeout=0.05)
            except queue.Empty:
                if self._draining or self._stopped \
                        or self._engine() is None:
                    # engine closed — or abandoned in a reference
                    # cycle that never ran __del__: don't spin forever
                    return None
                continue
            if not self._expired(r):
                batch.append(r)
                total = r.n
        # the queueing window opens when the batch opens: anything
        # already queued coalesces immediately (a zero window still
        # batches the backlog), then wait up to max_queue_ms for
        # co-travellers, dispatch early once full — and never sit past
        # a collected request's own deadline (dispatch-before-expiry
        # beats rejecting a request we hold)
        deadline = time.monotonic() + self._window_s
        if batch[0].deadline is not None:
            deadline = min(deadline, batch[0].deadline)
        while total < self._max_batch and not self._stopped:
            try:
                r = q.get_nowait()
            except queue.Empty:
                now = time.monotonic()
                if now >= deadline:
                    break
                if self._draining and q.empty():
                    break  # close() is waiting; don't sit out the window
                try:
                    r = q.get(timeout=min(deadline - now, 0.05))
                except queue.Empty:
                    continue
            if self._expired(r):
                continue
            if total + r.n > self._max_batch:
                self._carry = r  # opens the next batch
                break
            batch.append(r)
            total += r.n
            if r.deadline is not None and r.deadline < deadline:
                deadline = r.deadline
        return batch

    # -- shutdown ------------------------------------------------------
    def _drained(self, item):
        # hard-stop path: anything still queued is rejected, not lost
        if isinstance(item, _Request):
            telemetry.counter("serving.rejected_closed")
            _reject(item.future, EngineClosedError(
                "engine closed before the request was dispatched"))

    def close(self, timeout: float):
        """Graceful drain (finish queued work), hard stop at the
        deadline (reject what's left), join."""
        self._draining = True
        self.join(timeout=max(0.0, timeout))
        # hard phase: even if the graceful join succeeded this is a
        # cheap no-op loop; if it didn't, stop() drains + rejects and
        # enforces its own join deadline
        self.stop(timeout=min(timeout, 2.0) if timeout > 0 else 0.1)
        # _carry is the run loop's state: touch it only once the
        # thread is provably dead (a wedged-then-resuming run() would
        # otherwise dispatch the same request close just rejected); a
        # live-but-wedged batcher handles its own carry on resume —
        # _collect dispatches it immediately under the stop flag
        if not self.is_alive() and self._carry is not None:
            self._drained(self._carry)
            self._carry = None


def _reject(future, exc):
    try:
        future.set_exception(exc)
    except Exception:  # noqa: BLE001 — already resolved; nothing to do
        pass


_live_engines: "weakref.WeakSet[InferenceEngine]" = weakref.WeakSet()


@atexit.register
def _close_all_engines():
    for eng in list(_live_engines):
        try:
            eng.close(timeout=2.0)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


def _serving_enabled() -> bool:
    return os.environ.get("MXTPU_SERVING", "1").lower() \
        not in ("0", "false", "off")


class InferenceEngine:
    """Front a ``HybridBlock`` with a micro-batching request queue.

    Parameters
    ----------
    block : HybridBlock
        The model. Hybridized on first use; ``warmup()`` AOT-compiles
        every bucket so steady-state dispatch never traces or
        compiles.
    max_batch_size : int
        Row budget per dispatched forward; a coalesced batch never
        exceeds it. Requests larger than this are rejected at
        ``submit``.
    max_queue_ms : float
        Deadline for coalescing: once the oldest request in the
        forming batch has waited this long, dispatch with whatever
        arrived. 0 dispatches whatever is immediately available.
    queue_limit : int
        Bound on queued requests; beyond it ``submit`` raises
        :class:`QueueFullError` immediately (load shedding) instead of
        queueing unboundedly.
    timeout_ms : float, optional
        Default per-request queue-residency budget; a request older
        than this is rejected with :class:`RequestTimeoutError`
        instead of dispatched. ``submit(timeout_ms=...)`` overrides
        per call.
    bucketing : BucketingPolicy | str | None
        Pad-target policy for dispatched batches. Default: ONE bucket
        at ``max_batch_size`` — every forward runs the same compiled
        width, which is what makes engine results bit-identical to
        per-request ``block(x)`` under the same policy (see module
        docstring). Multi-bucket policies reduce padded compute at low
        occupancy at the cost of width-determinism.
    """

    def __init__(self, block, max_batch_size: int = 32,
                 max_queue_ms: float = 2.0, queue_limit: int = 256,
                 timeout_ms: float | None = None, bucketing=None):
        from ..gluon.block import HybridBlock
        if not isinstance(block, HybridBlock):
            raise TypeError(
                f"InferenceEngine fronts a HybridBlock (got "
                f"{type(block).__name__}); wrap plain callables in one")
        if int(max_batch_size) < 1:
            raise ValueError("max_batch_size must be >= 1")
        self.block = block
        self.max_batch_size = int(max_batch_size)
        self.max_queue_ms = float(max_queue_ms)
        self.queue_limit = max(1, int(queue_limit))
        self.timeout_ms = timeout_ms
        policy = as_policy(bucketing)
        if policy is None:
            policy = BucketingPolicy(buckets=[self.max_batch_size])
        elif policy.buckets is not None \
                and policy.buckets[-1] < max_batch_size:
            # implicit top bucket: without it, every occupancy above
            # the user's largest bucket maps to itself — one compiled
            # width (and one warmup AOT compile) per integer size up
            # to max_batch_size, unbounded width churn
            policy = BucketingPolicy(
                buckets=list(policy.buckets) + [self.max_batch_size])
        # a coalesced batch never exceeds max_batch_size, so no bucket
        # should either (an explicit ladder past it would re-pad)
        self.policy = policy.clamped(self.max_batch_size)
        self._sync = not _serving_enabled()
        self._lock = threading.Lock()
        #: serializes load_weights against the batcher's forwards so a
        #: rollover is batch-boundary atomic (a dispatched forward sees
        #: all-old or all-new weights, never a mix); uncontended cost
        #: is one lock op per BATCH, not per request
        self._swap_lock = threading.Lock()
        self._closed = False
        #: set (to a ReplicaFailedError) when the batcher thread died
        #: from an unexpected error — distinguishes a broken replica
        #: (retryable elsewhere) from a deliberate close()
        self._failure: ReplicaFailedError | None = None
        self._tmpl = None  # (spec_string, ((trailing shape, dtype), ...))
        self._spec = None
        # per-output-leaf "tracks the batch dim" mask, resolved
        # definitively at warmup by abstract shape evaluation at two
        # widths; None -> fall back to the shape[0]==width heuristic
        self._out_batched = None
        self._batcher = None if self._sync \
            else _Batcher(self, self.queue_limit)
        _live_engines.add(self)

    # -- lifecycle -----------------------------------------------------
    def warmup(self, *args):
        """AOT-compile every bucket the policy can dispatch, from one
        template request (``args`` exactly as callers will submit
        them, any batch size). After this, steady-state serving does
        zero traces and zero XLA compiles."""
        from ..gluon.block import _flatten_arrays, _rebuild
        leaves, spec = _flatten_arrays(args)
        self._adopt_template(leaves, spec)
        rows = [l[0:1] for l in leaves]
        for size in self.policy.sizes(self.max_batch_size):
            sized, _ = pad_leaves(rows, size, 1) if size > 1 \
                else (rows, 0)
            self.block.warmup(*_rebuild(spec, list(sized)))
        self._resolve_out_batched()
        return self

    def _resolve_out_batched(self):
        """Which output leaves track the batch dimension? Decided once
        by ``jax.eval_shape`` (abstract trace — no compile, no FLOPs)
        at two widths: a leaf whose leading dim follows the width is
        batched; anything else is a fixed/aggregate output. This
        replaces the per-dispatch ``shape[0] == width`` heuristic,
        which silently mis-slices a fixed output whose leading dim
        happens to equal the bucket width."""
        import jax
        import numpy as onp
        from ..gluon.block import CachedOp
        from ..random_state import next_key
        op = getattr(self.block, "_cached_op", None)
        if op is None:
            return
        entry = next((e for e in op._entries.values()
                      if e is not CachedOp._DYNAMIC), None)
        if entry is None:
            return
        key = next_key()
        key_sd = jax.ShapeDtypeStruct(key.shape, key.dtype)
        param_sds = [jax.ShapeDtypeStruct(nd.shape, nd.dtype)
                     for nd in entry.param_nds]
        trails = self._tmpl[1]

        def out_shapes(w):
            in_sds = [jax.ShapeDtypeStruct((w,) + tuple(trail),
                                           onp.dtype(dt))
                      for trail, dt in trails]
            outs, _aux = jax.eval_shape(entry.fwd, key_sd, param_sds,
                                        in_sds)
            return [tuple(o.shape) for o in outs]

        w1 = self.max_batch_size
        w2 = w1 - 1 if w1 > 1 else w1 + 1
        try:
            s1, s2 = out_shapes(w1), out_shapes(w2)
        except Exception:  # noqa: BLE001 — a forward that rejects the
            return         # probe width keeps the heuristic fallback
        self._out_batched = [
            bool(a) and bool(b) and a[0] == w1 and b[0] == w2
            for a, b in zip(s1, s2)]

    @property
    def precision(self) -> str:
        """``"int8"`` when the block carries quantize_net-produced
        int8 twins, else ``"fp32"``. Router fleets must be
        precision-homogeneous (a retried request must see one numeric
        configuration)."""
        from ..contrib.quantization import iter_quantized
        return "int8" if any(True for _ in iter_quantized(self.block)) \
            else "fp32"

    def load_weights(self, source, strict: bool = True):
        """Zero-downtime weight rollover for the micro-batching
        engine: swap the block's parameter buffers from a committed
        checkpoint (a ``CheckpointManager`` root or one step
        directory) or an in-memory ``{name: array}`` mapping, while
        traffic is live.

        The swap is batch-boundary atomic (``_swap_lock`` serializes
        it against the batcher's forwards) and recompile-free: CachedOp
        entries pass parameter buffers as runtime arguments, so
        installing same-shape/dtype buffers changes no trace. Queued
        requests are untouched; the first batch dispatched after the
        swap runs the new weights.

        On a quantize_net-produced int8 block, the checkpoint's fp32
        weights for the quantized twins are RE-QUANTIZED in place
        (per twin, under the same swap lock; the twins keep their
        calibrated activation scales) and the remaining parameters
        swap as usual — all validated before anything is installed,
        so the block is never left half fp32-new / half int8-old."""
        from .. import checkpoint as _ckpt
        from ..contrib.quantization import iter_quantized
        if self._closed:
            raise EngineClosedError("load_weights on a closed engine")
        if isinstance(source, dict):
            new_params = source
        else:
            new_params, _meta = _ckpt.read_params(source)
        t0 = telemetry.clock()
        with self._swap_lock:
            twins = list(iter_quantized(self.block))
            if not twins:
                _ckpt.swap_param_buffers(self.block.collect_params(),
                                         new_params, strict=strict)
            else:
                # validate the WHOLE plan before touching anything:
                # swap_param_buffers is already all-or-nothing for the
                # fp32 remainder, and the requantize loop below can no
                # longer fail once shapes/presence checked out here
                import numpy as onp
                plan, consumed = [], set()
                for name, q in twins:
                    src = q._src_name or name
                    wkey, bkey = f"{src}.weight", f"{src}.bias"
                    if wkey not in new_params:
                        if strict:
                            raise ValueError(
                                f"checkpoint is missing {wkey!r} for "
                                f"the quantized layer {name!r}")
                        continue
                    w = onp.asarray(new_params[wkey])
                    if w.shape != tuple(q.wq.shape):
                        raise ValueError(
                            f"checkpoint weight {wkey!r} shape "
                            f"{w.shape} does not match the quantized "
                            f"layer's {tuple(q.wq.shape)}")
                    b = new_params.get(bkey)
                    if (b is None) != (q.qbias is None):
                        raise ValueError(
                            f"checkpoint bias presence for {name!r} "
                            f"does not match the quantized layer")
                    if b is not None \
                            and onp.asarray(b).shape \
                            != tuple(q.qbias.shape):
                        raise ValueError(
                            f"checkpoint bias {bkey!r} shape does not "
                            f"match the quantized layer")
                    consumed.update((wkey, bkey))
                    plan.append((q, w, b))
                rest = {k: v for k, v in new_params.items()
                        if k not in consumed}
                # the twins' own Constant params (wq/w_scale/qbias)
                # are requantize's job, not the fp32 swap's — a
                # checkpoint from the UNQUANTIZED twin net cannot
                # cover them
                twin_prefixes = tuple(f"{name}." for name, _ in twins)
                target = {k: p for k, p
                          in self.block.collect_params().items()
                          if not k.startswith(twin_prefixes)}
                _ckpt.swap_param_buffers(target, rest, strict=strict)
                tq = telemetry.clock()
                for q, w, b in plan:
                    q.requantize(w, b)
                telemetry.hist_since("serving.quant.requantize", tq)
        telemetry.hist_since("serving.swap", t0)
        telemetry.counter("serving.weight_swaps")
        return self

    def close(self, timeout: float = 5.0):
        """Stop admission, drain the queue (dispatching what's
        already in it), join the batcher under ``timeout``; leftovers
        past the deadline are rejected, never left hanging. Idempotent;
        also invoked via ``atexit`` for engines still open at
        interpreter shutdown."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._batcher is not None:
            self._batcher.close(timeout)
        _live_engines.discard(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close(timeout=0.5)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @property
    def closed(self) -> bool:
        return self._closed

    # -- admission -----------------------------------------------------
    def _adopt_template(self, leaves, spec):
        if not leaves or any(not l.ndim for l in leaves):
            raise ValueError(
                "serving requests must carry the batch on axis 0 of "
                "every NDArray leaf (0-d/empty requests cannot be "
                "coalesced)")
        n = leaves[0].shape[0]
        if any(l.shape[0] != n for l in leaves):
            raise ValueError(
                "all request leaves must share one leading batch dim")
        tmpl = (spec.string,
                tuple((tuple(l.shape[1:]), str(l.dtype)) for l in leaves))
        with self._lock:
            if self._tmpl is None:
                self._tmpl = tmpl
                self._spec = spec
            elif self._tmpl != tmpl:
                raise ValueError(
                    f"request signature {tmpl} does not match the "
                    f"engine's template {self._tmpl}; one engine "
                    "serves one input signature (modulo batch size)")
        return n

    def submit(self, *args, timeout_ms: float | None = None) -> Future:
        """Queue one request; returns a ``concurrent.futures.Future``
        resolving to exactly what ``block(*args)`` returns (sliced out
        of the coalesced forward). Raises :class:`EngineClosedError` /
        :class:`QueueFullError` / ``ValueError`` immediately instead
        of returning a future that can never complete."""
        if self._failure is not None:
            telemetry.counter("serving.rejected_closed")
            raise ReplicaFailedError(str(self._failure),
                                     cause=self._failure.cause)
        if self._closed:
            telemetry.counter("serving.rejected_closed")
            raise EngineClosedError("submit on a closed engine")
        from ..gluon.block import _flatten_arrays
        leaves, spec = _flatten_arrays(args)
        n = self._adopt_template(leaves, spec)
        if n > self.max_batch_size:
            raise ValueError(
                f"request batch {n} exceeds max_batch_size="
                f"{self.max_batch_size}; split it client-side")
        telemetry.counter("serving.requests")
        future: Future = Future()
        if self._sync:  # MXTPU_SERVING=0: per-request dispatch
            try:
                # same swap-atomicity contract as the batcher path: a
                # forward racing load_weights sees all-old or all-new
                with self._swap_lock:
                    out = self.block(*args)
                future.set_result(out)
            except Exception as e:  # noqa: BLE001 — deliver to caller
                future.set_exception(e)
            return future
        tmo = self.timeout_ms if timeout_ms is None else timeout_ms
        req = _Request(
            leaves, n, future, telemetry.clock(),
            time.monotonic() + tmo / 1e3 if tmo is not None else None)
        try:
            self._batcher._queue.put_nowait(req)
        except queue.Full:
            telemetry.counter("serving.rejected_full")
            raise QueueFullError(
                f"request queue at queue_limit={self.queue_limit}") \
                from None
        telemetry.gauge("serving.queue.depth", self._batcher._queue.qsize())
        if self._failure is not None:
            # the batcher died while the request was being queued: its
            # drain may have missed this request — reject it ourselves
            _reject(future, ReplicaFailedError(str(self._failure),
                                               cause=self._failure.cause))
        elif self._closed:
            # close() raced the put: its drain may already have missed
            # this request, so reject it ourselves (no-op if dispatched)
            _reject(future, EngineClosedError(
                "engine closed while the request was being queued"))
        return future

    def predict(self, *args, timeout: float | None = None):
        """Blocking convenience: ``submit(*args).result(timeout)``."""
        return self.submit(*args).result(timeout)

    def _fail_all(self, exc):
        """The batcher died (or a fault was injected): mark the engine
        FAILED — later submits raise :class:`ReplicaFailedError`, not a
        plain closed — and reject every queued future so no waiter ever
        hangs on a dead replica."""
        failure = exc if isinstance(exc, ReplicaFailedError) \
            else ReplicaFailedError(
                f"inference batcher died: {type(exc).__name__}: {exc}",
                cause=exc)
        if not isinstance(exc, ReplicaFailedError):
            failure.__cause__ = exc
        self._failure = failure
        self._closed = True
        if self._batcher is not None:
            self._batcher._stopped = True  # a live-but-looping batcher
            # exits at its next queue poll; a dead one is already gone
            try:
                while True:
                    r = self._batcher._queue.get_nowait()
                    if isinstance(r, _Request):
                        _reject(r.future, failure)
            except queue.Empty:
                pass
        _live_engines.discard(self)

    # -- dispatch (batcher thread) -------------------------------------
    def _dispatch(self, batch):
        try:
            with self._swap_lock:
                self._dispatch_inner(batch)
        except Exception as e:  # noqa: BLE001 — fan the failure out
            telemetry.counter("serving.errors")
            for r in batch:
                _reject(r.future, e)

    def _dispatch_inner(self, batch):
        # Batch assembly and result slicing run on HOST numpy, not as
        # eager jax ops: jnp.concatenate compiles a fresh XLA program
        # per segment-count and a static slice compiles one per
        # (offset, length) — under varying occupancy that is unbounded
        # eager-compile churn ON the dispatch path, the exact thing
        # the engine exists to remove. numpy concat/slice moves no
        # floats through FP ops, so bit-identity is untouched; the
        # single device_put per leaf is the DeviceFeed pattern.
        import numpy as onp
        import jax.numpy as jnp
        from ..gluon.block import _flatten_arrays, _rebuild
        rows = sum(r.n for r in batch)
        target = self.policy.bucket(rows)
        if self._batcher is not None:
            # keep the depth gauge live (submit only raises it; the
            # peak field alone would read as a stuck-full queue)
            telemetry.gauge("serving.queue.depth",
                            self._batcher._queue.qsize())
        for r in batch:
            telemetry.hist_since("serving.queue.wait", r.t_submit)
        t0 = telemetry.clock()
        ctx = batch[0].leaves[0].ctx
        in_nds = []
        for j in range(len(batch[0].leaves)):
            segs = [onp.asarray(r.leaves[j]._data) for r in batch]
            if target > rows:
                last = segs[-1][-1:]
                segs.append(onp.broadcast_to(
                    last, (target - rows,) + tuple(last.shape[1:])))
            buf = segs[0] if len(segs) == 1 \
                else onp.concatenate(segs, axis=0)
            in_nds.append(NDArray(jnp.asarray(buf), ctx=ctx))
        out = self.block.infer(*_rebuild(self._spec, in_nds))
        telemetry.duration_since("serving.dispatch", t0)
        telemetry.counter("serving.batches")
        telemetry.value("serving.batch.occupancy", rows)
        if target > rows:
            telemetry.counter("serving.batch.pad", target - rows)
        out_leaves, out_spec = _flatten_arrays(
            out if isinstance(out, tuple) else (out,))
        single = not isinstance(out, tuple)
        # one D2H materialization per output leaf (the server must
        # materialize before responding anyway; onp.asarray keeps
        # bf16 as ml_dtypes — NDArray.asnumpy would upcast), then each
        # request gets zero-copy numpy views wrapped as host-resident
        # NDArrays: no per-request device op, no per-request compile
        # (every jnp op accepts a numpy-backed ._data transparently).
        # Batch-carrying leaves come from the warmup-time eval_shape
        # mask when available; the shape[0]==width heuristic is only
        # the un-warmed fallback (it can mis-slice a fixed output
        # whose leading dim collides with the bucket width).
        mask = self._out_batched

        def is_batched(i, l):
            if mask is not None and i < len(mask):
                return mask[i]
            return bool(l.ndim) and l.shape[0] == target

        host = [(onp.asarray(_engine.wait_to_read(l._data)), True)
                if is_batched(i, l) else (l, False)
                for i, l in enumerate(out_leaves)]
        off = 0
        for r in batch:
            # non-batched leaves get a fresh wrapper per request over
            # the shared (immutable-on-device) buffer: an in-place
            # NDArray op rebinds ._data on the wrapper, and a shared
            # wrapper would leak that rebind into other callers
            parts = [NDArray(h[off:off + r.n], ctx=ctx)
                     if batched else NDArray(h._data, ctx=ctx)
                     for h, batched in host]
            res = _rebuild(out_spec, parts)
            res = res[0] if single else tuple(res)
            off += r.n
            try:
                r.future.set_result(res)
            except Exception:  # noqa: BLE001 — lost to a racing
                pass           # timeout/close rejection; theirs stands
            telemetry.hist_since("serving.request.latency", r.t_submit)

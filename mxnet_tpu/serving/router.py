"""Router — a fault-tolerant serving fleet behind one ``submit()``.

One :class:`~mxnet_tpu.serving.GenerationEngine` (or
:class:`~mxnet_tpu.serving.InferenceEngine`) is one failure domain: a
crashed worker fails every in-flight stream and closes the only
engine. Serving millions of users means replicas fail *routinely*, so
the Router fronts N engine replicas with the exact submit semantics
callers already have and absorbs replica death instead of surfacing
it:

- **Join-shortest-queue balancing** — each request goes to the
  available replica with the least live load (queued requests + active
  slots: the same values the ``serving.generate.slots`` /
  ``queue.depth`` telemetry gauges publish, read per replica).
- **Health states** — per replica, ``HEALTHY`` / ``DEGRADED`` (recent
  errors or timeouts inside ``degraded_window_s``) / ``DOWN`` (worker
  dead, engine closed, or circuit open), from passive outcome tracking
  plus a cheap periodic probe thread (no model call — it checks worker
  liveness and drives breaker cooldowns even when traffic is idle).
- **Circuit breaker** — per replica, closed → open after
  ``breaker_threshold`` consecutive failures, open → half-open after
  ``breaker_cooldown_s``; a half-open replica gets exactly ONE trial
  request (success closes the breaker, failure re-opens it). A replica
  whose worker died is DOWN outright — in-process engines cannot
  resurrect, so no trial traffic is wasted on them.
- **Budget-capped retry on a different replica** — a request that
  fails because its replica broke (``ReplicaFailedError``, an injected
  dispatch fault, a replica closed mid-stream) is retried on another
  replica, up to ``max_retries`` times, with the *remaining* deadline.
  Greedy decode is deterministic, so a retry regenerates the same
  tokens — the router stream skips the prefix it already delivered and
  the caller sees one uninterrupted, token-identical stream.
- **Admission: tenant quotas, priorities, brownout shedding** — per
  tenant outstanding-request quotas (``TenantQuotaError``); under
  overload (fleet outstanding ≥ ``brownout_frac * queue_limit``) the
  lowest-priority classes are shed first (``LoadShedError``; priority
  0 is highest and sheds last) and, optionally, admitted generation
  budgets are capped to ``brownout_max_new_tokens`` (brownout: degrade
  answer length before availability); at ``queue_limit`` everything
  sheds.
- **Rolling fleet rollover** — :meth:`Router.load_weights` drains and
  swaps one replica at a time over PR 6's per-engine zero-downtime
  rollover: cordoned replicas stop taking new traffic while their
  queue drains, in-flight slots finish on the new weights, and no
  request is dropped fleet-wide.
- **Multi-tenant LoRA propagation** — ``submit(adapter=name)`` rides
  every dispatch and retry; :meth:`Router.load_adapter` /
  :meth:`unload_adapter` roll an adapter across the fleet (the
  ``load_weights`` pattern, zero retraces per engine), and a fleet
  whose adapter registries diverge is rejected AT DISPATCH — a
  cross-replica retry must be able to re-bind the same adapter on
  whichever replica catches it.

Every replica dispatch passes through the
:class:`~mxnet_tpu.serving.FaultInjector` seam (``fault_injector=``),
so each behavior above is provable with seeded, deterministic faults
(tests/test_router.py; ``bench.py --router`` kills a replica
mid-window and measures goodput/recovery — BENCH_r11.json).

Telemetry (docs/OBSERVABILITY.md): counters
``serving.router.{requests,completed,retries,replica_failures,
replica_crashes,replica_full,rejected_shed,rejected_quota,
rejected_full,rejected_closed,brownout_capped,breaker_opens,
breaker_half_opens,breaker_closes,fail_open,prefix_affinity_hits,
timeouts,errors,rollovers,probes}``, gauges
``serving.router.{outstanding,healthy_replicas}`` (with peaks), and
the ``serving.router.latency`` histogram (submit → final outcome).
"""
from __future__ import annotations

import collections
import os
import threading
import time
import weakref
from concurrent.futures import Future

from .. import telemetry, tracing
from .engine import (
    EngineClosedError, InferenceEngine, QueueFullError,
    ReplicaFailedError, RequestTimeoutError,
)
from .generate import GenerationEngine, GenerationStream

__all__ = ["Router", "RouterStream", "LoadShedError", "TenantQuotaError",
           "HEALTHY", "DEGRADED", "DOWN"]

#: health states (docs/SERVING.md "Fleet")
HEALTHY, DEGRADED, DOWN = "HEALTHY", "DEGRADED", "DOWN"
#: breaker states
_CLOSED, _OPEN, _HALF = "closed", "open", "half-open"


class LoadShedError(QueueFullError):
    """Brownout/overload shedding: the fleet rejected this request to
    protect higher-priority traffic (retry later, or at priority 0)."""


class TenantQuotaError(QueueFullError):
    """The tenant is at its outstanding-request quota."""


class RouterStream(GenerationStream):
    """A :class:`GenerationStream` with fleet provenance: ``tenant``,
    ``priority``, ``retries`` (cross-replica re-dispatches this request
    survived), and ``replicas`` (replica index per dispatch attempt).
    Token-stream semantics are unchanged — a retried request's stream
    continues seamlessly (greedy decode makes the retry prefix
    token-identical, so already-delivered tokens are skipped)."""

    def __init__(self, prompt_len, tenant, priority):
        super().__init__(prompt_len)
        self.tenant = tenant
        self.priority = priority
        self.retries = 0
        self.replicas: list = []


class _Replica:
    __slots__ = ("engine", "idx", "breaker", "opened_at", "consec",
                 "half_open_trial", "inflight", "dispatches", "failures",
                 "successes", "timeouts", "cordoned", "last_failure_at",
                 "last_error", "crash_seen")

    def __init__(self, engine, idx):
        self.engine = engine
        self.idx = idx
        self.breaker = _CLOSED
        self.opened_at = 0.0
        self.consec = 0            # consecutive failures (breaker input)
        self.half_open_trial = 0   # 1 while the single trial is out
        self.inflight = 0          # router-dispatched, not yet finished
        self.dispatches = 0
        self.failures = 0
        self.successes = 0
        self.timeouts = 0
        self.cordoned = False      # rolling rollover: prefer others
        self.last_failure_at = None
        self.last_error = None
        self.crash_seen = False


class _Req:
    __slots__ = ("payload", "max_new", "eos_id", "deadline", "tenant",
                 "priority", "retries_left", "sink", "t0", "finished",
                 "prefix_key", "sampling", "adapter")

    def __init__(self, payload, max_new, eos_id, deadline, tenant,
                 priority, retries_left, sink, t0, prefix_key=None,
                 sampling=None, adapter=None):
        self.payload = payload
        self.max_new = max_new
        self.eos_id = eos_id
        self.deadline = deadline   # absolute monotonic, or None
        self.tenant = tenant
        self.priority = priority
        self.retries_left = retries_left
        self.sink = sink           # RouterStream (generate) / Future
        self.t0 = t0
        self.finished = False
        self.prefix_key = prefix_key
        #: per-request sampling kwargs forwarded verbatim to EVERY
        #: dispatch attempt (the seed is pinned at admission, so a
        #: cross-replica retry replays the same stochastic stream and
        #: the prefix-skip stays token-identical — up to the seeded-
        #: stream schedule caveat of docs/SERVING.md: the new
        #: replica's co-tenant schedule differs, which can shift an
        #: ulp-knife-edge accept draw in rare cases; greedy retries
        #: are exact)
        self.sampling = sampling
        #: LoRA adapter name, forwarded verbatim to every dispatch
        #: attempt (registry homogeneity is checked at admission, so
        #: a cross-replica retry re-binds the same adapter and stays
        #: token-identical)
        self.adapter = adapter


class _Prober(threading.Thread):
    """Cheap periodic health sweep: worker liveness, breaker cooldowns,
    fleet gauges. No model call — the passive outcome tracking is the
    expensive signal; the probe exists so state advances (half-open
    after cooldown, DOWN on a silent death) even with zero traffic."""

    def __init__(self, router: "Router", interval_s: float):
        super().__init__(daemon=True, name="Router.prober")
        self._router = weakref.ref(router)
        self._interval = interval_s
        # NB: threading.Thread reserves the _stop name internally
        self._halt = threading.Event()
        self.start()

    def stop(self):
        self._halt.set()

    def run(self):
        while not self._halt.wait(self._interval):
            router = self._router()
            if router is None or router._closed:
                return
            try:
                router._probe_once()
            except Exception:  # noqa: BLE001 — the prober must survive
                pass
            del router


class Router:
    """Load-balance ``submit()`` across N engine replicas with health
    checks, circuit breakers, retries, load shedding, and rolling
    weight rollover (module docstring has the full semantics).

    Parameters
    ----------
    replicas : sequence of GenerationEngine | sequence of InferenceEngine
        The fleet (homogeneous: one engine kind, identically
        configured, identical weights — retry token-identity depends
        on it). The Router takes ownership: ``close()`` closes them.
    max_retries : int
        Cross-replica re-dispatch budget per request (0 disables).
    breaker_threshold : int
        Consecutive failures that open a replica's circuit.
    breaker_cooldown_s : float
        Open → half-open delay.
    degraded_window_s : float
        How long after a failure/timeout a replica reports DEGRADED.
    probe_interval_s : float
        Health-probe period.
    queue_limit : int, optional
        Fleet-wide outstanding-request bound (default: the sum of the
        replicas' own ``queue_limit``s). At the bound every submit
        sheds; from ``brownout_frac * queue_limit`` upward only
        priority 0 is admitted.
    brownout_frac : float
        Overload threshold as a fraction of ``queue_limit``.
    brownout_max_new_tokens : int, optional
        During brownout, cap admitted generation budgets to this many
        tokens (generation fleets only).
    tenant_quota : int | dict, optional
        Outstanding-request cap per tenant (int: every tenant; dict:
        per-tenant, ``None``/missing = unlimited).
    timeout_ms : float, optional
        Default end-to-end deadline per request; the *remaining*
        budget propagates to every dispatch attempt, including retries.
    fault_injector : FaultInjector, optional
        Chaos seam: consulted before every replica dispatch.
    prefix_affinity_slack : int
        How many queued requests of extra load a prefix-warm replica
        may carry and still win a ``submit(prefix_key=...)`` dispatch
        over the shortest queue (soft preference: health, breaker
        state, and larger imbalances always win).
    """

    def __init__(self, replicas, *, max_retries: int = 2,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 5.0,
                 degraded_window_s: float = 5.0,
                 probe_interval_s: float = 0.5,
                 queue_limit=None, brownout_frac: float = 0.8,
                 brownout_max_new_tokens=None, tenant_quota=None,
                 timeout_ms=None, fault_injector=None,
                 prefix_affinity_slack: int = 4):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("Router needs at least one replica")
        if all(isinstance(e, GenerationEngine) for e in replicas):
            self._mode = "generate"
        elif all(isinstance(e, InferenceEngine) for e in replicas):
            self._mode = "infer"
        else:
            raise TypeError(
                "replicas must be a homogeneous fleet of "
                "GenerationEngine or InferenceEngine instances")
        precisions = {getattr(e, "precision", "fp32") for e in replicas}
        if len(precisions) > 1:
            # a retried request re-runs on ANOTHER replica; mixing
            # fp32 and int8 replicas would make the retry's output
            # depend on which replica caught it — token-identity and
            # the bounded-divergence contract both break
            raise TypeError(
                f"replicas must be precision-homogeneous, got "
                f"{sorted(precisions)} (replica capabilities: "
                f"{self._fleet_capabilities(replicas)})")
        specs = {getattr(e, "speculation", "off") for e in replicas}
        if len(specs) > 1:
            # same rule for the speculation config (the draft model
            # and spec_k): a retried STOCHASTIC request replays its
            # seed, and its committed stream depends on the
            # draft/spec_k key-consumption schedule — a draft-model-
            # heterogeneous fleet would make the retry's tokens depend
            # on which replica caught it
            raise TypeError(
                f"replicas must be speculation-homogeneous, got "
                f"{sorted(specs)} (replica capabilities: "
                f"{self._fleet_capabilities(replicas)})")
        meshes = {getattr(e, "mesh_config", "off") for e in replicas}
        if len(meshes) > 1:
            # and for the mesh layout (shape included): a retried
            # request must replay the IDENTICAL numeric config, and a
            # tensor-parallel replica's logits differ from an
            # unsharded one's in the tp partial-sum reduction order —
            # token-identity across a retry only holds when every
            # replica computes the same way
            raise TypeError(
                f"replicas must be mesh-homogeneous (same mesh_layout "
                f"and mesh shape), got {sorted(meshes)} (replica "
                f"capabilities: {self._fleet_capabilities(replicas)})")
        loras = {getattr(e, "lora", "off") for e in replicas}
        if len(loras) > 1:
            # and for the LoRA bank config: an adapter= binding only
            # means the same thing fleet-wide when every replica's
            # bank has the same rank/capacity — a retry must be able
            # to land anywhere (per-NAME registry homogeneity is
            # enforced per dispatch; this is the structural half)
            raise TypeError(
                f"replicas must be LoRA-config-homogeneous, got "
                f"{sorted(loras)} (replica capabilities: "
                f"{self._fleet_capabilities(replicas)})")
        self._replicas = [_Replica(e, i) for i, e in enumerate(replicas)]
        self.max_retries = int(max_retries)
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.degraded_window_s = float(degraded_window_s)
        self.queue_limit = int(queue_limit) if queue_limit is not None \
            else sum(e.queue_limit for e in replicas)
        if not 0.0 < float(brownout_frac) <= 1.0:
            raise ValueError("brownout_frac must be in (0, 1]")
        self._brownout_at = max(1, int(float(brownout_frac)
                                       * self.queue_limit))
        self.brownout_max_new_tokens = brownout_max_new_tokens
        self._tenant_quota = tenant_quota
        self.timeout_ms = timeout_ms
        self._faults = fault_injector
        self.prefix_affinity_slack = int(prefix_affinity_slack)
        #: prefix_key -> replica idx that last held that prefix's
        #: pages (bounded FIFO; a soft routing hint, never load-bearing)
        self._affinity: "collections.OrderedDict" = \
            collections.OrderedDict()
        self._affinity_cap = 4096
        self._lock = threading.Lock()
        self._outstanding = 0
        self._tenant_out: dict = {}
        #: router-level adapter pins: name -> count of in-flight
        #: requests bound to it (pins survive retries — the engines'
        #: per-replica pin only covers the replica actually serving,
        #: but a cross-replica retry must be able to re-bind the
        #: adapter on ANY replica, so a fleet unload defers while any
        #: router request holds the name)
        self._adapter_inflight: dict = {}
        #: adapter names whose fleet-wide unload is deferred behind
        #: the pins above (new submits with them are rejected now)
        self._adapter_draining: set = set()
        #: drained names whose rolling unload is waiting for the
        #: prober thread (a stream-finish callback may hold an engine
        #: worker's step lock, where running the roll inline could
        #: deadlock against a load_adapter waiting on that engine's
        #: step boundary under the roll lock)
        self._adapter_drain_pending: set = set()
        #: serializes fleet-wide adapter rolls — a concurrent
        #: load_adapter/unload_adapter pair on one name must not
        #: interleave per replica, or the two rolls can finish in
        #: opposite orders on different replicas and leave the name
        #: PERSISTENTLY heterogeneous with both calls reporting
        #: success
        self._adapter_roll_lock = threading.Lock()
        self._closed = False
        self._prober = _Prober(self, float(probe_interval_s))

    @staticmethod
    def _fleet_capabilities(engines):
        """Per-replica capability summary for heterogeneity errors —
        names what each engine actually does instead of leaving the
        caller to diff constructors (the shared submit-kwarg-error
        discipline, fleet-shaped)."""
        caps = []
        for i, e in enumerate(engines):
            fn = getattr(e, "capabilities", None)
            caps.append(f"[{i}] {fn() if callable(fn) else 'n/a'}")
        return "; ".join(caps)

    # -- lifecycle -----------------------------------------------------
    @property
    def replicas(self):
        """The fleet's engines, in replica-index order."""
        return [rep.engine for rep in self._replicas]

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def outstanding(self) -> int:
        """Requests admitted and not yet finished, fleet-wide."""
        return self._outstanding

    def warmup(self, *args):
        """AOT-warm every live replica (generation fleets take no
        args; inference fleets forward ``args`` to each engine's
        ``warmup``)."""
        for rep in self._replicas:
            if not rep.engine.closed:
                rep.engine.warmup(*args)
        return self

    def close(self, timeout: float = 5.0, close_replicas: bool = True):
        """Stop admission, stop the prober, and (by default) close
        every replica — their drain/reject semantics apply, so no
        stream or future is ever left hanging. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._prober.stop()
        if close_replicas:
            for rep in self._replicas:
                try:
                    rep.engine.close(timeout)
                except Exception:  # noqa: BLE001 — close the rest
                    pass
        self._prober.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- health / breaker ----------------------------------------------
    def _replica_load(self, rep: _Replica):
        """Live load key (JSQ): queued + active on the engine — the
        same values the slot/queue-depth telemetry gauges publish —
        tie-broken by router-side inflight, then index (deterministic)."""
        eng = rep.engine
        worker = getattr(eng, "_worker", None)
        if worker is None:
            worker = getattr(eng, "_batcher", None)
        queued = worker._queue.qsize() if worker is not None else 0
        return (queued + getattr(eng, "_n_active", 0), rep.inflight,
                rep.idx)

    def _dead(self, rep: _Replica) -> bool:
        """Worker died or engine deliberately closed: permanently out
        (an in-process engine cannot resurrect — no trial traffic)."""
        return rep.engine._failure is not None or rep.engine.closed

    def _pick(self, exclude, affinity=None):
        """Select the dispatch target: the half-open trial slot first
        (the breaker can only close by observing a success), else the
        least-loaded closed-breaker replica; cordoned replicas (mid-
        rollover) are used only when nothing else is available. Last
        resort is FAIL-OPEN: when every live replica's breaker is
        open, route to the least-loaded one anyway — shedding every
        request because the whole fleet tripped (e.g. a retry burst
        meeting a transient error spike) would turn a partial outage
        into a total one; a success then closes the breaker.

        ``affinity`` is a SOFT prefix-affinity hint: among the healthy
        closed-breaker candidates, the replica already holding that
        prefix's KV pages wins as long as its queued load is within
        ``prefix_affinity_slack`` of the shortest queue — a warm
        prefix beats a marginally shorter queue, but health, breaker
        state, cordons, and real imbalance always win."""
        now = time.monotonic()
        with self._lock:
            half = best = best_cord = best_open = aff = None
            best_load = best_cord_load = best_open_load = aff_load = None
            for rep in self._replicas:
                if rep.idx in exclude or self._dead(rep):
                    continue
                if rep.breaker == _OPEN \
                        and now - rep.opened_at >= self.breaker_cooldown_s:
                    rep.breaker = _HALF
                    rep.half_open_trial = 0
                    telemetry.counter(
                        "serving.router.breaker_half_opens")
                    tracing.flight.record("router.breaker_half_open",
                                          replica=rep.idx)
                if rep.breaker == _HALF and rep.half_open_trial == 0:
                    if half is None:
                        half = rep
                    continue
                load = self._replica_load(rep)
                if rep.breaker in (_OPEN, _HALF):
                    if best_open is None or load < best_open_load:
                        best_open, best_open_load = rep, load
                elif rep.cordoned:
                    if best_cord is None or load < best_cord_load:
                        best_cord, best_cord_load = rep, load
                else:
                    if rep.idx == affinity:
                        aff, aff_load = rep, load
                    if best is None or load < best_load:
                        best, best_load = rep, load
            if half is not None:
                half.half_open_trial = 1
                return half
            if aff is not None and \
                    aff_load[0] <= best_load[0] + self.prefix_affinity_slack:
                if aff is not best:
                    # count only dispatches the hint actually CHANGED —
                    # an idle fleet where JSQ already picks the warm
                    # replica must not read as 100% affinity routing
                    telemetry.counter(
                        "serving.router.prefix_affinity_hits")
                return aff
            if best is not None:
                return best
            if best_cord is not None:
                return best_cord
            if best_open is not None:
                telemetry.counter("serving.router.fail_open")
            return best_open

    def _record_failure(self, rep: _Replica, exc):
        telemetry.counter("serving.router.replica_failures")
        now = time.monotonic()
        opened = False
        with self._lock:
            rep.failures += 1
            rep.consec += 1
            rep.last_failure_at = now
            rep.last_error = exc
            if rep.breaker == _HALF:
                rep.breaker = _OPEN
                rep.opened_at = now
                rep.half_open_trial = 0
                telemetry.counter("serving.router.breaker_opens")
                opened = True
            elif rep.breaker == _CLOSED \
                    and rep.consec >= self.breaker_threshold:
                rep.breaker = _OPEN
                rep.opened_at = now
                telemetry.counter("serving.router.breaker_opens")
                opened = True
        if opened:
            # incident post-mortem — dumped OUTSIDE the router lock
            # (the dump may write a file when MXTPU_FLIGHT_DIR is set)
            tracing.flight.dump(
                "router.breaker_open", replica=rep.idx,
                consecutive_failures=rep.consec,
                error=f"{type(exc).__name__}: {exc}")

    def _record_success(self, rep: _Replica):
        with self._lock:
            rep.successes += 1
            rep.consec = 0
            if rep.breaker in (_HALF, _OPEN):
                # a real success is the definitive health signal — it
                # closes a half-open (trial) AND an open (fail-open
                # dispatch) breaker
                rep.breaker = _CLOSED
                rep.half_open_trial = 0
                telemetry.counter("serving.router.breaker_closes")
                tracing.flight.record("router.breaker_close",
                                      replica=rep.idx)

    def _record_timeout(self, rep: _Replica):
        # a deadline miss marks the replica DEGRADED (slow) but never
        # trips the breaker: the deadline may simply have been tight.
        # An inconclusive half-open trial returns its slot so the next
        # request can probe again.
        with self._lock:
            rep.timeouts += 1
            rep.last_failure_at = time.monotonic()
            if rep.breaker == _HALF:
                rep.half_open_trial = 0

    def _abort_trial(self, rep: _Replica):
        """Return an unused half-open trial slot (the dispatch never
        reached the replica — e.g. its queue was full)."""
        with self._lock:
            if rep.breaker == _HALF:
                rep.half_open_trial = 0

    def _probe_once(self):
        telemetry.counter("serving.router.probes")
        now = time.monotonic()
        healthy = 0
        silent_dead = []
        with self._lock:
            for rep in self._replicas:
                eng = rep.engine
                worker = getattr(eng, "_worker", None)
                if worker is None:
                    worker = getattr(eng, "_batcher", None)
                dead_now = (worker is not None
                            and not worker.is_alive()
                            and not eng.closed
                            and eng._failure is None)
                if dead_now:
                    # silent death: the worker left no failure record
                    # (a BaseException escaped its handler, or the
                    # thread was torn down externally) — without this
                    # check the corpse reads HEALTHY and JSQ keeps
                    # routing to it
                    silent_dead.append(rep)
                if eng._failure is not None and not rep.crash_seen:
                    rep.crash_seen = True
                    rep.last_error = eng._failure
                    telemetry.counter("serving.router.replica_crashes")
                if rep.breaker == _OPEN and not self._dead(rep) \
                        and now - rep.opened_at >= self.breaker_cooldown_s:
                    rep.breaker = _HALF
                    rep.half_open_trial = 0
                    telemetry.counter("serving.router.breaker_half_opens")
                    tracing.flight.record("router.breaker_half_open",
                                          replica=rep.idx)
                if rep.breaker == _CLOSED and not self._dead(rep) \
                        and not dead_now:
                    healthy += 1
        # declare the deaths OUTSIDE the router lock: _fail_all fires
        # stream watchers whose retry path re-enters it
        for rep in silent_dead:
            exc = ReplicaFailedError(
                "replica worker died silently (thread not alive)")
            exclusive = getattr(rep.engine, "_gen_exclusive", None)
            if exclusive is not None:
                with exclusive():
                    rep.engine._fail_all(exc)
            else:
                rep.engine._fail_all(exc)
        telemetry.gauge("serving.router.healthy_replicas", healthy)
        self._run_pending_drains()

    def _run_pending_drains(self):
        """Deferred fleet unloads whose last router pin dropped —
        executed here on the prober thread, never inline in the
        releasing thread (a stream-finish callback may hold an engine
        worker's step lock, where blocking on the roll lock could
        deadlock against a ``load_adapter`` waiting on that same
        engine's step boundary)."""
        while True:
            with self._lock:
                if not self._adapter_drain_pending:
                    return
                name = self._adapter_drain_pending.pop()
            self._unload_adapter_now(name)

    def health(self) -> dict:
        """Snapshot per replica: ``{idx: {state, breaker, inflight,
        dispatches, failures, successes, timeouts, cordoned, load}}``
        with ``state`` in {HEALTHY, DEGRADED, DOWN}."""
        now = time.monotonic()
        out = {}
        with self._lock:
            for rep in self._replicas:
                if self._dead(rep) or rep.breaker == _OPEN:
                    state = DOWN
                elif rep.breaker == _HALF or (
                        rep.last_failure_at is not None
                        and now - rep.last_failure_at
                        < self.degraded_window_s):
                    state = DEGRADED
                else:
                    state = HEALTHY
                out[rep.idx] = {
                    "state": state, "breaker": rep.breaker,
                    "inflight": rep.inflight,
                    "dispatches": rep.dispatches,
                    "failures": rep.failures,
                    "successes": rep.successes,
                    "timeouts": rep.timeouts,
                    "cordoned": rep.cordoned,
                    "load": self._replica_load(rep)[0],
                }
        return out

    # -- admission -----------------------------------------------------
    def _quota_for(self, tenant):
        q = self._tenant_quota
        if q is None:
            return None
        if isinstance(q, dict):
            return q.get(tenant)
        return int(q)

    def _admit(self, tenant, priority, max_new, adapter=None):
        """Shedding + quota gate; reserves one outstanding slot and —
        atomically with it — the request's router-level adapter pin,
        so an ``unload_adapter`` can never slip between validation and
        admission (the pin defers the fleet unload until the last
        bound request releases). Returns the (possibly
        brownout-capped) generation budget."""
        with self._lock:
            if adapter is not None and adapter in self._adapter_draining:
                raise ValueError(
                    f"submit() adapter={adapter!r} is unloading "
                    f"fleet-wide (pinned by in-flight requests); it "
                    f"no longer accepts new submits")
            out = self._outstanding
            if out >= self.queue_limit:
                telemetry.counter("serving.router.rejected_shed")
                raise LoadShedError(
                    f"fleet at queue_limit={self.queue_limit} "
                    f"(outstanding={out}); all priorities shed")
            if out >= self._brownout_at:
                if priority > 0:
                    telemetry.counter("serving.router.rejected_shed")
                    raise LoadShedError(
                        f"brownout at outstanding={out} (>= "
                        f"{self._brownout_at}): shedding priority "
                        f"{priority}; only priority 0 admitted")
                if self.brownout_max_new_tokens is not None \
                        and max_new is not None \
                        and max_new > self.brownout_max_new_tokens:
                    max_new = int(self.brownout_max_new_tokens)
                    telemetry.counter("serving.router.brownout_capped")
            quota = self._quota_for(tenant)
            if quota is not None \
                    and self._tenant_out.get(tenant, 0) >= quota:
                telemetry.counter("serving.router.rejected_quota")
                raise TenantQuotaError(
                    f"tenant {tenant!r} at quota={quota} outstanding "
                    f"requests")
            self._outstanding = out + 1
            self._tenant_out[tenant] = \
                self._tenant_out.get(tenant, 0) + 1
            if adapter is not None:
                self._adapter_inflight[adapter] = \
                    self._adapter_inflight.get(adapter, 0) + 1
            telemetry.gauge("serving.router.outstanding",
                            self._outstanding)
        return max_new

    def _release(self, req: _Req) -> bool:
        """Undo the admission reservation; returns False if the
        request was already finished (idempotence — the single place
        the finished flag and the outstanding/tenant accounting
        change together). Dropping the last router-level pin on a
        draining adapter queues the deferred fleet-wide unload for
        the prober thread."""
        with self._lock:
            if req.finished:
                return False
            req.finished = True
            self._outstanding -= 1
            n = self._tenant_out.get(req.tenant, 1) - 1
            if n <= 0:
                self._tenant_out.pop(req.tenant, None)
            else:
                self._tenant_out[req.tenant] = n
            if getattr(req, "adapter", None) is not None:
                a = req.adapter
                left = self._adapter_inflight.get(a, 1) - 1
                if left <= 0:
                    self._adapter_inflight.pop(a, None)
                    if a in self._adapter_draining:
                        # keep the draining mark (no submit can
                        # re-pin the name) and hand the roll to the
                        # prober thread: this release may run in a
                        # stream-finish callback under an engine
                        # worker's step lock, where taking the roll
                        # lock could deadlock against a load_adapter
                        # waiting on that engine's step boundary
                        self._adapter_drain_pending.add(a)
                else:
                    self._adapter_inflight[a] = left
            telemetry.gauge("serving.router.outstanding",
                            self._outstanding)
        return True

    # -- submit --------------------------------------------------------
    def submit(self, *args, max_new_tokens=None, eos_id=None,
               timeout_ms=None, tenant: str = "default",
               priority: int = 0, prefix_key=None, temperature=None,
               top_k=None, top_p=None, seed=None, adapter=None,
               trace=None):
        """Queue one request on the fleet.

        Generation fleets take exactly one positional ``prompt`` and
        return a :class:`RouterStream`; inference fleets take the
        request args and return a ``Future``. ``tenant`` scopes the
        quota, ``priority`` (0 = highest) orders load shedding.
        ``prefix_key`` is an opaque caller-chosen label for the
        request's shared prompt prefix (e.g. a system-prompt id):
        requests with the same key are soft-biased toward the replica
        that served that key last, so its paged-KV prefix cache stays
        warm — health, breaker state, and join-shortest-queue still
        win (``serving.router.prefix_affinity_hits`` counts the
        dispatches the hint changed).
        ``temperature``/``top_k``/``top_p``/``seed`` are the engines'
        per-request sampling knobs, forwarded to every dispatch; a
        stochastic request without an explicit seed gets one pinned at
        admission, so a cross-replica retry replays the identical
        stream and the prefix-skip stays token-identical.
        ``adapter`` names a LoRA adapter the request decodes under
        (generation fleets; ``Router.load_adapter`` installs it
        fleet-wide): the name must resolve on EVERY live replica —
        the fleet's registries are compared at dispatch and a
        heterogeneous fleet is rejected, because a cross-replica
        retry must be able to re-bind the same adapter anywhere.
        ``trace`` arms per-request tracing (generation fleets):
        ``True`` forces a span trace for this request, ``False``
        suppresses it, ``None`` defers to the ``MXTPU_TRACING``
        process default. The ONE trace object follows the request
        across replica retry hops, so ``stream.trace()`` reconstructs
        the full fleet-level lifecycle including the hop.
        Raises :class:`EngineClosedError` / :class:`LoadShedError` /
        :class:`TenantQuotaError` / :class:`QueueFullError` /
        ``ValueError`` immediately, never via a hung stream."""
        if self._closed:
            telemetry.counter("serving.router.rejected_closed")
            raise EngineClosedError("submit on a closed Router")
        tmo = self.timeout_ms if timeout_ms is None else timeout_ms
        deadline = time.monotonic() + tmo / 1e3 if tmo is not None \
            else None
        if self._mode == "generate":
            if len(args) != 1:
                raise TypeError(
                    "a generation fleet's submit takes exactly one "
                    "positional prompt")
            lead = self._replicas[0].engine
            prompt, max_new, eos = lead._validate(
                args[0], max_new_tokens, eos_id)
            temp, tk, tp, seed = lead._validate_sampling(
                temperature, top_k, top_p, seed)
            if adapter is not None:
                self._validate_adapter(adapter)
            sampling = None
            if temp > 0:
                if seed is None:
                    # pin the seed NOW: a retry must replay the exact
                    # stochastic stream on the next replica
                    seed = int.from_bytes(os.urandom(4), "little")
                sampling = {"temperature": temp, "top_k": tk,
                            "top_p": tp, "seed": seed}
            max_new = self._admit(tenant, priority, max_new,
                                  adapter=adapter)
            sink = RouterStream(int(prompt.size), tenant, priority)
            tr = tracing.start_trace(trace, source="router",
                                     tenant=tenant,
                                     prompt_len=int(prompt.size),
                                     max_new=max_new)
            if tr is not None:
                sink._trace = tr
            req = _Req(prompt, max_new, eos, deadline, tenant, priority,
                       self.max_retries, sink, telemetry.clock(),
                       prefix_key=prefix_key, sampling=sampling,
                       adapter=adapter)
        else:
            if max_new_tokens is not None or eos_id is not None \
                    or temperature is not None or top_k is not None \
                    or top_p is not None or seed is not None \
                    or adapter is not None:
                raise TypeError(
                    "max_new_tokens/eos_id, the sampling knobs and "
                    "adapter= apply to generation fleets only")
            self._admit(tenant, priority, None)
            sink = Future()
            sink.tenant, sink.priority = tenant, priority
            sink.retries, sink.replicas = 0, []
            req = _Req(args, None, None, deadline, tenant, priority,
                       self.max_retries, sink, telemetry.clock(),
                       prefix_key=prefix_key)
        telemetry.counter("serving.router.requests")
        try:
            self._dispatch(req, frozenset(), inline=True)
        except BaseException:
            self._release(req)
            raise
        return sink

    def _validate_adapter(self, adapter):
        """Resolve an ``adapter=`` binding against the fleet at
        dispatch time: the REQUESTED name must be loaded on every
        LIVE replica (a cross-replica retry re-binds the name on
        whichever replica catches it — a fleet where this name is
        missing, or unloading, on some replicas cannot honor that).
        The check is scoped to the requested name: an in-progress
        rolling load/unload of an UNRELATED adapter must not shed
        valid tenant traffic. Rejected requests raise here, at the
        router edge, before any admission state is reserved."""
        lead = self._replicas[0].engine
        if not getattr(lead, "lora_enabled", False):
            raise lead._submit_error(
                "adapter", adapter, "this fleet has no LoRA bank "
                "(replicas constructed without lora_rank=)")
        live = [rep for rep in self._replicas if not self._dead(rep)]
        # one dict lookup per replica (has_adapter) — the submit hot
        # path never materializes/sorts whole registries; those are
        # built only to compose a failing request's error message
        have = {rep.idx for rep in live
                if rep.engine.has_adapter(adapter)}
        if have and len(have) < len(live):
            raise TypeError(
                f"adapter={adapter!r} rejected: the fleet's "
                f"registries are heterogeneous for this name (loaded "
                f"on replicas {sorted(have)!r}, missing on "
                f"{sorted({r.idx for r in live} - have)!r}) — a "
                f"cross-replica retry could not re-bind the adapter; "
                f"roll the load fleet-wide via Router.load_adapter")
        if not have:
            loaded = sorted({n for rep in live
                             for n in rep.engine.adapters})
            raise ValueError(
                f"unknown adapter {adapter!r}: not loaded on the "
                f"fleet (loaded adapters: {loaded!r})")

    def generate(self, prompt, timeout=None, **kwargs):
        """Blocking convenience (generation fleets):
        ``submit(prompt, **kwargs).result(timeout)``."""
        return self.submit(prompt, **kwargs).result(timeout)

    def predict(self, *args, timeout=None, **kwargs):
        """Blocking convenience (inference fleets):
        ``submit(*args, **kwargs).result(timeout)``."""
        return self.submit(*args, **kwargs).result(timeout)

    # -- dispatch ------------------------------------------------------
    def _remaining_ms(self, req: _Req):
        if req.deadline is None:
            return None, False
        rem = req.deadline - time.monotonic()
        return rem * 1e3, rem <= 0

    def _fail(self, req: _Req, exc, inline: bool):
        """Terminal failure: raise synchronously from ``submit`` when
        the first dispatch never succeeded, deliver through the sink
        otherwise."""
        if inline:
            # admission is released by submit's except hook; outcome
            # counters for the raise path:
            if isinstance(exc, RequestTimeoutError):
                telemetry.counter("serving.router.timeouts")
            elif not isinstance(exc, (QueueFullError, ValueError,
                                      TypeError)):
                telemetry.counter("serving.router.errors")
            raise exc
        self._finish_req(req, exc=exc)

    def _dispatch(self, req: _Req, exclude, inline: bool = False):
        exclude = set(exclude)
        while True:
            if self._closed:
                return self._fail(req, EngineClosedError(
                    "Router closed while the request was in flight"),
                    inline)
            rem_ms, expired = self._remaining_ms(req)
            if expired:
                if self._mode == "generate" and req.sink.tokens:
                    # partial output already delivered: finish the
                    # stream the way an engine-side deadline would
                    return self._finish_req(req, reason="timeout")
                return self._fail(req, RequestTimeoutError(
                    "request deadline expired before a replica could "
                    "serve it"), inline)
            affinity = None
            if req.prefix_key is not None:
                with self._lock:
                    affinity = self._affinity.get(req.prefix_key)
            rep = self._pick(exclude, affinity=affinity)
            if rep is None:
                return self._fail(req, ReplicaFailedError(
                    f"no available replica in the fleet "
                    f"({len(self._replicas)} total: down, circuit-open, "
                    f"or already tried)"), inline)
            tr = getattr(req.sink, "_trace", None)
            try:
                if self._faults is not None:
                    self._faults.on_dispatch(rep.idx, rep.engine)
                if self._mode == "generate":
                    akw = {} if req.adapter is None \
                        else {"adapter": req.adapter}
                    if tr is not None:
                        tr.event("dispatch", replica=rep.idx)
                    # the ONE trace object rides along to the replica
                    # engine (its spans accumulate under this request);
                    # an untraced router request must also suppress any
                    # process-default engine trace, so the replica
                    # stream never grows a second, router-invisible one
                    attempt = rep.engine.submit(
                        req.payload, max_new_tokens=req.max_new,
                        eos_id=req.eos_id, timeout_ms=rem_ms,
                        trace=tr if tr is not None else False,
                        **(req.sampling or {}), **akw)
                else:
                    attempt = rep.engine.submit(*req.payload,
                                                timeout_ms=rem_ms)
            except QueueFullError:
                # saturation, not sickness: never trips the breaker —
                # spill to the next-shortest queue, shed only when
                # every candidate is full
                self._abort_trial(rep)
                telemetry.counter("serving.router.replica_full")
                exclude.add(rep.idx)
                if len(exclude) >= len(self._replicas):
                    telemetry.counter("serving.router.rejected_full")
                    return self._fail(req, QueueFullError(
                        "every available replica's queue is full"),
                        inline)
                continue
            except (ValueError, TypeError) as e:
                self._abort_trial(rep)  # the request is malformed,
                return self._fail(req, e, inline)  # not the replica
            except Exception as e:  # noqa: BLE001 — replica failure
                self._record_failure(rep, e)
                if req.retries_left > 0 and not self._closed:
                    req.retries_left -= 1
                    req.sink.retries += 1
                    telemetry.counter("serving.router.retries")
                    if tr is not None:
                        tr.event("retry", replica=rep.idx,
                                 error=f"{type(e).__name__}: {e}")
                    tracing.flight.record(
                        "router.retry", replica=rep.idx,
                        error=type(e).__name__,
                        trace_id=None if tr is None else tr.trace_id)
                    exclude.add(rep.idx)
                    continue
                return self._fail(req, e, inline)
            with self._lock:
                rep.inflight += 1
                rep.dispatches += 1
                if req.prefix_key is not None:
                    # this replica now holds the prefix's pages — bias
                    # the key's future requests toward it
                    self._affinity.pop(req.prefix_key, None)
                    self._affinity[req.prefix_key] = rep.idx
                    while len(self._affinity) > self._affinity_cap:
                        self._affinity.popitem(last=False)
            req.sink.replicas.append(rep.idx)
            if self._mode == "generate":
                self._attach_gen(req, rep, attempt)
            else:
                self._attach_infer(req, rep, attempt)
            return

    # -- per-attempt completion ----------------------------------------
    def _attach_gen(self, req: _Req, rep: _Replica,
                    stream: GenerationStream):
        """Mirror the replica stream into the router stream. On a
        retry, ``skip`` tokens were already delivered — greedy decode
        regenerates the identical prefix, which is skipped instead of
        re-emitted (the caller's stream never stutters)."""
        skip = len(req.sink.tokens)
        seen = [0]

        def on_token(tok):
            seen[0] += 1
            if seen[0] > skip:
                req.sink._emit(tok)

        def on_finish(reason, exc):
            try:
                self._attempt_done(req, rep, reason, exc)
            except Exception as e:  # noqa: BLE001 — never strand the
                self._finish_req(req, exc=e)  # caller on a router bug

        stream._watch(on_token, on_finish)

    def _attach_infer(self, req: _Req, rep: _Replica, fut: Future):
        def on_done(f):
            exc = f.exception()
            try:
                self._attempt_done(req, rep, None, exc,
                                   result=None if exc else f.result())
            except Exception as e:  # noqa: BLE001
                self._finish_req(req, exc=e)

        fut.add_done_callback(on_done)

    def _attempt_done(self, req, rep, reason, exc, result=None):
        with self._lock:
            rep.inflight -= 1
        if exc is None and reason in (None, "length", "eos"):
            self._record_success(rep)
            return self._finish_req(req, reason=reason, result=result)
        if exc is None and reason == "timeout":
            # engine-side deadline: partial output is already out
            self._record_timeout(rep)
            return self._finish_req(req, reason=reason)
        if isinstance(exc, RequestTimeoutError):
            self._record_timeout(rep)
            return self._finish_req(req, exc=exc)
        if exc is None and reason == "closed":
            # the replica shut down mid-stream (rolling restart): the
            # partial generation continues on another replica; an
            # inconclusive half-open trial returns its slot
            self._abort_trial(rep)
            exc = EngineClosedError("replica closed mid-generation")
        else:
            self._record_failure(rep, exc)
        self._maybe_retry(req, rep, exc, reason=reason)

    def _maybe_retry(self, req, rep, exc, reason=None):
        if req.retries_left > 0 and not self._closed:
            req.retries_left -= 1
            req.sink.retries += 1
            telemetry.counter("serving.router.retries")
            tr = getattr(req.sink, "_trace", None)
            if tr is not None:
                tr.event("retry", replica=rep.idx,
                         error=f"{type(exc).__name__}: {exc}"
                         if exc is not None else reason)
            tracing.flight.record(
                "router.retry", replica=rep.idx,
                error=type(exc).__name__ if exc is not None else reason,
                trace_id=None if tr is None else tr.trace_id)
            return self._dispatch(req, frozenset({rep.idx}))
        if reason is not None and self._mode == "generate":
            return self._finish_req(req, reason=reason)
        self._finish_req(req, exc=exc)

    def _finish_req(self, req: _Req, reason=None, exc=None, result=None):
        """Deliver the request's final outcome exactly once and release
        its admission reservation."""
        if not self._release(req):
            return
        if exc is not None:
            telemetry.counter(
                "serving.router.timeouts"
                if isinstance(exc, RequestTimeoutError)
                else "serving.router.errors")
        else:
            telemetry.counter("serving.router.completed")
            if reason == "timeout":
                telemetry.counter("serving.router.timeouts")
        telemetry.hist_since("serving.router.latency", req.t0)
        if self._mode == "generate":
            req.sink._finish(reason=reason, exc=exc)
        else:
            try:
                if exc is not None:
                    req.sink.set_exception(exc)
                else:
                    req.sink.set_result(result)
            except Exception:  # noqa: BLE001 — already resolved
                pass

    # -- rolling rollover ----------------------------------------------
    def load_weights(self, source, strict: bool = True,
                     drain_timeout_s: float = 10.0):
        """Fleet-wide zero-downtime weight rollover, one replica at a
        time: cordon (new traffic prefers the others), wait for the
        replica's queue to drain (bounded by ``drain_timeout_s`` —
        in-flight slots are safe to swap under, per PR 6's per-engine
        contract), swap via the engine's own ``load_weights``, restore.
        No request is dropped fleet-wide; a single-replica fleet keeps
        serving through its cordon (cordoning is a preference, not a
        hard exclusion). Returns the number of replicas swapped.

        ``source`` is a checkpoint path (read ONCE, then installed
        into every replica) or an in-memory ``{name: array}`` mapping."""
        if self._closed:
            raise EngineClosedError("load_weights on a closed Router")
        if isinstance(source, dict):
            new_params = source
        else:
            from .. import checkpoint as _ckpt
            new_params, _meta = _ckpt.read_params(source)
        swapped = 0
        for rep in self._replicas:
            if self._dead(rep):
                continue
            with self._lock:
                rep.cordoned = True
            try:
                deadline = time.monotonic() + drain_timeout_s
                worker = getattr(rep.engine, "_worker", None)
                if worker is None:
                    worker = getattr(rep.engine, "_batcher", None)
                while worker is not None \
                        and worker._queue.qsize() > 0 \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
                rep.engine.load_weights(new_params, strict=strict)
                swapped += 1
            except EngineClosedError:
                # the replica died/closed between the _dead() check and
                # its swap: skip it and KEEP ROLLING — aborting here
                # would strand the rest of the fleet on the old weights
                # (mixed versions break retry token-identity fleet-wide;
                # one dead replica is already routed around)
                continue
            finally:
                with self._lock:
                    rep.cordoned = False
        telemetry.counter("serving.router.rollovers")
        return swapped

    # -- fleet-wide adapter management ----------------------------------
    def load_adapter(self, name, params, alpha=1.0):
        """Fleet-wide LoRA adapter rollover, one replica at a time —
        the ``load_weights`` rolling pattern on the tenant axis:
        cordon (new traffic prefers the others), install via the
        engine's own zero-retrace ``load_adapter``, restore. No drain
        wait is needed: a NEW adapter touches no in-flight request,
        and a refresh of an existing one has the per-engine rollover
        semantics (in-flight slots continue on the refreshed
        factors). Returns the number of replicas that installed it.
        ``submit(adapter=name)`` requires the name on EVERY live
        replica, so route traffic at it only after this returns. A
        per-replica rejection (e.g. one engine still draining the
        name's previous unload) does NOT abort the roll — the rest of
        the fleet still installs and the first error re-raises at the
        end (aborting mid-roll would strand the fleet heterogeneous
        on every replica AFTER the failed one; re-running converges,
        refresh is idempotent)."""
        if self._closed:
            raise EngineClosedError("load_adapter on a closed Router")
        with self._adapter_roll_lock:
            # the roll lock serializes fleet rolls per name: a
            # concurrent unload roll interleaving per replica could
            # otherwise finish in opposite orders on different
            # replicas and leave the name persistently heterogeneous
            # with both calls reporting success
            with self._lock:
                if name in self._adapter_draining:
                    # the engine-level rule, fleet-shaped: a reload
                    # now would report success and then be silently
                    # evicted when the pending deferred unload drains
                    raise ValueError(
                        f"adapter {name!r} is unloading fleet-wide "
                        f"(pinned by in-flight requests); retry once "
                        f"they finish")
            swapped, first_err = 0, None
            for rep in self._replicas:
                if self._dead(rep):
                    continue
                with self._lock:
                    rep.cordoned = True
                try:
                    rep.engine.load_adapter(name, params, alpha=alpha)
                    swapped += 1
                except EngineClosedError:
                    continue  # keep rolling — the load_weights rule
                except ValueError as e:
                    if first_err is None:
                        first_err = e
                    continue
                finally:
                    with self._lock:
                        rep.cordoned = False
        if first_err is not None:
            raise first_err
        return swapped

    def unload_adapter(self, name):
        """Fleet-wide adapter unload. While ANY router request is
        in flight bound to the name, the whole fleet keeps it loaded
        and the unload DEFERS (returns 0): a cross-replica retry must
        be able to re-bind the adapter on whichever replica catches
        it, so no replica may free its slot while another still
        serves the name — the engine-level pin generalized to the
        fleet. The name stops accepting new submits immediately; the
        last bound request's release runs the rolling per-replica
        unload. With nothing in flight the unload rolls now; returns
        the number of replicas that freed the slot immediately."""
        if self._closed:
            raise EngineClosedError("unload_adapter on a closed Router")
        loaded = any(
            rep.engine.has_adapter(name) for rep in self._replicas
            if not self._dead(rep)
            and getattr(rep.engine, "lora_enabled", False))
        if not loaded:
            raise ValueError(
                f"unknown adapter {name!r}: not loaded on the fleet")
        with self._lock:
            # mark the name draining in BOTH paths before any slot is
            # freed: a submit sitting between _validate_adapter and
            # _admit must hit the draining rejection, not pin a name
            # whose rolling unload is already freeing replicas
            self._adapter_draining.add(name)
            if self._adapter_inflight.get(name, 0) > 0:
                return 0
        return self._unload_adapter_now(name)

    def _unload_adapter_now(self, name):
        """The rolling per-replica unload (the ``load_adapter``
        loop): called with the name already in ``_adapter_draining``
        (set by ``unload_adapter``, or kept by the last bound
        request's release) so no new submit can pin it mid-roll; the
        draining mark clears when the roll finishes. Per replica the
        engine's own deferred-unload semantics still apply."""
        freed = 0
        try:
            with self._adapter_roll_lock:
                with self._lock:
                    if name not in self._adapter_draining:
                        # another roll of this name ran while we
                        # waited on the roll lock (e.g. a retried
                        # inline unload beat the prober's queued
                        # drain) — and a reload may have installed
                        # fresh factors since; rolling now would
                        # silently evict them
                        return 0
                for rep in self._replicas:
                    if self._dead(rep):
                        continue
                    with self._lock:
                        rep.cordoned = True
                    try:
                        if rep.engine.unload_adapter(name):
                            freed += 1
                    except (EngineClosedError, ValueError):
                        # dead-mid-roll, or a replica that never had
                        # the name (crashed and replaced mid-load) —
                        # keep rolling
                        continue
                    finally:
                        with self._lock:
                            rep.cordoned = False
        finally:
            with self._lock:
                self._adapter_draining.discard(name)
                # a queued drain is satisfied by ANY roll of the
                # name: a stale pending entry would later evict a
                # freshly reloaded adapter
                self._adapter_drain_pending.discard(name)
        return freed

"""mxnet_tpu.serving — the inference fast path.

`InferenceEngine` coalesces concurrent single-sample (or small-batch)
requests onto one AOT-warmed CachedOp forward per dispatch — dynamic
micro-batching with bounded queueing delay, admission control, and
graceful shutdown. `GenerationEngine` is its autoregressive sibling:
slot-based continuous batching over one fixed-shape KV-cache decode
step (generate.py). See docs/SERVING.md for knobs and operational
guidance, ``bench.py --serving`` / ``--generate`` (BENCH_r08/r09.json)
for the measured A/Bs.
"""
from .engine import (  # noqa: F401
    InferenceEngine, ServingError, EngineClosedError, QueueFullError,
    RequestTimeoutError,
)
from .generate import (  # noqa: F401
    GenerationEngine, GenerationStream, GenerationResult,
)

__all__ = ["InferenceEngine", "ServingError", "EngineClosedError",
           "QueueFullError", "RequestTimeoutError",
           "GenerationEngine", "GenerationStream", "GenerationResult"]

"""mxnet_tpu.serving — the inference fast path.

`InferenceEngine` coalesces concurrent single-sample (or small-batch)
requests onto one AOT-warmed CachedOp forward per dispatch — dynamic
micro-batching with bounded queueing delay, admission control, and
graceful shutdown. See docs/SERVING.md for knobs and operational
guidance, ``bench.py --serving`` / BENCH_r08.json for the measured
A/B against per-request dispatch.
"""
from .engine import (  # noqa: F401
    InferenceEngine, ServingError, EngineClosedError, QueueFullError,
    RequestTimeoutError,
)

__all__ = ["InferenceEngine", "ServingError", "EngineClosedError",
           "QueueFullError", "RequestTimeoutError"]

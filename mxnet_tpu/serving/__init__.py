"""mxnet_tpu.serving — the inference fast path.

`InferenceEngine` coalesces concurrent single-sample (or small-batch)
requests onto one AOT-warmed CachedOp forward per dispatch — dynamic
micro-batching with bounded queueing delay, admission control, and
graceful shutdown. `GenerationEngine` is its autoregressive sibling:
slot-based continuous batching over one fixed-shape KV-cache decode
step (generate.py); with ``paged=True`` the cache is a PAGED pool
with prefix reuse (shared prompts prefilled once, refcounted,
copy-on-write) and chunked prefill (paging.py owns the host-side
page/prefix bookkeeping); with ``draft_model=`` it decodes
SPECULATIVELY (a small draft proposes k tokens, the target verifies
k+1 positions in one program — greedy output token-identical,
stochastic distribution-preserving), and ``submit(temperature=,
top_k=, top_p=, seed=)`` gives every request its own sampling knobs
and explicit PRNG key. `Router` fronts N engine replicas as ONE
fault-tolerant fleet: join-shortest-queue balancing, per-replica
health/circuit-breaker state, cross-replica retry, per-tenant quotas,
priority load shedding, and rolling zero-downtime weight rollover
(router.py); `FaultInjector` (faults.py) is the deterministic
chaos-injection seam that proves all of it. See docs/SERVING.md for
knobs and operational guidance, ``bench.py --serving`` / ``--generate``
/ ``--router`` / ``--prefix`` (BENCH_r08/r09/r11/r13.json) for the
measured A/Bs.
"""
from .engine import (  # noqa: F401
    InferenceEngine, ServingError, EngineClosedError, QueueFullError,
    RequestTimeoutError, ReplicaFailedError,
)
from .generate import (  # noqa: F401
    GenerationEngine, GenerationStream, GenerationResult,
)
from .faults import FaultInjector, FaultRule, InjectedFault  # noqa: F401
from .router import (  # noqa: F401
    Router, RouterStream, LoadShedError, TenantQuotaError,
    HEALTHY, DEGRADED, DOWN,
)

__all__ = ["InferenceEngine", "ServingError", "EngineClosedError",
           "QueueFullError", "RequestTimeoutError", "ReplicaFailedError",
           "GenerationEngine", "GenerationStream", "GenerationResult",
           "Router", "RouterStream", "LoadShedError", "TenantQuotaError",
           "FaultInjector", "FaultRule", "InjectedFault",
           "HEALTHY", "DEGRADED", "DOWN"]

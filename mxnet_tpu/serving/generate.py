"""GenerationEngine — slot-based continuous batching for
autoregressive decoding.

The InferenceEngine (engine.py) multiplies throughput for FIXED
forwards by coalescing requests; generation breaks its model: one
request is not one forward but a prefill plus an unknown number of
decode steps. Whole-batch ("static") generation — collect B prompts,
decode until ALL finish — leaves slots idle behind the longest
sequence and stalls arrivals behind batch formation. Iteration-level
scheduling (Orca, OSDI'22; vLLM's continuous batching) instead admits
and evicts requests at DECODE-STEP boundaries. The TPU-native twist
here is fixed-shape slot batches: the KV cache is a preallocated
``max_slots``-row pytree (gluon/model_zoo/gpt.py ``init_cache``) and
every step of every mix of requests runs ONE AOT-warmed decode
program — occupancy changes rebind slot rows, never shapes, so the
steady state compiles exactly nothing.

Architecture::

    caller threads ── submit(prompt) ──► bounded request queue
                                              │ (admission control:
                                              │  queue_limit, timeout,
                                              ▼  closed-engine reject)
                                        generator thread
                     ┌──────────────────────────────────────────────┐
                     │ per step: admit queued prompts into FREE     │
                     │ slots (prefill bucketed on the seq axis via  │
                     │ BucketingPolicy, K/V scattered into the      │
                     │ cache at the slot row) ── one fixed-shape    │
                     │ decode_step over ALL slots ── emit one token │
                     │ per live slot into its stream ── evict       │
                     │ EOS / max-tokens / deadline slots (freed     │
                     │ rows admit the next prompts mid-sequence)    │
                     └──────────────────────────────────────────────┘

``submit`` returns a :class:`GenerationStream` — a token-stream
future: iterate it to consume tokens as they are generated, or call
``result(timeout)`` for the completed :class:`GenerationResult`.
Admission control and shutdown follow the InferenceEngine contract
exactly (``QueueFullError`` / ``RequestTimeoutError`` /
``EngineClosedError``; ``close()`` drains-then-rejects via the shared
``BoundedQueueWorker``; no stream is ever left hanging), and
``MXTPU_SERVING=0`` degrades to synchronous inline generation.

Decoding is GREEDY (argmax) — which is what makes engine output
token-identical to a single-request ``prefill`` + ``decode_step`` loop
at the same slot width: rows of one XLA program are bit-independent,
so a request's tokens do not depend on its co-tenants.

``paged=True`` swaps the dense per-slot cache for the PAGED KV cache
(vLLM-style block tables; docs/SERVING.md "Paged KV cache"): a global
page pool + static-shape page tables, host-side refcounted page
allocation (serving/paging.py), prefix reuse (a shared system prompt
is prefilled ONCE and its immutable pages are shared across slots,
copy-on-write at the divergence page), and Sarathi/Orca-style chunked
prefill (at most ONE fixed-width chunk per engine iteration,
interleaved with the decode step, so a long prompt bounds TPOT instead
of stalling every in-flight request for a whole monolithic prefill).
Same fixed-shape/zero-steady-state-compile discipline; greedy output
stays token-identical to the dense engine.

``draft_model=`` turns on SPECULATIVE DECODING (docs/SERVING.md
"Speculative decoding & sampling"): a second, smaller decoder
proposes ``spec_k`` tokens per slot per iteration and the target
verifies all ``spec_k + 1`` positions in one fixed-shape program,
committing 1..``spec_k + 1`` tokens — the per-SLOT throughput
multiplier that composes with continuous batching's cross-slot one.
Greedy output stays TOKEN-IDENTICAL to the non-speculative engine;
stochastic requests use the residual-distribution accept rule, which
preserves the target distribution exactly. ``submit(temperature=,
top_k=, top_p=, seed=)`` is a first-class per-request feature on
every engine: knobs ride per-slot runtime vectors through one
fixed-shape sampling program (ops/sampling.py), keys are explicit
and split per slot per step inside the trace, and a seeded stream
is bitwise-reproducible whenever the admission schedule is replayed
— across engine restarts included.

Telemetry (docs/OBSERVABILITY.md): counters
``serving.generate.{requests,tokens,prefills,evictions,rejected_full,
rejected_closed,timeouts,errors}``, gauges ``serving.generate.slots``
(occupancy + peak) / ``serving.generate.queue.depth``, histograms
``serving.generate.{prefill,decode,ttft}``; paged mode adds
``serving.generate.pages.{allocated,shared,cow_copies,freed}`` /
``pages.free`` / ``prefix_hits`` / ``prefill_chunks`` and the
``prefill_chunks_per_iter`` gauge whose peak proves the one-chunk
decode-stall bound; speculation adds
``serving.generate.spec.{proposed,accepted,rejected}`` counters and
the ``spec.accept_rate`` / ``spec.tokens_per_step`` gauges; sampling
adds ``serving.generate.sampling.requests``; multi-tenant LoRA adds
the ``serving.generate.lora.{adapters_loaded,adapters_evicted,
requests}`` counters, the ``lora.active_adapters`` gauge, the
``lora.load`` histogram, and the ``ops.lora.trace`` compile counter
(the bank analog of ``model.gpt.trace`` for the zero-retrace gates).
"""
from __future__ import annotations

import collections
import contextlib
import os
import queue
import threading
import time
import weakref

import numpy as onp

from .. import telemetry, tracing
from ..random_state import request_key
from .._bounded_worker import BoundedQueueWorker
from ..bucketing import BucketingPolicy, as_policy
from . import paging
from .engine import (
    EngineClosedError, QueueFullError, ReplicaFailedError,
    RequestTimeoutError, _live_engines, _serving_enabled,
)

__all__ = ["GenerationEngine", "GenerationStream", "GenerationResult"]


class GenerationResult:
    """Completed generation: ``tokens`` (generated ids, prompt
    excluded), ``finish_reason`` in {"eos", "length", "timeout",
    "closed"}, and the ``prompt_len`` it continued from."""

    __slots__ = ("tokens", "finish_reason", "prompt_len")

    def __init__(self, tokens, finish_reason, prompt_len):
        self.tokens = tokens
        self.finish_reason = finish_reason
        self.prompt_len = prompt_len

    def __len__(self):
        return len(self.tokens)

    def __repr__(self):
        return (f"GenerationResult({len(self.tokens)} tokens, "
                f"finish_reason={self.finish_reason!r})")


class GenerationStream:
    """Per-request token-stream future.

    Iterating yields token ids as the engine produces them (multiple
    iterators each see the full stream); ``result(timeout)`` blocks for
    the final :class:`GenerationResult`. A rejected/failed request
    raises the failure from both paths — never a hung consumer."""

    def __init__(self, prompt_len):
        self.prompt_len = prompt_len
        self._cv = threading.Condition()
        self._tokens: list = []
        self._reason = None
        self._exc = None
        self._watchers: list = []
        #: ``time.perf_counter()`` stamps of the first token and of
        #: completion — producer-side, so latency measurement needs no
        #: consumer thread racing the stream (bench.py --generate).
        self.first_token_at = None
        self.done_at = None
        #: the request's tracing.Trace, or None (tracing off for this
        #: request — the near-zero disabled path)
        self._trace = None

    # -- producer side (generator thread) ------------------------------
    def _emit(self, token: int):
        # one protocol, one implementation: the finished-stream guard
        # (a stale step racing an injected crash must not append),
        # first-token stamp, wakeup and watcher fan-out all live in
        # _emit_many
        self._emit_many((token,))

    def _emit_many(self, tokens):
        """Append a SEQUENCE of tokens under one lock acquisition and
        one wakeup — the speculative-commit fast path: a verify step
        commits up to k+1 tokens at once, and per-token notify_all
        with a live ``result()`` waiter costs a GIL bounce each (the
        dominant per-iteration cost at interactive concurrency)."""
        if not tokens:
            return
        with self._cv:
            if self._reason is not None or self._exc is not None:
                return  # finished streams take no more tokens
            if not self._tokens:
                self.first_token_at = time.perf_counter()
            toks = [int(t) for t in tokens]
            self._tokens.extend(toks)
            if self._trace is not None:
                self._trace.event("emit", n=len(toks),
                                  total=len(self._tokens))
            self._cv.notify_all()
            for on_token, _fin in self._watchers:
                for tok in toks:
                    on_token(tok)

    def _finish(self, reason=None, exc=None):
        with self._cv:
            if self._reason is not None or self._exc is not None:
                return  # first outcome stands (close racing a finish)
            self._reason = reason
            self._exc = exc
            self.done_at = time.perf_counter()
            if self._trace is not None:
                self._trace.finish(reason=reason, error=exc)
            self._cv.notify_all()
            watchers, self._watchers = self._watchers, []
            for _tok, on_finish in watchers:
                on_finish(reason, exc)

    def _watch(self, on_token, on_finish):
        """Producer-side event subscription (the Router's retry hook):
        ``on_token(tok)`` fires for every token — including, first, a
        replay of tokens already emitted — and ``on_finish(reason,
        exc)`` exactly once at completion. Callbacks run under the
        stream lock on the producer thread; they must be quick and must
        not raise (a raise propagates into the producing engine)."""
        with self._cv:
            for tok in self._tokens:
                on_token(tok)
            if self._reason is not None or self._exc is not None:
                on_finish(self._reason, self._exc)
            else:
                self._watchers.append((on_token, on_finish))

    # -- consumer side --------------------------------------------------
    def done(self) -> bool:
        with self._cv:
            return self._reason is not None or self._exc is not None

    @property
    def trace_id(self):
        """The request's trace id, or None when untraced."""
        return None if self._trace is None else self._trace.trace_id

    def trace(self):
        """The request's recorded spans (list of dicts — see
        ``tracing.Span``), or None when the request was not traced
        (tracing disabled and no ``submit(trace=True)``). Available
        live (spans so far) and after completion (the full
        queue→admission→prefill→decode→emit→finish lifecycle)."""
        return None if self._trace is None else self._trace.spans()

    @property
    def tokens(self):
        """Snapshot of the tokens generated so far."""
        with self._cv:
            return list(self._tokens)

    def __iter__(self):
        i = 0
        while True:
            with self._cv:
                while i >= len(self._tokens) and self._reason is None \
                        and self._exc is None:
                    self._cv.wait()  # every producer path notifies
                if i < len(self._tokens):
                    tok = self._tokens[i]
                    i += 1
                elif self._exc is not None:
                    raise self._exc
                else:
                    return
            yield tok

    def result(self, timeout=None) -> GenerationResult:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._reason is None and self._exc is None:
                rem = None if deadline is None \
                    else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise TimeoutError(
                        "generation still running after result() timeout")
                self._cv.wait(rem)
            if self._exc is not None:
                raise self._exc
            return GenerationResult(list(self._tokens), self._reason,
                                    self.prompt_len)


class _GenRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "stream", "t_submit",
                 "t_enq", "deadline", "temperature", "top_k", "top_p",
                 "key", "adapter_idx")

    def __init__(self, prompt, max_new, eos_id, stream, t_submit,
                 t_enq, deadline, temperature=0.0, top_k=0, top_p=1.0,
                 key=None, adapter_idx=0):
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.stream = stream
        self.t_submit = t_submit
        self.t_enq = t_enq     # monotonic enqueue stamp (queue wait)
        self.deadline = deadline
        self.temperature = temperature   # 0.0 = greedy
        self.top_k = top_k               # 0 = off
        self.top_p = top_p               # 1.0 = off
        self.key = key                   # (2,) uint32 PRNG key data
        self.adapter_idx = adapter_idx   # LoRA bank slot (0 = base)


class _Adapter:
    """Host-side registry record of one loaded LoRA adapter: its bank
    slot, the number of requests pinning it (submitted and not yet
    finished), and whether an unload is deferred behind those pins."""

    __slots__ = ("name", "idx", "refs", "unloading")

    def __init__(self, name, idx):
        self.name = name
        self.idx = idx
        self.refs = 0
        self.unloading = False


class _Slot:
    __slots__ = ("stream", "last", "left", "eos_id", "deadline", "n_ctx")

    def __init__(self, stream, last, left, eos_id, deadline, n_ctx):
        self.stream = stream
        self.last = last       # last emitted token (next step's input)
        self.left = left       # generated-token budget remaining
        self.eos_id = eos_id
        self.deadline = deadline
        self.n_ctx = n_ctx     # cache rows filled (prompt + decoded)


class _PagedSlot:
    """Slot state in paged mode. ``state`` is "prefill" (chunks still
    pending — the slot sits out decode steps) or "decode". ``row`` is
    the host mirror of the slot's page-table row (physical page per
    logical page index; scrap 0 past its reservation); ``page_refs``
    are the pool references the slot holds (released at eviction);
    ``cow_pending`` is ``(src, dst, logical_idx)`` when the slot's next
    decode write would land in a SHARED page — the divergence page is
    copied to ``dst`` right before that first write (copy-on-write)."""

    __slots__ = ("stream", "last", "left", "eos_id", "deadline", "n_ctx",
                 "state", "chunks", "row", "page_refs", "cow_pending",
                 "prompt", "seq", "t_submit", "draft_prompt", "key",
                 "adapter_idx")

    def __init__(self, stream, left, eos_id, deadline, n_ctx, row,
                 page_refs, prompt, seq, t_submit):
        self.stream = stream
        self.adapter_idx = 0   # LoRA bank slot (0 = base model)
        self.draft_prompt = None   # kept in speculative mode for the
        # draft's dense prefill when the slot enters decode
        self.key = None   # stochastic requests: the PRNG key, parked
        # here until decode entry (see _arm_sampling)
        self.last = None
        self.left = left
        self.eos_id = eos_id
        self.deadline = deadline
        self.n_ctx = n_ctx
        self.state = "prefill"
        self.chunks = collections.deque()
        self.row = row
        self.page_refs = page_refs
        self.cow_pending = None
        self.prompt = prompt   # kept until registered in the index
        self.seq = seq         # admission order (oldest prefills first)
        self.t_submit = t_submit


class _GenWorker(BoundedQueueWorker):
    """Consumer side of the request queue: the admit/step loop.

    Same shutdown contract as the InferenceEngine batcher: a graceful
    ``_draining`` phase finishes admitted work, ``stop()`` is the hard
    deadline whose drain rejects queued leftovers through
    ``_drained``."""

    def __init__(self, engine: "GenerationEngine", queue_limit: int):
        super().__init__(queue_limit, name="GenerationEngine.worker")
        self._engine = weakref.ref(engine)
        self._draining = False
        self.start()

    def run(self):
        try:
            self._run()
        except Exception as e:  # noqa: BLE001 — a failed step must not
            # strand waiters: fail every live stream and queued request
            telemetry.counter("serving.generate.errors")
            eng = self._engine()
            if eng is not None:
                eng._fail_all(e)
            return
        # hard-stopped mid-generation: the worker owns the slots, so it
        # (not close(), racing is_alive) finishes leftover streams —
        # truncated output with finish_reason="closed", never a hang
        eng = self._engine()
        if eng is not None and self._stopped:
            eng._close_active("closed")

    def _run(self):
        while not self._stopped:
            eng = self._engine()
            if eng is None:
                return  # abandoned engine: streams die with their refs
            # every model-touching path holds _gen_lock — warmup() may
            # be tracing the jitted closures concurrently, and tracing
            # (parameter rebinding in the _bind wrapper) is not
            # thread-safe against itself
            with eng._gen_lock:
                eng._admit(self._queue)
                active = eng._n_active
                if active:
                    eng._step()
            if eng._gen_waiters:
                # fairness: this loop re-acquires _gen_lock back to
                # back, and lock handoff is unfair under the GIL — a
                # rollover/warmup/fault-injection caller could starve
                # for an entire generation. Cede one scheduler slice
                # between steps when someone is waiting (rare).
                time.sleep(0.0005)
            if active:
                continue
            del eng  # don't pin the engine while blocking on the queue
            try:
                r = self._queue.get(timeout=0.05)
            except queue.Empty:
                if self._draining:
                    return
                continue
            eng = self._engine()
            if eng is None:
                r.stream._finish(exc=EngineClosedError(
                    "engine was garbage-collected"))
                return
            with eng._gen_lock:
                eng._admit_one(r)

    def _drained(self, item):
        if isinstance(item, _GenRequest):
            telemetry.counter("serving.generate.rejected_closed")
            item.stream._finish(exc=EngineClosedError(
                "engine closed before the request was scheduled"))

    def close(self, timeout: float):
        self._draining = True
        self.join(timeout=max(0.0, timeout))
        self.stop(timeout=min(timeout, 2.0) if timeout > 0 else 0.1)


class GenerationEngine:
    """Continuously-batched greedy generation over a decoder model.

    Parameters
    ----------
    model
        A decoder exposing the explicit-cache generation API —
        ``init_cache(batch_size, max_length, dtype)`` /
        ``prefill(tokens, valid_length, cache, slots)`` /
        ``decode_step(tokens, cache)`` (gluon/model_zoo/gpt.py
        ``GPTModel`` is the in-tree implementation).
    max_slots : int
        Concurrent sequences per decode step — the fixed batch width
        of the decode program and the KV-cache row count.
    max_length : int, optional
        Cache sequence capacity (default: the model's position table).
        A prompt must leave room for at least one generated token.
    max_new_tokens : int
        Default generated-token budget per request (``submit``
        overrides per call).
    eos_id : int, optional
        Default stop token (``submit`` overrides per call).
    queue_limit : int
        Bound on queued requests; beyond it ``submit`` raises
        :class:`QueueFullError` immediately (load shedding).
    timeout_ms : float, optional
        Default deadline: a request still QUEUED past it is rejected
        with :class:`RequestTimeoutError`; one already generating is
        finished early with ``finish_reason="timeout"`` (partial
        output delivered — tokens already streamed can't be unsent).
    prefill_bucketing : BucketingPolicy | str | None
        Sequence-axis policy for prefill (default pow2, min 8, clamped
        to the cache capacity; paged mode raises the floor to the page
        size). Each bucket is one compiled prefill width — ``warmup()``
        AOT-compiles them all.
    paged : bool
        Replace the dense per-slot cache with the PAGED KV cache: a
        global pool of fixed-size pages plus a static-shape page table
        per slot (docs/SERVING.md "Paged KV cache"). Enables prefix
        reuse (shared prompts prefilled once, refcounted, copy-on-write
        at the divergence page) and chunked prefill (at most one chunk
        per engine iteration, so long prompts can't stall in-flight
        decode). Greedy output stays token-identical to dense mode.
    page_size : int
        Tokens per KV page (power of two dividing ``max_length``).
        Also the prefix-sharing granularity: only whole pages are
        shared.
    n_pages : int, optional
        Physical pages in the pool (default: the dense cache's exact
        HBM budget, ``max_slots * max_length / page_size``, plus the
        reserved scrap page). Fewer pages overcommit HBM against
        short/shared traffic: admission defers (FIFO) while the pool
        is exhausted, after evicting cold cached prefixes.
    prefill_chunk : int
        Chunked-prefill width (multiple of ``page_size``; default
        ``max(32, 2 * page_size)`` capped at the cache capacity). A
        prompt longer than one bucketed chunk is admitted as
        fixed-width chunks, one per engine iteration.
    prefix_cache : bool
        Keep finished prompts' pages in a refcounted LRU index so
        later requests sharing their prefix skip that prefill (an
        exact repeat skips prefill entirely — its first token is
        computed straight off the cached K/V).
    quantize : str, optional
        ``"int8_weights"`` arms weight-only int8 decode: the model's
        attention/MLP projection weights are quantized per-output-
        channel symmetric int8 at engine load (re-quantized under the
        swap lock on every ``load_weights`` rollover) and the decode
        path runs the fused dequant-matmul kernel — the fp32 weights
        never re-stream from HBM. Greedy output is held to the
        bounded-divergence gate documented in docs/SERVING.md
        ("Low-precision decode"), not token-identity.
    kv_dtype : str, optional
        ``"int8"`` stores the KV cache quantized (a quarter the K/V
        bytes of fp32; per-head-per-slot scales dense, per-head-per-
        page scales paged — so a paged pool holds ~4x the pages in
        the same HBM). Alias for ``cache_dtype`` with the quantized
        layout; attention dequantizes inside the decode kernels.
    draft_model : optional
        A second, SMALLER decoder from the same model family (same
        vocabulary) that turns on draft-model SPECULATIVE DECODING:
        each engine iteration the draft proposes ``spec_k`` tokens per
        decoding slot and the target model verifies all ``spec_k + 1``
        positions in one fixed-shape program, committing the accepted
        prefix plus one target token — between 1 and ``spec_k + 1``
        tokens per slot per iteration instead of exactly one. Greedy
        output stays TOKEN-IDENTICAL to the non-speculative engine
        (the accept rule only ever commits the target's own greedy
        tokens); stochastic requests use the speculative-sampling
        residual rule, which preserves the target distribution
        exactly. The draft keeps its own dense fp32 cache and is
        rolled back to the accept point every iteration.
    spec_k : int
        Draft tokens proposed per slot per iteration (default 4).
        Each cache row reserves a ``spec_k`` scratch margin at the
        top (usable capacity is ``max_length - spec_k``) so a verify
        write never clamps; rejected entries die above the ``len``
        waterline.
    speculative : bool, optional
        Defaults to ``draft_model is not None``. Passing
        ``speculative=True`` without a draft raises — self-speculation
        is not implemented.
    mesh_layout : str, optional
        ``"tp"`` runs ONE model sharded across the device mesh
        (tensor parallel — parallel/partition.py's ``"tp"`` layout):
        the attention/MLP weights are placed over the mesh's ``tp``
        axis by their logical axes, the KV cache is sharded over the
        HEADS axis, and every generation program compiles SPMD — so a
        model (plus cache) larger than one device's HBM serves from
        the whole mesh. Greedy output is token-identical to the
        unsharded engine (the only numeric difference is the
        reduction order of the ``tp`` partial sums). Currently the
        dense fp32 engine only; ``num_heads`` must be divisible by
        the ``tp`` axis size.
    mesh : jax.sharding.Mesh, optional
        The mesh for ``mesh_layout`` (default: the process-global
        ``parallel.get_mesh()``). Must carry a ``tp`` axis.
    lora_rank : int, optional
        Arm batched multi-tenant LoRA (docs/SERVING.md "Multi-tenant
        LoRA"): the model grows a stacked adapter bank (ops/lora.py)
        over its attention projections and every generation program
        gathers each slot's adapter by a per-slot index vector —
        thousands of fine-tunes share ONE engine, one compiled
        program, one KV pool. ``load_adapter(name, params)`` /
        ``unload_adapter(name)`` manage tenants at runtime with zero
        retraces (the banks are runtime arguments, the quant-table
        discipline); ``submit(adapter=name)`` binds a request.
        Per-tenant greedy output is token-identical to a dedicated
        single-adapter engine. Composes with ``paged=True`` (prefix
        reuse stays base-model-only), int8 (the LoRA delta stays fp32
        over the dequant base path) and speculative decoding (the
        draft proposes with the BASE model; verify/commit runs
        adapted — greedy commits stay the adapted model's own,
        acceptance degrades gracefully and is reported).
    max_adapters : int, optional
        Loadable adapter slots in the bank (default 8; bank slot 0 is
        the reserved all-zeros base adapter on top of these). Only
        meaningful with ``lora_rank``.
    decode_ticks : int, optional
        Fuse ``k`` decode iterations into one jitted scan per engine
        tick (docs/SERVING.md "Multi-tick decode"): one host sync and
        one dispatch amortize over up to k tokens per slot, with
        per-slot eos/budget stop handling moved IN-PROGRAM. Default 1
        is bitwise today's single-step path. Greedy output is
        token-identical across tick sizes; seeded sampling is
        bitwise-reproducible on a replayed admission schedule.
        Composes with ``paged``/int8 KV/LoRA/per-request sampling;
        rejected alongside ``speculative`` (that path already
        amortizes its sync over ``spec_k + 1`` tokens). Trades tail
        latency granularity for throughput: deadlines and eviction
        run at block (k-token) granularity.
    compute_dtype : str, optional
        ``"bfloat16"`` runs the generation programs with bf16
        parameters and activations (fp32 master weights stay the
        source of truth; rollovers re-cast with zero retraces) —
        softmax/LayerNorm statistics and the returned logits stay
        fp32, and the KV cache defaults to bf16 (int8 KV still
        composes via ``kv_dtype``). Held to the same teacher-forced
        bounded-divergence contract as int8. Default/``"float32"``
        is bitwise today's fp32 path.
    """

    def __init__(self, model, max_slots: int = 8, max_length=None,
                 max_new_tokens: int = 64, eos_id=None,
                 queue_limit: int = 256, timeout_ms=None,
                 prefill_bucketing=None, cache_dtype=None,
                 paged: bool = False, page_size: int = 16,
                 n_pages=None, prefill_chunk=None,
                 prefix_cache: bool = True, quantize=None,
                 kv_dtype=None, draft_model=None, spec_k: int = 4,
                 speculative=None, mesh_layout=None, mesh=None,
                 lora_rank=None, max_adapters=None,
                 decode_ticks: int = 1, compute_dtype=None):
        self.paged = bool(paged)
        if speculative is None:
            speculative = draft_model is not None
        self.speculative = bool(speculative)
        if self.speculative and draft_model is None:
            raise ValueError(
                "speculative=True needs a draft_model (a second, "
                "smaller decoder from the same model_zoo family)")
        if draft_model is not None and not self.speculative:
            raise ValueError(
                "draft_model without speculative decoding is inert; "
                "drop it or pass speculative=True")
        self.draft = draft_model
        self.spec_k = int(spec_k)
        self.decode_ticks = int(decode_ticks)
        if self.decode_ticks < 1:
            raise ValueError(f"decode_ticks must be >= 1, got "
                             f"{decode_ticks}")
        if self.decode_ticks > 1 and self.speculative:
            raise ValueError(
                "decode_ticks > 1 does not compose with speculative "
                "decoding: the speculative iteration already amortizes "
                "one host sync over up to spec_k+1 tokens — pick one "
                "amortization scheme")
        if quantize not in (None, "int8_weights"):
            raise ValueError(
                f"unsupported quantize={quantize!r} (only "
                f"'int8_weights')")
        if kv_dtype is not None:
            if cache_dtype is not None \
                    and str(cache_dtype) != str(kv_dtype):
                raise ValueError(
                    f"kv_dtype={kv_dtype!r} conflicts with "
                    f"cache_dtype={cache_dtype!r}")
            if str(kv_dtype) != "int8":
                raise ValueError(
                    f"unsupported kv_dtype={kv_dtype!r} (only 'int8'; "
                    f"use cache_dtype for plain float layouts)")
            cache_dtype = kv_dtype
        self.quantize = quantize
        if quantize is not None:
            if not callable(getattr(model, "quantize_params", None)):
                raise TypeError(
                    "quantize='int8_weights' needs a model exposing "
                    "quantize_params() (gluon.model_zoo.gpt.GPTModel)")
            t0 = telemetry.clock()
            model.quantize_params()
            telemetry.hist_since("serving.generate.quant.quantize", t0)
            n, saved = model.quantized_param_stats() \
                if callable(getattr(model, "quantized_param_stats",
                                    None)) else (0, 0)
            telemetry.counter("serving.generate.quant.params", n)
            telemetry.counter("serving.generate.quant.bytes_saved",
                              saved)
        if compute_dtype not in (None, "float32", "bfloat16"):
            raise ValueError(
                f"unsupported compute_dtype={compute_dtype!r} (only "
                f"'float32' or 'bfloat16')")
        self.compute_dtype = "float32" if compute_dtype is None \
            else str(compute_dtype)
        if self.compute_dtype == "bfloat16":
            if mesh_layout is not None:
                raise ValueError(
                    "compute_dtype='bfloat16' does not compose with "
                    "mesh_layout yet: the cast shadow buffers are not "
                    "re-placed over the mesh")
            if not callable(getattr(model, "cast_compute_params",
                                    None)):
                raise TypeError(
                    "compute_dtype='bfloat16' needs a model exposing "
                    "cast_compute_params() "
                    "(gluon.model_zoo.gpt.GPTModel)")
            # master weights stay fp32; the closures consume a bf16
            # shadow list installed as runtime arguments (the int8
            # quant-table discipline — load_weights re-casts with
            # zero retraces). The draft model, if any, stays fp32:
            # its logits only steer proposals.
            t0 = telemetry.clock()
            model.cast_compute_params("bfloat16")
            telemetry.hist_since("serving.generate.cast.cast", t0)
        self.lora_enabled = lora_rank is not None
        if max_adapters is not None and not self.lora_enabled:
            raise ValueError(
                "max_adapters without lora_rank is inert; pass "
                "lora_rank= to arm the batched adapter bank")
        if self.lora_enabled:
            self.lora_rank = int(lora_rank)
            if self.lora_rank < 1:
                raise ValueError(f"lora_rank must be >= 1, got "
                                 f"{lora_rank}")
            self.max_adapters = 8 if max_adapters is None \
                else int(max_adapters)
            if self.max_adapters < 1:
                raise ValueError(f"max_adapters must be >= 1, got "
                                 f"{max_adapters}")
            for attr in ("arm_lora", "set_adapter", "clear_adapter"):
                if not callable(getattr(model, attr, None)):
                    raise TypeError(
                        f"lora_rank= needs a model exposing the "
                        f"batched-LoRA API (missing {attr!r}); see "
                        f"gluon.model_zoo.gpt.GPTModel")
            # bank slot 0 is the reserved all-zeros base adapter, so
            # the bank holds max_adapters + 1 slots; arming BEFORE
            # warmup() means the one structural retrace happens there
            model.arm_lora(self.max_adapters + 1, self.lora_rank)
        else:
            self.lora_rank = None
            self.max_adapters = 0
        api = ("init_paged_cache", "prefill_paged", "decode_step_paged",
               "peek_logits_paged", "bind_slot_paged",
               "copy_page_paged") if self.paged \
            else ("init_cache", "prefill", "decode_step")
        if self.speculative:
            api += (("verify_commit_paged",)
                    if self.paged else ("verify_commit",))
        if self.decode_ticks > 1:
            api += (("decode_multi_paged",)
                    if self.paged else ("decode_multi",))
        for attr in api:
            if not callable(getattr(model, attr, None)):
                raise TypeError(
                    f"GenerationEngine needs a decoder with the "
                    f"explicit-cache generation API (missing "
                    f"{attr!r}); see gluon.model_zoo.gpt.GPTModel")
        if self.speculative:
            if self.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
            for attr in ("init_cache", "prefill", "propose_tokens",
                         "advance_len"):
                if not callable(getattr(draft_model, attr, None)):
                    raise TypeError(
                        f"draft_model needs the dense explicit-cache "
                        f"generation API (missing {attr!r}); see "
                        f"gluon.model_zoo.gpt.GPTModel")
            tv = getattr(model, "_vocab_size", None)
            dv = getattr(draft_model, "_vocab_size", None)
            if tv is not None and dv is not None and tv != dv:
                raise TypeError(
                    f"draft vocab {dv} != target vocab {tv}: "
                    f"speculative decoding needs one tokenizer — the "
                    f"draft proposes TARGET token ids")
        if int(max_slots) < 1:
            raise ValueError("max_slots must be >= 1")
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.mesh_layout = mesh_layout
        self._part = None
        self._tp_heads = 0
        self._cache_sh = None  # canonical TP cache shardings (lazy)
        self._rep_sh = None    # replicated-over-mesh target (draft)
        self._step_collectives = None  # per-decode collective counts
        if mesh_layout is not None:
            if mesh_layout != "tp":
                raise ValueError(
                    f"unsupported mesh_layout={mesh_layout!r} (only "
                    f"'tp')")
            from .. import parallel as _parallel
            from ..parallel import partition as _partition
            m = mesh if mesh is not None else _parallel.get_mesh()
            if m is None:
                raise RuntimeError(
                    "mesh_layout='tp' needs a mesh: pass mesh= or "
                    "call parallel.set_mesh first")
            tp = int(m.shape.get("tp", 1))
            if tp <= 1:
                raise ValueError(
                    "mesh_layout='tp' needs a mesh with a 'tp' axis "
                    "of size > 1 (parallel.make_mesh((1, n), "
                    "('dp', 'tp')))")
            n_heads = int(getattr(model, "_num_heads", 0) or 0)
            if n_heads <= 0:
                raise TypeError(
                    "mesh_layout='tp' needs a model exposing "
                    "_num_heads (the KV cache shards by heads; "
                    "gluon.model_zoo.gpt.GPTModel does)")
            if n_heads % tp:
                raise ValueError(
                    f"num_heads {n_heads} is not divisible by the tp "
                    f"axis size {tp}: the KV cache shards by heads")
            self._tp_heads = n_heads
            self._part = _partition.Partitioner("tp", mesh=m)
            from jax.sharding import NamedSharding as _NS, \
                PartitionSpec as _P
            self._rep_sh = _NS(m, _P())
            # place the parameters over the mesh BEFORE any closure
            # traces: the jitted generation programs read the params'
            # committed shardings and compile SPMD. The attention ops
            # trace on their jnp paths (ops.attention.jnp_only — a
            # pallas_call cannot ride inside an SPMD program), which
            # requires rebuilding any closures a prior single-device
            # user of this model left behind.
            if callable(getattr(model, "_gen_params", None)):
                model._gen_params()   # materialize deferred shapes
            self._part.place(model.collect_params())
            if callable(getattr(model, "set_force_jnp_attention",
                                None)):
                model.set_force_jnp_attention(True)
            # derived generation state (int8 quant tables computed
            # above from the then-unplaced weights; LoRA banks armed
            # above) re-places onto shardings riding the weights' axes
            if callable(getattr(model, "shard_generation_state",
                                None)):
                model.shard_generation_state(self._part)
            if self.speculative:
                # the DRAFT runs REPLICATED over the mesh while the
                # target is tp: its params/cache are small (a draft is
                # a truncation of the target), and replication keeps
                # propose/verify_commit at their 3-dispatch shape —
                # no cross-placement transfers inside the iteration
                _partition.Partitioner("dp", mesh=m).place(
                    draft_model.collect_params())
                if callable(getattr(draft_model,
                                    "set_force_jnp_attention", None)):
                    draft_model.set_force_jnp_attention(True)
            for axis, size in m.shape.items():
                telemetry.gauge(f"parallel.mesh.axis_sizes.{axis}",
                                int(size))
        else:
            # a single-device engine must UNDO a prior tp engine's
            # jnp-only tracing mark on a reused model (and draft) —
            # leaving it set would silently trace the slow jnp
            # attention paths instead of Pallas on a TPU box, with no
            # error or telemetry signal
            for mdl in (model, draft_model):
                if mdl is not None and callable(
                        getattr(mdl, "set_force_jnp_attention", None)):
                    mdl.set_force_jnp_attention(False)
        self.model = model
        self.max_slots = int(max_slots)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.queue_limit = max(1, int(queue_limit))
        self.timeout_ms = timeout_ms
        self._s_max = int(max_length) if max_length is not None \
            else int(model.max_length)
        #: usable sequence capacity. A speculative engine reserves a
        #: ``spec_k`` scratch margin at the top of every cache row: a
        #: verify step writes up to ``len + spec_k`` K/V entries before
        #: knowing how many will commit, and that write must never
        #: clamp/wrap — rejected entries sit above the ``len``
        #: waterline (never attended, overwritten next step) instead
        self._s_cap = self._s_max - self.spec_k if self.speculative \
            else self._s_max
        if self._s_cap < 2:
            raise ValueError(
                f"max_length {self._s_max} leaves no usable capacity "
                f"after the spec_k={self.spec_k} verify margin")
        policy = as_policy(prefill_bucketing)
        if cache_dtype is None and self.compute_dtype == "bfloat16":
            # bf16 compute writes bf16 K/V — default the cache to
            # match (half the HBM and bandwidth); int8 KV still
            # composes by passing cache_dtype/kv_dtype="int8"
            cache_dtype = "bfloat16"
        self._cache_dtype = cache_dtype
        if self.paged:
            ps = int(page_size)
            if ps < 1 or (ps & (ps - 1)):
                raise ValueError("page_size must be a power of two")
            if self._s_max % ps:
                raise ValueError(
                    f"page_size {ps} must divide max_length "
                    f"{self._s_max}")
            self._ps = ps
            self._p_max = self._s_max // ps
            chunk = int(prefill_chunk) if prefill_chunk is not None \
                else min(self._s_max, max(32, 2 * ps))
            if chunk % ps or not 0 < chunk <= self._s_max:
                raise ValueError(
                    f"prefill_chunk {chunk} must be a positive "
                    f"multiple of page_size {ps} within the cache "
                    f"capacity {self._s_max}")
            self._chunk = chunk
            if policy is None:
                policy = BucketingPolicy(mode="pow2",
                                         min_size=max(8, ps))
            self.policy = policy.clamped(self._s_max)
            for w in self.policy.sizes(self._chunk):
                if w <= self._chunk and w % ps:
                    raise ValueError(
                        f"prefill bucket {w} is not a multiple of "
                        f"page_size {ps} (page-granular scatter needs "
                        f"aligned widths)")
            #: default pool = the dense cache's HBM budget exactly
            #: (max_slots full-length rows) + the scrap page; prefix
            #: sharing turns the saving into extra effective slots
            np_total = int(n_pages) if n_pages is not None \
                else self.max_slots * self._p_max + 1
            self._pool = paging.PagePool(np_total)
            self._prefix = paging.PrefixIndex(self._pool, ps) \
                if prefix_cache else None
            self._blocked: collections.deque = collections.deque()
            self._seq = 0
            self._chunks_this_iter = 0
            self._cache = model.init_paged_cache(
                self.max_slots, np_total, ps, self._s_max,
                dtype=cache_dtype)
        else:
            if policy is None:
                policy = BucketingPolicy(mode="pow2", min_size=8)
            self.policy = policy.clamped(self._s_max)
            self._cache = model.init_cache(self.max_slots, self._s_max,
                                           dtype=cache_dtype)
        # COMMIT the cache to its device up front: a fresh
        # ``init_cache`` holds uncommitted arrays, a jitted step's
        # outputs are committed — and the pjit C++ fast path caches
        # executables PER INPUT-SHARDING SIGNATURE, so the first
        # admission after the first step would silently recompile
        # every prefill bucket a second time (~1s stalls that no
        # trace counter sees; found by driving the speculative engine
        # under JAX_LOG_COMPILES)
        self._cache = self._commit(self._cache)
        #: the draft model's OWN cache: dense even under a paged
        #: target (the draft is small — its whole cache costs a
        #: fraction of one target layer's pool) and fp32 (its logits
        #: only steer proposals; the target's verify is what commits)
        self._draft_cache = None if not self.speculative \
            else self._commit_draft(
                draft_model.init_cache(self.max_slots, self._s_max))
        #: per-slot sampling state, threaded as runtime (B,) vectors
        #: through the fixed-shape sampling/verify programs — a mixed
        #: greedy/stochastic batch runs ONE compiled program
        self._temps = onp.zeros((self.max_slots,), "f4")
        self._topks = onp.zeros((self.max_slots,), "i4")
        self._topps = onp.ones((self.max_slots,), "f4")
        self._keys = onp.zeros((self.max_slots, 2), "u4")
        self._n_sampling = 0   # active slots with temperature > 0
        self._samplers = None  # jitted ops/sampling.py programs (lazy)
        #: per-slot LoRA bank indices, threaded as a runtime (B,)
        #: vector through every fixed-shape generation program — a
        #: batch mixing any tenants (base rows included) runs ONE
        #: compiled program; the vector is data, never shape
        self._adapter_idx = onp.zeros((self.max_slots,), "i4")
        #: host-side adapter registry: name -> _Adapter (bank slot,
        #: pin count, deferred-unload flag). _lora_lock is a LEAF
        #: lock — taken from submit, stream-finish callbacks and the
        #: swap-locked load/unload paths, never around a model call
        self._lora_lock = threading.Lock()
        self._lora_reg: dict = {}
        self._lora_free = list(range(1, self.max_adapters + 1))
        #: freed-but-not-yet-zeroed bank slots: eviction paths run in
        #: stream-finish callbacks that may hold the worker's
        #: ``_gen_lock``, where ``model.clear_adapter`` (a
        #: read-modify-write of the banks) cannot be serialized
        #: against a concurrent ``set_adapter`` — so the factors are
        #: zeroed lazily inside the NEXT ``load_adapter``'s
        #: ``_gen_exclusive`` window (a freed slot is unreachable —
        #: no registry name maps to it — so this is hygiene, never
        #: correctness, and bank bytes are preallocated either way)
        self._lora_stale: set = set()
        self._kv_int8 = "k_scale" in self._cache
        if self._kv_int8:   # quant.* telemetry only for quantized
            # engines — an fp32 fleet must not populate the namespace
            kv_bytes = sum(
                int(a.size) * a.dtype.itemsize
                for key in ("k", "v", "k_scale", "v_scale")
                for a in self._cache.get(key, ()))
            telemetry.gauge("serving.generate.quant.kv_bytes_per_slot",
                            kv_bytes // self.max_slots)
        self._slots: list = [None] * self.max_slots
        self._n_active = 0
        #: serializes every model call (worker admit/step, sync-mode
        #: generation, warmup) — jit TRACING mutates shared parameter
        #: bindings, so two threads may never trace concurrently
        self._gen_lock = threading.Lock()
        #: count of threads waiting on _gen_lock via _gen_exclusive —
        #: the worker's step loop yields between steps when non-zero
        #: (unfair lock handoff would otherwise starve them)
        self._gen_waiters = 0
        self._lock = threading.Lock()
        self._closed = False
        #: set (to a ReplicaFailedError) when the generator thread died
        #: from an unexpected error — a broken replica, not a close()
        self._failure: ReplicaFailedError | None = None
        self._sync = not _serving_enabled()
        self._worker = None if self._sync \
            else _GenWorker(self, self.queue_limit)
        _live_engines.add(self)

    @property
    def precision(self) -> str:
        """The replica's numeric configuration — ``"fp32"``,
        ``"int8_weights"``, ``"int8_kv"``, ``"bf16"`` or a ``+``-join
        of the armed reductions. Router fleets must be
        precision-homogeneous: retries re-run a request on another
        replica and the bounded-divergence contract only holds within
        ONE reduced-precision configuration."""
        parts = []
        if self.compute_dtype == "bfloat16":
            parts.append("bf16")
        if self.quantize is not None:
            parts.append(self.quantize)
        if self._kv_int8:
            parts.append("int8_kv")
        return "+".join(parts) if parts else "fp32"

    @property
    def speculation(self) -> str:
        """The replica's speculative-decoding configuration — ``"off"``
        or ``"k=<spec_k>:draft=<type>:<layers>L-<units>u"``. Router
        fleets must be speculation-homogeneous (the precision-
        homogeneity rule's sibling): a retried STOCHASTIC request
        replays its seed, and its committed stream depends on the
        draft/spec_k key-consumption schedule — mixing configurations
        would make the retry's tokens depend on which replica caught
        it."""
        if not self.speculative:
            return "off"
        d = self.draft
        return (f"k={self.spec_k}:draft={type(d).__name__}:"
                f"{getattr(d, '_num_layers', '?')}L-"
                f"{getattr(d, '_units', '?')}u")

    @property
    def lora(self) -> str:
        """The replica's batched-LoRA configuration — ``"off"`` or
        ``"rank=<r>:max=<n>"``. Router fleets must be LoRA-config-
        homogeneous (the precision/speculation rule's sibling): a
        retried request re-runs ``adapter=`` on another replica, and
        the binding only means the same thing when every replica's
        bank has the same shape."""
        if not self.lora_enabled:
            return "off"
        return f"rank={self.lora_rank}:max={self.max_adapters}"

    @property
    def mesh_config(self) -> str:
        """The replica's mesh-parallel configuration — ``"off"`` or
        ``"tp:<axis>=<size>x..."``. Router fleets must be
        mesh-homogeneous (the precision/speculation/LoRA rule's
        sibling): a cross-replica retry must replay the IDENTICAL
        numeric config, and a tp engine's logits differ from an
        unsharded replica's in the partial-sum reduction order — a
        mixed fleet would make a retried stream's tokens depend on
        which replica caught it."""
        if self._part is None:
            return "off"
        mesh = self._part.mesh
        axes = "x".join(f"{a}={int(n)}" for a, n in mesh.shape.items())
        return f"{self.mesh_layout}:{axes}"

    def capabilities(self) -> str:
        """One-line summary of the engine's configured capabilities —
        quoted by every ``submit`` kwarg-validation error so a caller
        holding the wrong engine sees what this one actually does."""
        return (f"precision={self.precision}, "
                f"speculation={self.speculation}, lora={self.lora}, "
                f"paged={self.paged}, mesh={self.mesh_config}")

    def _submit_error(self, arg, value, why):
        """The shared ``submit`` kwarg-validation error: names the
        offending argument AND the engine's configured capabilities
        (a bare TypeError told the caller neither)."""
        return TypeError(
            f"submit() {arg}={value!r} not supported: {why} "
            f"(engine capabilities: {self.capabilities()})")

    # -- multi-tenant LoRA (docs/SERVING.md "Multi-tenant LoRA") --------
    @property
    def adapters(self):
        """Sorted names of the loaded adapters (unload-pending ones —
        pinned by in-flight requests — excluded: they reject new
        submits already)."""
        with self._lora_lock:
            return sorted(name for name, ad in self._lora_reg.items()
                          if not ad.unloading)

    def has_adapter(self, name) -> bool:
        """Membership check for ONE adapter name (loaded and not
        unload-pending) — a single dict lookup under the leaf lock.
        The Router's per-submit validation hot path: it must not
        materialize and sort the whole registry per replica per
        request just to answer a membership question."""
        with self._lora_lock:
            ad = self._lora_reg.get(name)
            return ad is not None and not ad.unloading

    def _lora_active_locked(self):
        """Loaded-adapter count for the ``lora.active_adapters``
        gauge — unload-pending names excluded, matching the
        :attr:`adapters` property and the OBSERVABILITY.md row (they
        already reject new submits). Call under ``_lora_lock``."""
        return sum(1 for ad in self._lora_reg.values()
                   if not ad.unloading)

    def load_adapter(self, name, params, alpha=1.0):
        """Load (or refresh) one tenant's LoRA adapter under the swap
        lock, with ZERO retraces: the stacked banks are runtime
        arguments of the jitted closures, so installing the factors is
        a step-boundary array swap — the ``load_weights`` discipline
        applied to the tenant axis. ``params`` is the flat
        ``{"layers.<li>.<proj>.A"/".B": array}`` mapping of
        ``GPTModel.set_adapter``. Refreshing an existing name keeps
        its bank slot; in-flight requests bound to it simply continue
        on the new factors (the documented rollover semantics)."""
        if not self.lora_enabled:
            raise TypeError(
                f"load_adapter({name!r}): this engine has no LoRA "
                f"bank (constructed without lora_rank=) (engine "
                f"capabilities: {self.capabilities()})")
        if self._closed:
            raise EngineClosedError("load_adapter on a closed engine")
        t0 = telemetry.clock()
        with self._gen_exclusive():
            with self._lora_lock:
                ad = self._lora_reg.get(name)
                if ad is not None and ad.unloading:
                    raise ValueError(
                        f"adapter {name!r} is unloading (pinned by "
                        f"in-flight requests); retry once they finish")
                if ad is None and not self._lora_free:
                    raise ValueError(
                        f"adapter capacity exhausted: {self.max_adapters} "
                        f"slots all hold live adapters "
                        f"({sorted(self._lora_reg)!r}, unload-pending "
                        f"included)")
                idx = ad.idx if ad is not None \
                    else self._lora_free[0]
                stale = self._lora_stale
                self._lora_stale = set()
            # the model calls happen under _gen_exclusive only (never
            # the leaf lock): a worker step is between iterations
            # here. First zero any slots freed since the last swap
            # window (evicted tenants' factors must not linger in the
            # bank), then install the new factors.
            for s in stale:
                # idx included even though set_adapter overwrites it:
                # if the install's validation raises, the slot must
                # not keep the evicted tenant's factors
                self.model.clear_adapter(s)
            self.model.set_adapter(idx, params, alpha=alpha)
            with self._lora_lock:
                if self._lora_reg.get(name) is None:
                    # fresh load — or a refresh whose name vanished
                    # between the two lock sections (a concurrent
                    # unload completing via a pin drop takes only the
                    # leaf lock): the factors ARE installed in `idx`,
                    # so re-register instead of returning success for
                    # an adapter that is no longer loaded
                    self._lora_free.remove(idx)
                    self._lora_reg[name] = _Adapter(name, idx)
                # the slot holds a live install now: a concurrent
                # eviction in the window above must not leave it
                # marked for the next swap's lazy zeroing
                self._lora_stale.discard(idx)
                n_active = self._lora_active_locked()
        telemetry.hist_since("serving.generate.lora.load", t0)
        telemetry.counter("serving.generate.lora.adapters_loaded")
        telemetry.gauge("serving.generate.lora.active_adapters",
                        n_active)
        return self

    def unload_adapter(self, name) -> bool:
        """Unload an adapter. Returns True when the bank slot was
        freed immediately; False when in-flight requests still pin it
        — the unload is DEFERRED: the name stops accepting new
        submits now, and the slot is freed when the last pinned
        request finishes (``lora.adapters_evicted`` counts the actual
        eviction either way)."""
        if not self.lora_enabled:
            raise TypeError(
                f"unload_adapter({name!r}): this engine has no LoRA "
                f"bank (constructed without lora_rank=) (engine "
                f"capabilities: {self.capabilities()})")
        with self._lora_lock:
            ad = self._lora_reg.get(name)
            if ad is None:
                raise ValueError(
                    f"unknown adapter {name!r} (loaded: "
                    f"{sorted(self._lora_reg)!r})")
            if ad.refs > 0:
                ad.unloading = True
                n_active = self._lora_active_locked()
                deferred = True
            else:
                del self._lora_reg[name]
                self._lora_free.append(ad.idx)
                self._lora_free.sort()
                self._lora_stale.add(ad.idx)
                n_active = self._lora_active_locked()
                deferred = False
        telemetry.gauge("serving.generate.lora.active_adapters",
                        n_active)
        if deferred:
            return False
        telemetry.counter("serving.generate.lora.adapters_evicted")
        return True

    def _pin_adapter(self, name):
        """Resolve an ``adapter=`` submit binding to its bank slot and
        pin it (in-flight requests keep their adapter loaded: an
        unload while they run is deferred, never a mid-stream tenant
        swap to base)."""
        with self._lora_lock:
            ad = self._lora_reg.get(name)
            if ad is None or ad.unloading:
                loaded = sorted(n for n, a in self._lora_reg.items()
                                if not a.unloading)
                raise ValueError(
                    f"submit() adapter={name!r} is not loaded on this "
                    f"engine (loaded adapters: {loaded!r}; engine "
                    f"capabilities: {self.capabilities()})")
            ad.refs += 1
            return ad.idx

    def _unpin_adapter(self, name):
        """Drop one request's pin; completes a deferred unload when
        the last pin goes (stream-finish callback — leaf lock only,
        safe under the worker's ``_gen_lock``)."""
        evicted = False
        with self._lora_lock:
            ad = self._lora_reg.get(name)
            if ad is None:
                return
            ad.refs -= 1
            if ad.refs <= 0 and ad.unloading:
                del self._lora_reg[name]
                self._lora_free.append(ad.idx)
                self._lora_free.sort()
                self._lora_stale.add(ad.idx)
                evicted = True
                n_active = self._lora_active_locked()
        if evicted:
            telemetry.counter("serving.generate.lora.adapters_evicted")
            telemetry.gauge("serving.generate.lora.active_adapters",
                            n_active)

    def _ensure_samplers(self):
        """The jitted ops/sampling.py programs (lazy — importing jax
        at engine construction is fine, but tracing belongs under
        ``_gen_lock`` at warmup/first use). Each actual trace counts
        ``ops.sampling.trace`` — the sampling analog of
        ``model.gpt.trace`` for the zero-steady-state-compile gates."""
        if self._samplers is None:
            import jax

            from ..ops import sampling as _smp

            def counted(fn):
                def wrapper(*args):
                    telemetry.counter("ops.sampling.trace")
                    tracing.flight.record("compile",
                                          what="ops.sampling")
                    return fn(*args)
                return wrapper

            self._samplers = {
                "sample": jax.jit(counted(_smp.sample_tokens)),
            }
        return self._samplers

    def _warm_samplers(self, vocab: int):
        """Compile every engine-level sampler shape the steady state
        can hit: the (1, V) first-token pick and the (B, V)
        decode-step pick (the speculative draft/accept math lives
        inside the model's fused closures — ``_warmup_spec``)."""
        smp = self._ensure_samplers()
        b = self.max_slots
        smp["sample"](onp.zeros((1, 2), "u4"),
                      onp.zeros((1, vocab), "f4"),
                      onp.zeros((1,), "f4"),
                      onp.zeros((1,), "i4"), onp.ones((1,), "f4"))
        smp["sample"](onp.zeros((b, 2), "u4"),
                      onp.zeros((b, vocab), "f4"),
                      onp.zeros((b,), "f4"),
                      onp.zeros((b,), "i4"), onp.ones((b,), "f4"))

    def _commit(self, cache):
        """Pin a cache pytree to its device(s) (see the constructor
        note: committed and uncommitted inputs compile SEPARATE pjit
        executables, and caches cross that line after their first
        donated step). The target must be EXPLICIT — a bare
        ``device_put`` preserves the uncommitted state. Under
        ``mesh_layout="tp"`` the target is the partitioner's cache
        sharding (K/V over the heads axis) instead of one device."""
        import jax
        if self._part is not None:
            return self._part.place_cache(cache, self._tp_heads)
        return jax.device_put(cache, jax.devices()[0])

    def _recommit(self, cache):
        """TP mode: pin a jitted step's returned cache back onto the
        canonical heads-sharded placement, so every program always
        sees ONE input-sharding signature (GSPMD is free to pick a
        different output sharding, and the pjit executable cache keys
        on input shardings — a drifting cache would silently compile
        a second executable per program). The shardings pytree is
        computed ONCE (the cache's shapes are fixed for the engine's
        lifetime) so the per-step cost is one device_put that is a
        no-op copy-wise when the shardings already match. Entirely
        outside TP mode."""
        if self._part is None:
            return cache
        import jax
        if self._cache_sh is None:
            self._cache_sh = self._part.cache_shardings(cache,
                                                        self._tp_heads)
        return jax.device_put(cache, self._cache_sh)

    def _commit_draft(self, cache):
        """Commit the DRAFT model's dense cache: replicated over the
        whole mesh under ``mesh_layout="tp"`` (the replicated-draft
        rule — every device holds the full draft state, so the fused
        propose program runs SPMD with zero cross-device traffic),
        one device otherwise."""
        import jax
        if self._part is not None:
            return jax.device_put(cache, self._rep_sh)
        return jax.device_put(cache, jax.devices()[0])

    def _recommit_draft(self, cache):
        """TP mode: pin a draft step's returned cache back onto the
        replicated placement (the draft analog of :meth:`_recommit` —
        one input-sharding signature per program)."""
        if self._part is None:
            return cache
        import jax
        return jax.device_put(cache, self._rep_sh)

    def _emit_collectives(self):
        """Bump the ``parallel.collectives.*`` counters by the decode
        program's per-step collective counts (measured once from the
        compiled HLO at warmup — ``GPTModel.decode_hlo``)."""
        if self._step_collectives:
            for kind, n in self._step_collectives.items():
                telemetry.counter(f"parallel.collectives.{kind}", n)

    # -- lifecycle -----------------------------------------------------
    @contextlib.contextmanager
    def _gen_exclusive(self):
        """Acquire ``_gen_lock`` as a registered waiter. The worker's
        step loop re-acquires the lock back to back and Python lock
        handoff is unfair — without the waiter signal a rollover,
        warmup, or fault-injection caller can starve for as long as a
        whole generation under continuous decode traffic."""
        with self._lock:
            self._gen_waiters += 1
        try:
            with self._gen_lock:
                yield
        finally:
            with self._lock:
                self._gen_waiters -= 1

    def warmup(self):
        """Compile the steady state ahead of traffic: one prefill per
        sequence bucket the policy can produce, plus the decode step.
        After this, serving any traffic mix triggers zero new traces
        (``model.gpt.trace`` telemetry stays flat)."""
        # compile against a THROWAWAY cache of the live cache's shapes
        # (the jit cache keys on shapes/dtypes, so the programs carry
        # over): the worker thread may already be serving self._cache,
        # and prefill/decode_step DONATE their cache argument — touching
        # the live one here would race the step loop into a
        # donated-buffer error. _gen_lock additionally keeps our traces
        # mutually exclusive with any in-flight worker step.
        with self._gen_exclusive():
            if self._closed:
                # close() won the lock first: compiling against a
                # closing engine is wasted work at best and a
                # donated-buffer race at worst — bail cleanly
                return self
            if self.paged:
                self._warmup_paged()
                self._warmup_telemetry()
                return self
            cache = self._commit(self.model.init_cache(
                self.max_slots, self._s_max, dtype=self._cache_dtype))
            for sb in self.policy.sizes(self._s_cap - 1):
                toks = onp.zeros((1, sb), "i4")
                _, cache = self.model.prefill(toks, [sb], cache,
                                              slots=[0])
                if self._part is not None:
                    # pin back to the canonical heads-sharded layout
                    # so every program warms against the ONE input
                    # sharding signature the live path will feed it
                    cache = self._recommit(cache)
            lg, cache = self.model.decode_step(
                onp.zeros((self.max_slots,), "i4"), cache)
            cache = self._recommit(cache)
            if self.decode_ticks > 1:
                cache = self._warmup_multi(cache)
            self._warm_samplers(int(lg.shape[-1]))
            if self.speculative:
                self._warmup_spec(cache)
            self._warmup_telemetry()
        return self

    def _warmup_multi(self, cache):
        """Compile the fused multi-tick decode scan against the
        throwaway cache. ONE program serves every traffic mix — the
        budget/eos/sampling vectors are runtime data — so this single
        warm call is the whole multi-tick steady state."""
        b, k = self.max_slots, self.decode_ticks
        fn = self.model.decode_multi_paged if self.paged \
            else self.model.decode_multi
        _, _, _, cache = fn(
            onp.zeros((b,), "i4"), onp.full((b,), k, "i4"), cache, k,
            onp.zeros((b, 2), "u4"), onp.zeros((b,), "f4"),
            onp.zeros((b,), "i4"), onp.ones((b,), "f4"),
            onp.full((b,), -1, "i4"))
        return self._recommit(cache)

    def _warmup_telemetry(self):
        """Post-warmup measurements (outside any serving window):
        the MEASURED per-device bytes of params + live cache
        (``serving.generate.per_device_bytes`` — under
        ``mesh_layout="tp"`` this is each device's SHARE; single-
        device engines report the full footprint), and, for a
        mesh-sharded engine, the decode program's per-step collective
        counts (compiled-HLO evidence feeding the
        ``parallel.collectives.*`` counters each tick)."""
        from ..parallel import partition as _partition
        if callable(getattr(self.model, "collect_params", None)):
            leaves = [p.data()._data
                      for p in self.model.collect_params().values()]
            telemetry.gauge(
                "serving.generate.per_device_bytes",
                _partition.per_device_bytes(leaves + [self._cache]))
        if self._part is not None \
                and callable(getattr(self.model, "decode_hlo", None)):
            if self.speculative and callable(
                    getattr(self.model, "verify_commit_hlo", None)):
                # a speculative engine's steady state runs the fused
                # verify_commit per iteration, never the single-token
                # decode — measure the program the counters describe
                text = self.model.verify_commit_hlo(
                    self.spec_k, self._cache, paged=self.paged)
            else:
                toks = onp.zeros((self.max_slots,), "i4")
                kw = {}
                if self.paged:
                    kw["active"] = onp.ones((self.max_slots,), "i4")
                text = self.model.decode_hlo(toks, self._cache, **kw)
            colls = _partition.hlo_collectives(text)
            self._step_collectives = {
                kind.replace("-", "_"): int(v["count"])
                for kind, v in colls.items()}

    def _warmup_spec(self, cache):
        """Compile the speculative steady state against throwaway
        caches: the draft's prefill buckets, the fused k-step propose
        (greedy AND sampled variants — traffic can flip between them
        as stochastic requests come and go), the fused
        verify+accept+advance (both variants), and the draft-rollback
        advance_len."""
        b, k = self.max_slots, self.spec_k
        zb = onp.zeros((b,), "i4")
        ones = onp.ones((b,), "i4")
        keys = onp.zeros((b, 2), "u4")
        tf = onp.zeros((b,), "f4")
        pf = onp.ones((b,), "f4")
        dcache = self._commit_draft(self.draft.init_cache(b,
                                                          self._s_max))
        for sb in self.policy.sizes(self._s_cap - 1):
            _, dcache = self.draft.prefill(
                onp.zeros((1, sb), "i4"), [sb], dcache, slots=[0])
            dcache = self._recommit_draft(dcache)
        dt, dcache = self.draft.propose_tokens(zb, dcache, k)
        dcache = self._recommit_draft(dcache)
        dt, q, _, dcache = self.draft.propose_tokens(
            zb, dcache, k, keys=keys, temps=tf, top_ks=zb, top_ps=pf)
        dcache = self._recommit_draft(dcache)
        dcache = self._recommit_draft(self.draft.advance_len(zb,
                                                             dcache))
        vc = self.model.verify_commit_paged if self.paged \
            else self.model.verify_commit
        _, _, cache = vc(zb, dt, ones, cache)
        cache = self._recommit(cache)
        _, _, _, cache = vc(zb, dt, ones, cache, q=q, keys=keys,
                            temps=tf, top_ks=zb, top_ps=pf)

    def _warmup_paged(self):
        """Compile the paged steady state against a throwaway cache:
        one fresh-prefill program per bucket <= the chunk width, one
        chunk program per page-multiple width <= the chunk width (tail
        chunks shrink near the cache end), the decode step, the peek
        (prefix-hit) path, and the table-bind / page-copy (COW)
        helpers. Physical page ids are DATA, not shape — id choice
        here is arbitrary."""
        cache = self._commit(self.model.init_paged_cache(
            self.max_slots, self._pool.n_pages, self._ps, self._s_max,
            dtype=self._cache_dtype))
        row = onp.ones((self._p_max,), "i4")
        for sb in self.policy.sizes(self._chunk):
            if sb > self._chunk:
                continue
            _, cache = self.model.prefill_paged(
                onp.zeros((1, sb), "i4"), sb, 0, row, cache,
                fresh=True)
            cache = self._recommit(cache)
        for w in range(self._ps, self._chunk + 1, self._ps):
            _, cache = self.model.prefill_paged(
                onp.zeros((1, w), "i4"), w, 0, row, cache, start=0)
            cache = self._recommit(cache)
        lg, cache = self.model.decode_step_paged(
            onp.zeros((self.max_slots,), "i4"),
            onp.ones((self.max_slots,), "i4"), cache)
        cache = self._recommit(cache)
        if self.decode_ticks > 1:
            cache = self._warmup_multi(cache)
        self.model.peek_logits_paged(0, 0, cache)
        cache = self._recommit(self.model.bind_slot_paged(0, row, 1,
                                                          cache))
        cache = self._recommit(self.model.copy_page_paged(1, 1, cache))
        self._warm_samplers(int(lg.shape[-1]))
        if self.speculative:
            self._warmup_spec(cache)

    def load_weights(self, source, strict: bool = True):
        """Zero-downtime weight rollover: swap the model's parameter
        buffers from a committed checkpoint while traffic is live.

        ``source`` is a checkpoint path (a ``CheckpointManager`` root —
        latest committed step wins — or one step directory) or an
        in-memory ``{name: array}`` mapping. The swap happens at a
        decode-STEP boundary under ``_gen_lock``: in-flight slots keep
        their KV cache and continue decoding (their next token simply
        comes from the new weights), queued requests are untouched, and
        nothing recompiles — the jitted prefill/decode closures take
        parameter buffers as runtime arguments, so installing
        same-shape/dtype buffers into the live parameter NDArrays
        changes no trace (``model.gpt.trace`` stays flat; asserted in
        tests). Sharded parameters keep their placement via
        ``device_put`` onto the old buffer's sharding.

        ``strict=True`` (default) requires the checkpoint names to
        cover the model's parameters exactly; ``strict=False`` swaps
        the intersection. Shape mismatches always raise — before any
        buffer is touched, so a bad checkpoint can never leave the
        model half-swapped."""
        from .. import checkpoint as _ckpt
        if self._closed:
            raise EngineClosedError("load_weights on a closed engine")
        if isinstance(source, dict):
            new_params = source
        else:
            new_params, _meta = _ckpt.read_params(source)
        t0 = telemetry.clock()
        with self._gen_exclusive():  # step boundary: the worker is
            # between decode steps (and yields to us promptly — the
            # waiter signal), warmup is not tracing
            _ckpt.swap_param_buffers(self.model.collect_params(),
                                     new_params, strict=strict)
            if self.quantize is not None:
                # re-quantize from the fresh fp32 buffers INSIDE the
                # swap window: the quant tables are runtime args of
                # the jitted closures, so this installs new int8
                # weights with zero retraces — and a decode step may
                # never see new fp32 params with stale int8 tables
                tq = telemetry.clock()
                self.model.quantize_params()
                if self._part is not None:
                    # fresh tables follow the (still-placed) weights'
                    # axes — re-pin explicitly so the closures keep
                    # seeing the one canonical table sharding
                    self.model.shard_generation_state(self._part)
                telemetry.hist_since(
                    "serving.generate.quant.requantize", tq)
            if self.compute_dtype == "bfloat16":
                # re-cast the bf16 shadow buffers from the fresh fp32
                # masters INSIDE the swap window — same avals, so zero
                # retraces (the quant-table discipline); a decode step
                # may never see stale bf16 params after the swap
                tc = telemetry.clock()
                self.model.cast_compute_params("bfloat16")
                telemetry.hist_since(
                    "serving.generate.cast.recast", tc)
            if self.paged and self._prefix is not None:
                # the prefix cache holds K/V computed with the OLD
                # weights: a post-swap prefix hit would silently serve
                # stale attention context forever. Flush it (pages
                # pinned by in-flight slots stay alive via their own
                # refs — those slots finish on mixed weights, the same
                # documented in-flight tradeoff as the dense rollover)
                # and suppress registration of any prompt prefilled
                # before/across the swap — publishing mixed-weight K/V
                # would poison future requests.
                self._prefix.release_all()
                for s in self._slots:
                    if s is not None:
                        s.prompt = None
        telemetry.hist_since("serving.generate.swap", t0)
        telemetry.counter("serving.generate.weight_swaps")
        return self

    def close(self, timeout: float = 5.0):
        """Stop admission, finish ACTIVE generations and drain the
        queue under ``timeout``; past the deadline queued requests are
        rejected and still-active streams are finished early with
        ``finish_reason="closed"`` — nothing ever hangs. Idempotent;
        also invoked via ``atexit``."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._worker is not None:
            self._worker.close(timeout)
            if not self._worker.is_alive():
                # thread provably dead: it can no longer touch slots
                self._close_active("closed")
        else:
            self._close_active("closed")  # sync mode: nothing active
        _live_engines.discard(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close(timeout=0.5)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    @property
    def closed(self) -> bool:
        return self._closed

    # -- admission -----------------------------------------------------
    def _validate(self, prompt, max_new_tokens, eos_id):
        prompt = onp.asarray(prompt)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(
                f"prompt must be a non-empty 1-D token sequence, got "
                f"shape {prompt.shape}")
        if not onp.issubdtype(prompt.dtype, onp.integer):
            raise ValueError(f"prompt must hold token ids, got dtype "
                             f"{prompt.dtype}")
        if prompt.size > self._s_cap - 1:
            margin = "" if not self.speculative else \
                f" minus the spec_k={self.spec_k} verify margin"
            raise ValueError(
                f"prompt length {prompt.size} leaves no room to "
                f"generate (cache capacity {self._s_max}{margin})")
        max_new = self.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.paged:
            cap = min(int(prompt.size) + max_new, self._s_cap)
            need = -(-cap // self._ps)
            if need > self._pool.n_pages - 1:
                raise ValueError(
                    f"request needs up to {need} KV pages but the pool "
                    f"holds {self._pool.n_pages - 1} allocatable pages")
        eos = self.eos_id if eos_id is None else eos_id
        return prompt.astype("i4"), max_new, eos

    @staticmethod
    def _validate_sampling(temperature, top_k, top_p, seed):
        """Normalize/validate the per-request sampling knobs. Returns
        ``(temperature, top_k, top_p, seed)`` with the greedy/off
        defaults filled in (``0.0``, ``0``, ``1.0``, ``None``). Shared
        with the Router's pre-admission validation."""
        t = 0.0 if temperature is None else float(temperature)
        if not t >= 0.0:   # also rejects NaN
            raise ValueError(
                f"temperature must be >= 0 (0 = greedy), got "
                f"{temperature!r}")
        k = 0 if top_k is None else int(top_k)
        if k < 0:
            raise ValueError(f"top_k must be >= 0 (0 = off), got "
                             f"{top_k!r}")
        p = 1.0 if top_p is None else float(top_p)
        if not 0.0 < p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1] (1 = off), got {top_p!r}")
        if seed is not None:
            seed = int(seed)
        return t, k, p, seed

    def submit(self, prompt, max_new_tokens=None, eos_id=None,
               timeout_ms=None, temperature=None, top_k=None,
               top_p=None, seed=None, adapter=None,
               trace=None) -> GenerationStream:
        """Queue one prompt; returns a :class:`GenerationStream`.
        Raises :class:`EngineClosedError` / :class:`QueueFullError` /
        ``ValueError`` immediately instead of returning a stream that
        can never complete.

        ``temperature``/``top_k``/``top_p`` select per-request
        stochastic sampling (default greedy: ``temperature`` absent or
        0 — ``top_k``/``top_p`` are then ignored). ``seed`` pins the
        request's explicit PRNG key: the same seed yields a bitwise-
        identical token stream on every rerun of the same engine
        configuration, across engine restarts (docs/SERVING.md
        "Speculative decoding & sampling"). Without a seed, a fresh
        one is drawn per request.

        ``adapter`` names a loaded LoRA adapter (``load_adapter``) the
        request decodes under — per-slot runtime data, so any tenant
        mix shares the one compiled program; the adapter stays PINNED
        (unload defers) until the request finishes. Default: the base
        model.

        ``trace`` arms per-request tracing: ``True`` records the
        request's full lifecycle as spans readable via the stream's
        ``trace()``; ``False`` disables it even under
        ``MXTPU_TRACING=1``; ``None`` (default) follows the module
        flag; a ``tracing.Trace`` instance threads an existing trace
        through (the Router's cross-replica retries)."""
        if self._failure is not None:
            telemetry.counter("serving.generate.rejected_closed")
            raise ReplicaFailedError(str(self._failure),
                                     cause=self._failure.cause)
        if self._closed:
            telemetry.counter("serving.generate.rejected_closed")
            raise EngineClosedError("submit on a closed engine")
        prompt, max_new, eos = self._validate(prompt, max_new_tokens,
                                              eos_id)
        temp, tk, tp, seed = self._validate_sampling(
            temperature, top_k, top_p, seed)
        if adapter is not None and not self.lora_enabled:
            raise self._submit_error(
                "adapter", adapter, "this engine has no LoRA bank "
                "(constructed without lora_rank=)")
        key = None
        if temp > 0:
            telemetry.counter("serving.generate.sampling.requests")
            if seed is None:
                seed = int.from_bytes(os.urandom(4), "little")
            key = request_key(seed)
        aidx = 0
        if adapter is not None:
            aidx = self._pin_adapter(adapter)  # raises on unknown name
            telemetry.counter("serving.generate.lora.requests")
        telemetry.counter("serving.generate.requests")
        stream = GenerationStream(int(prompt.size))
        tr = tracing.start_trace(trace)
        if tr is not None:
            stream._trace = tr
            tr.event("submit", prompt_len=int(prompt.size),
                     max_new=max_new)
        if adapter is not None:
            # every stream finishes exactly once on every engine path
            # (the no-hung-stream contract) — the finish callback is
            # therefore the one place the pin reliably drops
            stream._watch(lambda _tok: None,
                          lambda _r, _e: self._unpin_adapter(adapter))
        tmo = self.timeout_ms if timeout_ms is None else timeout_ms
        now = time.monotonic()
        req = _GenRequest(
            prompt, max_new, eos, stream, telemetry.clock(), now,
            now + tmo / 1e3 if tmo is not None else None,
            temperature=temp, top_k=tk, top_p=tp, key=key,
            adapter_idx=aidx)
        if self._sync:  # MXTPU_SERVING=0: inline generation
            with self._gen_lock:
                self._admit_one(req)
                while self._n_active:
                    self._step()
                if self.paged and self._blocked:
                    # an idle sync engine can never unblock a stashed
                    # request (validated capacity makes this a pool-
                    # accounting bug, not a load condition) — reject
                    # rather than hang
                    self._blocked.popleft().stream._finish(
                        exc=QueueFullError(
                            "page pool exhausted for a synchronous "
                            "request"))
            return stream
        try:
            self._worker._queue.put_nowait(req)
        except queue.Full:
            telemetry.counter("serving.generate.rejected_full")
            if adapter is not None:
                # the stream never reaches the engine, so its finish
                # callback never fires — drop the pin here
                self._unpin_adapter(adapter)
            raise QueueFullError(
                f"request queue at queue_limit={self.queue_limit}") \
                from None
        telemetry.gauge("serving.generate.queue.depth",
                        self._worker._queue.qsize())
        if self._failure is not None:
            # the worker died while the request was being queued: its
            # drain may have missed this request — fail it ourselves
            stream._finish(exc=ReplicaFailedError(
                str(self._failure), cause=self._failure.cause))
        elif self._closed:
            # close() raced the put: its drain may have missed this
            # request — reject it ourselves (no-op if already handled)
            stream._finish(exc=EngineClosedError(
                "engine closed while the request was being queued"))
        return stream

    def generate(self, prompt, timeout=None, **kwargs) -> GenerationResult:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt, **kwargs).result(timeout)

    # -- scheduling (generator thread / sync mode) ---------------------
    def _admit(self, q):
        if self.paged:
            # page-starved requests wait in _blocked (FIFO — younger
            # queue entries must not starve an older blocked one).
            # queue_wait is recorded at the ACTUAL admission (or the
            # rejection), so time spent blocked on KV pages shows up
            # in the histogram an operator reads next to pages.free
            while self._blocked and self._n_active < self.max_slots:
                r = self._blocked[0]
                waited_ms = (time.monotonic() - r.t_enq) * 1e3
                if r.deadline is not None \
                        and time.monotonic() > r.deadline:
                    telemetry.hist("serving.generate.queue_wait",
                                   waited_ms)
                    telemetry.counter("serving.generate.timeouts")
                    r.stream._finish(exc=RequestTimeoutError(
                        f"request deadline expired while awaiting KV "
                        f"pages (waited {waited_ms:.1f} ms)"))
                    self._blocked.popleft()
                    continue
                if not self._try_admit_paged(r):
                    break
                telemetry.hist("serving.generate.queue_wait", waited_ms)
                if r.stream._trace is not None:
                    r.stream._trace.add_ms("queue", waited_ms,
                                           blocked=True)
                self._blocked.popleft()
        while self._n_active < self.max_slots \
                and not (self.paged and self._blocked):
            try:
                r = q.get_nowait()
            except queue.Empty:
                break
            self._admit_one(r)
        telemetry.gauge(
            "serving.generate.queue.depth",
            q.qsize() + (len(self._blocked) if self.paged else 0))

    def _admit_one(self, r: _GenRequest):
        """Admit ``r`` into a free slot and (dense mode) prefill it and
        emit its first token; paged mode allocates its pages and either
        peeks the first token off a fully-cached prefix or queues its
        prefill chunks. Called only at step boundaries."""
        waited_ms = (time.monotonic() - r.t_enq) * 1e3
        if r.deadline is not None and time.monotonic() > r.deadline:
            telemetry.hist("serving.generate.queue_wait", waited_ms)
            telemetry.counter("serving.generate.timeouts")
            r.stream._finish(exc=RequestTimeoutError(
                f"request expired in queue before prefill (waited "
                f"{waited_ms:.1f} ms)"))
            return
        try:
            self._admit_one_inner(r, waited_ms)
        except Exception as e:  # noqa: BLE001 — the worker is about to
            # die (_fail_all); without this the IN-HAND request —
            # already popped from the queue, not yet in a slot — would
            # be invisible to the cleanup and hang its caller forever
            r.stream._finish(exc=ReplicaFailedError(
                f"admission failed: {type(e).__name__}: {e}", cause=e))
            raise

    def _admit_one_inner(self, r: _GenRequest, waited_ms):
        if self.paged:
            # a page-starved request goes to _blocked: its queue_wait
            # is recorded when it actually admits (or rejects), not
            # here — the blocked time is the interesting part
            if self._try_admit_paged(r):
                telemetry.hist("serving.generate.queue_wait",
                               waited_ms)
                if r.stream._trace is not None:
                    r.stream._trace.add_ms("queue", waited_ms)
            else:
                if r.stream._trace is not None:
                    r.stream._trace.event("deferred", why="kv_pages")
                self._blocked.append(r)
            return
        telemetry.hist("serving.generate.queue_wait", waited_ms)
        tr = r.stream._trace
        if tr is not None:
            tr.add_ms("queue", waited_ms)
        slot = self._slots.index(None)
        n = int(r.prompt.size)
        if tr is not None:
            tr.event("admission", slot=slot, mode="dense")
        tracing.flight.record("gen.admit", slot=slot, mode="dense",
                              trace_id=r.stream.trace_id)
        sb = self.policy.bucket(n)
        padded = onp.zeros((1, sb), "i4")
        padded[0, :n] = r.prompt
        self._arm_sampling(slot, r)
        pt0 = time.perf_counter() if tr is not None else 0.0
        t0 = telemetry.clock()
        logits, self._cache = self.model.prefill(
            padded, onp.asarray([n], "i4"), self._cache,
            slots=onp.asarray([slot], "i4"),
            **self._akw(self._adapter_idx[slot:slot + 1]))
        if self._part is not None:
            self._cache = self._recommit(self._cache)
        if self.speculative:
            # the draft mirrors the target's committed prefix from the
            # moment the slot exists — its own (dense) prefill of the
            # same padded prompt into the same slot row
            _, self._draft_cache = self.draft.prefill(
                padded, onp.asarray([n], "i4"), self._draft_cache,
                slots=onp.asarray([slot], "i4"))
            self._draft_cache = self._recommit_draft(self._draft_cache)
        telemetry.hist_since("serving.generate.prefill", t0)
        telemetry.counter("serving.generate.prefills")
        if tr is not None:
            tr.add("prefill", pt0, slot=slot, tokens=n)
        tok = self._pick_first(slot, onp.asarray(logits)[0])
        s = _Slot(r.stream, tok, r.max_new - 1, r.eos_id, r.deadline,
                  n_ctx=n)
        self._slots[slot] = s
        self._n_active += 1
        r.stream._emit(tok)
        telemetry.counter("serving.generate.tokens")
        telemetry.hist_since("serving.generate.ttft", r.t_submit)
        if s.eos_id is not None and tok == s.eos_id:
            self._evict(slot, "eos")
        elif s.left <= 0 or s.n_ctx >= self._s_cap:
            self._evict(slot, "length")
        else:
            telemetry.gauge("serving.generate.slots", self._n_active)

    def _arm_sampling(self, slot: int, r: _GenRequest):
        """Install a request's sampling knobs into the per-slot
        vectors the fixed-shape programs read (greedy requests write
        the defaults — the vectors must never carry a previous
        tenant's state). The PRNG key is installed here only in DENSE
        mode, where admission prefills synchronously and the first
        pick follows immediately; a PAGED slot can sit in its prefill
        phase for several iterations whose decode ticks split EVERY
        row's key — installing at admission would make the
        pre-first-token split count depend on co-tenant activity and
        break seeded reproducibility, so the key waits on the slot
        (``_PagedSlot.key``) until ``_first_token`` installs it."""
        self._temps[slot] = r.temperature
        self._topks[slot] = r.top_k
        self._topps[slot] = r.top_p
        self._adapter_idx[slot] = r.adapter_idx
        if r.temperature > 0:
            self._n_sampling += 1
            if not self.paged:
                self._keys[slot] = r.key

    def _akw(self, idx):
        """``adapters=`` kwarg for a model call — present only on a
        LoRA-enabled engine, so other decoder families never need to
        grow the keyword."""
        return {"adapters": idx} if self.lora_enabled else {}

    def _pick_first(self, slot: int, logits_row):
        """First token of a fresh admission, from its prefill/peek
        logits row: host argmax for greedy slots (bit-identical to the
        pre-sampling engine), the jitted (1, V) sampler for stochastic
        ones — the same key chain the decode steps continue."""
        logits_row = logits_row.reshape(-1)
        if self._temps[slot] <= 0:
            return int(logits_row.argmax())
        smp = self._ensure_samplers()
        tok, nk = smp["sample"](
            self._keys[slot:slot + 1],
            onp.asarray(logits_row, "f4")[None],
            self._temps[slot:slot + 1], self._topks[slot:slot + 1],
            self._topps[slot:slot + 1])
        self._keys[slot] = onp.asarray(nk)[0]
        return int(onp.asarray(tok)[0])

    # -- paged scheduling ----------------------------------------------
    def _alloc_pages(self, n):
        """Allocate ``n`` pool pages, evicting LRU cached prefixes to
        make room; None when even an empty prefix cache can't cover
        them (the pages are pinned by active slots)."""
        out = self._pool.alloc(n)
        while out is None and self._prefix is not None \
                and self._prefix.evict_lru():
            out = self._pool.alloc(n)
        return out

    def _release_pages(self, pids):
        for pid in pids:
            self._pool.release(pid)

    def _try_admit_paged(self, r: _GenRequest) -> bool:
        """Place ``r`` into a free slot: match the longest cached
        prefix, reserve its worst-case private pages (so decode can
        never run out mid-sequence), and either peek its first token
        straight off a fully-cached prompt or queue its prefill
        chunks. False when the pool (after prefix-cache eviction)
        cannot cover the reservation — the request stays blocked."""
        length = int(r.prompt.size)
        ps = self._ps
        cap_pages = -(-min(length + r.max_new, self._s_cap) // ps)
        shared_pages, shared_tokens = [], 0
        if self._prefix is not None and r.adapter_idx == 0:
            # prefix reuse is BASE-MODEL-only: cached pages hold K/V
            # computed under the projections that prefilled them, and
            # an adapter changes q/k/v — serving one tenant's pages to
            # another (or adapted pages to base traffic) would
            # silently swap attention context. Adapter requests always
            # prefill fresh and never publish to the index.
            shared_pages, shared_tokens = self._prefix.match(r.prompt)
        peek = shared_tokens == length
        first_write = (length if peek else shared_tokens) // ps
        # retain the matched pages BEFORE allocating: _alloc_pages may
        # LRU-evict the very record backing them, and unretained pages
        # would return to the free list and come straight back as this
        # request's PRIVATE pages (LIFO) — the row would alias shared
        # and private, and chunk prefill would overwrite the shared
        # prefix K/V (found by review with a live tight-pool repro)
        refs = []
        n_shared = len(shared_pages) if peek else first_write
        for i in range(n_shared):
            self._pool.retain(shared_pages[i])
            refs.append(shared_pages[i])
        private = self._alloc_pages(cap_pages - first_write)
        if private is None and refs:
            # our retained prefix refs pinned exactly the pages the
            # allocator's eviction sweep tried to reclaim: drop the
            # match and retry UNSHARED — a transiently page-heavy
            # prefix hit must degrade to a plain prefill, not fail an
            # admission a retry would satisfy
            self._release_pages(refs)
            refs = []
            shared_pages, shared_tokens = [], 0
            peek = False
            first_write = n_shared = 0
            private = self._alloc_pages(cap_pages)
        if private is None:
            self._release_pages(refs)
            return False
        slot = self._slots.index(None)
        tr = r.stream._trace
        if tr is not None:
            tr.event("admission", slot=slot, mode="paged", peek=peek,
                     prefix_tokens=shared_tokens)
        tracing.flight.record("gen.admit", slot=slot, mode="paged",
                              peek=peek, prefix_tokens=shared_tokens,
                              trace_id=r.stream.trace_id)
        row = onp.zeros((self._p_max,), "i4")   # scrap past the cap
        for i in range(n_shared):
            row[i] = shared_pages[i]
        refs.extend(private)
        s = _PagedSlot(r.stream, r.max_new, r.eos_id, r.deadline,
                       n_ctx=length, row=row, page_refs=refs,
                       prompt=r.prompt, seq=self._seq,
                       t_submit=r.t_submit)
        s.adapter_idx = r.adapter_idx
        if self.speculative:
            # survives prefix registration (which clears s.prompt):
            # the draft's dense prefill runs when the slot enters
            # decode, prefix hit or not — the draft has no prefix cache
            s.draft_prompt = r.prompt
        s.key = r.key   # installed at decode entry (_first_token)
        self._arm_sampling(slot, r)
        self._seq += 1
        if peek:
            if length % ps:
                # the shared partial tail is this slot's divergence
                # page: COW it right before the first decode write
                s.cow_pending = (int(row[first_write]), private[0],
                                 first_write)
                row[first_write + 1:cap_pages] = private[1:]
            else:
                row[first_write:cap_pages] = private
            telemetry.counter("serving.generate.prefix_hits")
            self._slots[slot] = s
            self._n_active += 1
            pt0 = time.perf_counter() if tr is not None else 0.0
            t0 = telemetry.clock()
            self._cache = self._recommit(self.model.bind_slot_paged(
                slot, row, length, self._cache))
            logits = self.model.peek_logits_paged(
                int(r.prompt[-1]), slot, self._cache,
                **self._akw(self._adapter_idx[slot:slot + 1]))
            telemetry.hist_since("serving.generate.prefill", t0)
            telemetry.counter("serving.generate.prefills")
            if tr is not None:
                tr.add("prefill", pt0, slot=slot, tokens=length,
                       peek=True)
            self._register_prefix(s)
            self._first_token(slot, s, onp.asarray(logits))
            return True
        row[first_write:cap_pages] = private
        start0 = first_write * ps
        fresh = (start0 == 0
                 and self.policy.bucket(length) <= self._chunk)
        if fresh:
            w = self.policy.bucket(length)
            toks = onp.zeros((1, w), "i4")
            toks[0, :length] = r.prompt
            s.chunks.append((toks, 0, length, True))
        else:
            pos = start0
            while pos < length:
                w = min(self._chunk, self._s_max - pos)
                nv = min(w, length - pos)
                toks = onp.zeros((1, w), "i4")
                toks[0, :nv] = r.prompt[pos:pos + nv]
                s.chunks.append((toks, pos, nv, False))
                pos += nv
        self._slots[slot] = s
        self._n_active += 1
        return True

    def _register_prefix(self, s: _PagedSlot):
        """Publish a completed prompt's pages to the prefix index so
        later identical/shared-prefix requests reuse them. When the
        prompt ends mid-page and this slot will keep decoding, the now
        index-retained tail page becomes shared — arm a COW so the
        slot's first decode write copies it instead of corrupting the
        cached prefix."""
        if self._prefix is None or s.prompt is None \
                or s.adapter_idx != 0:  # adapted K/V never publishes
            return
        length = int(s.prompt.size)
        needs_cow = (length % self._ps != 0 and s.cow_pending is None
                     and s.left > 1 and s.n_ctx < self._s_cap)
        dst = None
        if needs_cow:
            dst = self._alloc_pages(1)
            if dst is None:
                return  # can't afford to freeze the tail: skip caching
        if not self._prefix.register(s.prompt, s.row):
            if dst:
                self._release_pages(dst)
        elif dst:
            s.cow_pending = (int(s.row[length // self._ps]), dst[0],
                             length // self._ps)
            s.page_refs.append(dst[0])
        s.prompt = None

    def _first_token(self, slot: int, s: _PagedSlot, logits_row):
        """Emit a freshly-admitted request's first token (from its last
        prefill chunk's logits or the prefix-hit peek) — the paged
        analog of dense ``_admit_one``'s tail. In speculative mode the
        slot's entry into decode is also where the DRAFT catches up:
        one dense draft prefill of the full prompt (the draft has no
        paged pool and no prefix cache — it is small enough that a
        monolithic prefill is cheaper than teaching it chunking)."""
        if self.speculative and s.draft_prompt is not None:
            n = int(s.draft_prompt.size)
            sb = self.policy.bucket(n)
            padded = onp.zeros((1, sb), "i4")
            padded[0, :n] = s.draft_prompt
            _, self._draft_cache = self.draft.prefill(
                padded, onp.asarray([n], "i4"), self._draft_cache,
                slots=onp.asarray([slot], "i4"))
            self._draft_cache = self._recommit_draft(self._draft_cache)
            s.draft_prompt = None
        if s.key is not None:
            # decode entry is where the request's PRNG key goes live:
            # installing it at admission would let every co-tenant
            # tick during the chunked prefill split it (the
            # fixed-shape programs advance ALL rows), making the
            # stream depend on co-tenant activity
            self._keys[slot] = s.key
            s.key = None
        tok = self._pick_first(
            slot, logits_row.reshape(-1, logits_row.shape[-1])[0])
        s.last = tok
        s.left -= 1
        s.state = "decode"
        s.stream._emit(tok)
        telemetry.counter("serving.generate.tokens")
        telemetry.hist_since("serving.generate.ttft", s.t_submit)
        if s.eos_id is not None and tok == s.eos_id:
            self._evict(slot, "eos")
        elif s.left <= 0 or s.n_ctx >= self._s_cap:
            self._evict(slot, "length")
        else:
            telemetry.gauge("serving.generate.slots", self._n_active)

    def _prefill_tick(self) -> int:
        """Run AT MOST ONE prefill chunk (oldest admitted slot first):
        the decode-stall bound — a 192-token prompt spends several
        iterations prefilling, each interleaved with a decode step over
        the in-flight slots, so TPOT p99 is bounded by one chunk, not
        one monolithic prefill."""
        best = None
        for i, s in enumerate(self._slots):
            if s is not None and s.state == "prefill" \
                    and (best is None or s.seq < self._slots[best].seq):
                best = i
        if best is None:
            return 0
        s = self._slots[best]
        if s.deadline is not None and time.monotonic() > s.deadline:
            telemetry.counter("serving.generate.timeouts")
            self._evict_exc(best, RequestTimeoutError(
                "request deadline expired during chunked prefill"))
            return 0
        toks, start, n_valid, fresh = s.chunks.popleft()
        tr = s.stream._trace
        pt0 = time.perf_counter() if tr is not None else 0.0
        t0 = telemetry.clock()
        logits, self._cache = self.model.prefill_paged(
            toks, n_valid, best, s.row, self._cache, start=start,
            fresh=fresh,
            **self._akw(self._adapter_idx[best:best + 1]))
        self._cache = self._recommit(self._cache)
        telemetry.hist_since("serving.generate.prefill", t0)
        telemetry.counter("serving.generate.prefill_chunks")
        if tr is not None:
            tr.add("prefill_chunk", pt0, slot=best, start=start,
                   tokens=n_valid)
        self._chunks_this_iter += 1
        if not s.chunks:
            telemetry.counter("serving.generate.prefills")
            self._register_prefix(s)
            self._first_token(best, s, onp.asarray(logits))
        return 1

    def _cow_sweep(self):
        """Copy-on-write: a decoding slot whose next cache write would
        land in a SHARED page copies the divergence page first and
        rebinds its table row. Runs before every paged decode/verify
        step (a speculative verify writes through the same table)."""
        for i, s in enumerate(self._slots):
            if s is not None and s.state == "decode" \
                    and s.cow_pending is not None:
                src, dst, logical = s.cow_pending
                tr = s.stream._trace
                pt0 = time.perf_counter() if tr is not None else 0.0
                self._cache = self._recommit(self.model.copy_page_paged(
                    src, dst, self._cache))
                s.row[logical] = dst
                self._cache = self._recommit(self.model.bind_slot_paged(
                    i, s.row, s.n_ctx, self._cache))
                self._pool.release(src)
                s.page_refs.remove(src)
                s.cow_pending = None
                telemetry.counter("serving.generate.pages.cow_copies")
                if tr is not None:
                    tr.add("cow_copy", pt0, slot=i, src=src, dst=dst)

    def _pick_step_tokens(self, logits):
        """Per-slot next tokens from a decode step's raw (B, V)
        logits: the host argmax when every active slot is greedy (the
        pre-sampling engine's exact path), otherwise one fixed-shape
        sampler call whose greedy rows are in-program argmax (the same
        ints) and whose stochastic rows consume their slot's key."""
        if self._n_sampling:
            if self._part is not None:
                # TP mode: hand the sampler HOST logits — the device
                # logits carry a GSPMD-chosen (vocab-sharded) layout,
                # and the sampler's pjit executable cache keys on
                # input shardings; warmup fed host arrays, so the
                # live path must too (one signature per program)
                logits = onp.asarray(logits)
            tok, nk = self._ensure_samplers()["sample"](
                self._keys, logits, self._temps, self._topks,
                self._topps)
            # onp.array, not asarray: a jax array converts to a
            # READ-ONLY numpy view, and _arm_sampling assigns into
            # this buffer per admission
            self._keys = onp.array(nk, dtype="u4")
            return onp.asarray(tok)
        return onp.asarray(logits).argmax(axis=-1)

    def _decode_idxs(self):
        """The slots a decode/spec tick serves this iteration: every
        occupied slot (dense mode — dense slots are always decoding)
        or every slot in its decode phase (paged mode — prefilling
        slots ride the fixed-shape program masked out)."""
        return [i for i, s in enumerate(self._slots)
                if s is not None
                and (not self.paged or s.state == "decode")]

    def _tick_counters(self, dispatches, fused):
        """Amortization telemetry, bumped once per decode/spec tick:
        the tick materialized its outputs in ONE host sync
        (``host_syncs``), dispatched ``dispatches`` jitted programs
        to produce them, and fused ``fused`` decode iterations behind
        that sync (``ticks_per_sync`` — the ``decode_ticks`` knob's
        live readout; 1 on a plain tick). ``bench.py --latency``
        gates host-syncs/token and dispatch counts from these
        counters, so the amortization is measured, never asserted."""
        telemetry.counter("serving.generate.host_syncs")
        telemetry.counter("serving.generate.dispatches",
                          int(dispatches))
        telemetry.gauge("serving.generate.ticks_per_sync", int(fused))

    def _commit_outputs(self, idxs, outs, span_cb, clipped=None):
        """The ONE host-commit bookkeeping loop every tick flavor
        (plain, multi-tick, speculative) funnels through: record the
        slot's tracing span (``span_cb(slot, s, out)``), emit its
        token block, advance its budget/length counters, and apply
        the eviction ladder — eos first, then budget/capacity
        (``clipped`` marks speculative slots whose emission was
        clipped short of the in-program commit: exhausted even when
        the counters alone would not say so), then deadline (checked
        once per BLOCK — a multi-token tick times out at block
        granularity). Returns the number of tokens emitted."""
        now = time.monotonic()
        n_emitted = 0
        for i in idxs:
            s = self._slots[i]
            out = outs[i]
            span_cb(i, s, out)
            s.stream._emit_many(out)
            n_emitted += len(out)
            if not out:   # can only mean an exhausted slot the evict
                self._evict(i, "length")     # checks below would have
                continue                     # caught last tick
            s.last = out[-1]
            s.left -= len(out)
            s.n_ctx += len(out)
            if s.eos_id is not None and out[-1] == s.eos_id:
                self._evict(i, "eos")
            elif s.left <= 0 or s.n_ctx >= self._s_cap \
                    or (clipped is not None and clipped.get(i)):
                self._evict(i, "length")
            elif s.deadline is not None and now > s.deadline:
                telemetry.counter("serving.generate.timeouts")
                self._evict(i, "timeout")
        if n_emitted:  # one delta per tick, not one call per token
            telemetry.counter("serving.generate.tokens", n_emitted)
        telemetry.gauge("serving.generate.slots", self._n_active)
        return n_emitted

    def _decode_tick(self):
        """One decode tick over all DECODING slots — dense and paged
        (prefilling paged slots ride along masked out: their writes
        are redirected to the scrap page and their ``len`` stands
        still). With ``decode_ticks > 1`` the tick runs the fused
        multi-tick scan instead of the single-step program
        (docs/SERVING.md "Multi-tick decode"): one host sync commits
        up to k tokens per slot."""
        if self.paged:
            self._cow_sweep()
        idxs = self._decode_idxs()
        if not idxs:
            return
        if self.decode_ticks > 1:
            self._decode_tick_multi(idxs)
            return
        toks = onp.zeros((self.max_slots,), "i4")
        active = onp.zeros((self.max_slots,), "i4")
        any_trace = False
        for i in idxs:
            s = self._slots[i]
            toks[i] = s.last
            active[i] = 1
            if s.stream._trace is not None:
                any_trace = True
        tt0 = time.perf_counter() if any_trace else 0.0
        t0 = telemetry.clock()
        if self.paged:
            logits, self._cache = self.model.decode_step_paged(
                toks, active, self._cache,
                **self._akw(self._adapter_idx))
            self._cache = self._recommit(self._cache)
        else:
            logits, self._cache = self.model.decode_step(
                toks, self._cache, **self._akw(self._adapter_idx))
            if self._part is not None:
                self._cache = self._recommit(self._cache)
        self._emit_collectives()
        telemetry.hist_since("serving.generate.decode", t0)
        step_toks = self._pick_step_tokens(logits)
        self._tick_counters(1, 1)
        outs = {i: [int(step_toks[i])] for i in idxs}

        def span(i, s, out):
            if s.stream._trace is not None:
                s.stream._trace.add("decode", tt0, slot=i,
                                    token=out[-1])
        self._commit_outputs(idxs, outs, span)

    def _decode_tick_multi(self, idxs):
        """One MULTI-TICK decode tick: ``decode_ticks`` fused decode
        iterations in ONE jitted scan, committed through one host
        sync. Per-slot eos/budget stop handling runs IN-PROGRAM — a
        finished slot keeps scanning against its frozen/scrap
        position with its emissions masked — so the host receives a
        finished (B, k) token block plus its emission mask and
        commits each slot's prefix in one ``_emit_many``. Budgets
        are clamped host-side to each slot's remaining token budget
        and capacity headroom, so the scan can never over-emit; mixed
        greedy/stochastic batches and every per-request knob are
        runtime vectors (keys split per scan step in-trace), so
        steady-state traffic compiles nothing."""
        k = self.decode_ticks
        b = self.max_slots
        toks = onp.zeros((b,), "i4")
        budgets = onp.zeros((b,), "i4")
        eos_ids = onp.full((b,), -1, "i4")
        any_trace = False
        for i in idxs:
            s = self._slots[i]
            toks[i] = s.last
            budgets[i] = min(k, s.left, self._s_cap - s.n_ctx)
            if s.eos_id is not None:
                eos_ids[i] = s.eos_id
            if s.stream._trace is not None:
                any_trace = True
        tt0 = time.perf_counter() if any_trace else 0.0
        t0 = telemetry.clock()
        fn = self.model.decode_multi_paged if self.paged \
            else self.model.decode_multi
        tok_blk, emit_blk, keys, self._cache = fn(
            toks, budgets, self._cache, k, self._keys, self._temps,
            self._topks, self._topps, eos_ids,
            **self._akw(self._adapter_idx))
        if self.paged or self._part is not None:
            self._cache = self._recommit(self._cache)
        self._emit_collectives()
        tok_h = onp.asarray(tok_blk)   # the (B, k) block's ONE sync
        emit_h = onp.asarray(emit_blk)
        # onp.array, not asarray: a jax array converts to a READ-ONLY
        # numpy view, and _arm_sampling assigns into this buffer
        self._keys = onp.array(keys, dtype="u4")
        telemetry.hist_since("serving.generate.decode", t0)
        self._tick_counters(1, k)
        outs = {i: [int(t) for t in tok_h[i, :int(emit_h[i].sum())]]
                for i in idxs}

        def span(i, s, out):
            # ONE span covering the whole k-token block (never k
            # spans, never zero) — the flight/trace contract
            if s.stream._trace is not None:
                s.stream._trace.add("decode", tt0, slot=i,
                                    tokens=len(out))
        self._commit_outputs(idxs, outs, span)

    def _evict_exc(self, slot: int, exc):
        """Reject a slot whose stream has delivered nothing yet (a
        prefill-phase deadline): an exception, not a truncated
        result."""
        s = self._slots[slot]
        if s.stream._trace is not None:
            s.stream._trace.event("evict", slot=slot,
                                  error=f"{type(exc).__name__}: {exc}")
        tracing.flight.record("gen.evict", slot=slot,
                              error=type(exc).__name__,
                              trace_id=s.stream.trace_id)
        s.stream._finish(exc=exc)
        self._free_slot(slot)

    def _release_slot_refs(self, s):
        if self.paged and s.page_refs:
            self._release_pages(s.page_refs)
            s.page_refs = []

    def _free_slot(self, slot: int):
        s = self._slots[slot]
        self._release_slot_refs(s)
        self._slots[slot] = None
        self._n_active -= 1
        if self._temps[slot] > 0:
            self._n_sampling -= 1
        self._temps[slot] = 0.0    # the next tenant must never read a
        self._topks[slot] = 0      # previous request's knobs
        self._topps[slot] = 1.0
        self._adapter_idx[slot] = 0  # freed rows decode as base
        telemetry.counter("serving.generate.evictions")
        telemetry.gauge("serving.generate.slots", self._n_active)

    def _step(self):
        """One engine iteration. Paged mode: at most one prefill chunk
        (``_prefill_tick``) then one fixed-shape decode step over the
        decoding slots. Dense mode: one decode step over ALL slots;
        emit one token per live slot, evict finished slots (their rows
        are free for the next admission — mid-sequence, zero
        recompiles)."""
        if self.paged:
            # the gauge counts EVERY chunk run inside this iteration
            # (accumulated by _prefill_tick itself, not inferred from
            # its call count) so the one-chunk decode-stall bound is
            # falsifiable: a future second tick call would push the
            # peak past 1 and fail the tests/bench gate
            self._chunks_this_iter = 0
            self._prefill_tick()
            telemetry.gauge("serving.generate.prefill_chunks_per_iter",
                            self._chunks_this_iter)
            if any(s is not None and s.state == "decode"
                   for s in self._slots):
                if self.speculative:
                    self._spec_tick()
                else:
                    self._decode_tick()
            return
        if self.speculative:
            self._spec_tick()
            return
        self._decode_tick()

    # -- speculative decoding (docs/SERVING.md) -------------------------
    def _spec_tick(self):
        """One speculative iteration over every decoding slot: the
        draft proposes ``spec_k`` tokens per slot (k dense draft
        steps, tokens and keys chained on-device — no host sync), the
        target verifies all ``k + 1`` positions in ONE fixed-shape
        program, the accept rule (ops/sampling.py) commits the
        accepted prefix plus one target-derived token, and both caches
        advance to the accept point (``advance_len`` — the rejected
        tail sits above the ``len`` waterline and the next verify
        overwrites it; the draft, which ran k steps, ROLLS BACK by the
        same counter). Greedy slots commit exactly the tokens
        non-speculative decode would; stochastic slots commit a
        sample from exactly the warped target distribution."""
        if self.paged:
            self._cow_sweep()
        idxs = self._decode_idxs()
        if not idxs:
            return
        k = self.spec_k
        b = self.max_slots
        toks = onp.zeros((b,), "i4")
        active = onp.zeros((b,), "i4")
        any_trace = False
        for i in idxs:
            toks[i] = self._slots[i].last
            active[i] = 1
            if self._slots[i].stream._trace is not None:
                any_trace = True
        tt0 = time.perf_counter() if any_trace else 0.0
        sampled = bool(self._n_sampling)
        t0 = telemetry.clock()
        # three dispatches + one host sync per iteration: the fused
        # k-step draft propose, the fused verify+accept+advance, and
        # the draft rollback — at serving model sizes the per-call
        # dispatch overhead dominates, so the k draft steps, the k+1
        # verify, the accept rule and the len bump each run INSIDE
        # one program instead of as ~3k separate calls
        if sampled:
            dt, q, keys, self._draft_cache = self.draft.propose_tokens(
                toks, self._draft_cache, k, keys=self._keys,
                temps=self._temps, top_ks=self._topks,
                top_ps=self._topps)
            self._draft_cache = self._recommit_draft(self._draft_cache)
            commit, n_commit, keys, self._cache = (
                self.model.verify_commit_paged if self.paged
                else self.model.verify_commit)(
                toks, dt, active, self._cache, q=q, keys=keys,
                temps=self._temps, top_ks=self._topks,
                top_ps=self._topps,
                **self._akw(self._adapter_idx))
        else:
            dt, self._draft_cache = self.draft.propose_tokens(
                toks, self._draft_cache, k)
            self._draft_cache = self._recommit_draft(self._draft_cache)
            commit, n_commit, self._cache = (
                self.model.verify_commit_paged if self.paged
                else self.model.verify_commit)(
                toks, dt, active, self._cache,
                **self._akw(self._adapter_idx))
        self._cache = self._recommit(self._cache)
        self._emit_collectives()
        commit_h = onp.asarray(commit)    # the tick's one host sync
        n_h = onp.asarray(n_commit)
        if sampled:
            self._keys = onp.array(keys, dtype="u4")  # writable copy
        telemetry.hist_since("serving.generate.decode", t0)
        # commit bookkeeping: eos cuts the emission at the stop token,
        # budget/capacity clip it. A clipped slot is EVICTED, so the
        # cache's full-commit len (advanced in-program) is a dead
        # row's counter; the draft rolls back by the same arithmetic
        # (it ran k steps on every row — fixed shape).
        ddelta = onp.full((b,), -k, "i4")
        outs = {}
        clipped = {}
        proposed = len(idxs) * k
        accepted = 0
        for i in idxs:
            s = self._slots[i]
            m = int(n_h[i])
            accepted += m - 1
            out = [int(t) for t in commit_h[i, :m]]
            if s.eos_id is not None and s.eos_id in out:
                out = out[:out.index(s.eos_id) + 1]
            out = out[:min(len(out), s.left, self._s_cap - s.n_ctx)]
            outs[i] = out
            clipped[i] = len(out) < m
            ddelta[i] += m
        self._draft_cache = self._recommit_draft(
            self.draft.advance_len(ddelta, self._draft_cache))
        telemetry.counter("serving.generate.spec.proposed", proposed)
        telemetry.counter("serving.generate.spec.accepted", accepted)
        telemetry.counter("serving.generate.spec.rejected",
                          proposed - accepted)
        if proposed:
            telemetry.gauge("serving.generate.spec.accept_rate",
                            accepted / proposed)
        # propose + verify_commit + draft advance = 3 dispatches; the
        # one host sync amortizes over up to k+1 tokens per slot
        self._tick_counters(3, k + 1)

        def span(i, s, out):
            if s.stream._trace is not None:
                s.stream._trace.add("verify", tt0, slot=i, proposed=k,
                                    committed=len(out))
        n_emitted = self._commit_outputs(idxs, outs, span,
                                         clipped=clipped)
        telemetry.gauge("serving.generate.spec.tokens_per_step",
                        n_emitted)

    def _evict(self, slot: int, reason: str):
        s = self._slots[slot]
        if s.stream._trace is not None:
            s.stream._trace.event("evict", slot=slot, reason=reason)
        tracing.flight.record("gen.evict", slot=slot, reason=reason,
                              trace_id=s.stream.trace_id)
        s.stream._finish(reason=reason)
        self._free_slot(slot)

    def _close_active(self, reason: str):
        """Finish every still-active stream with ``reason`` (idempotent
        per stream: a first outcome stands) and free the slots. A paged
        slot still in its PREFILL phase has delivered nothing — it is
        rejected with :class:`EngineClosedError` like a queued request,
        never handed an empty 'successful' result. Paged mode also
        rejects page-starved blocked requests."""
        for i, s in enumerate(self._slots):
            if s is not None:
                if self.paged and s.state == "prefill":
                    s.stream._finish(exc=EngineClosedError(
                        "engine closed during chunked prefill (no "
                        "tokens were generated)"))
                else:
                    s.stream._finish(reason=reason)
                self._release_slot_refs(s)
                self._slots[i] = None
        self._n_active = 0
        self._n_sampling = 0
        self._teardown_paged(EngineClosedError(
            "engine closed while the request awaited KV pages"))

    def _teardown_paged(self, exc):
        """Terminal paged cleanup shared by close and worker-crash:
        reject every page-starved blocked request with ``exc`` and
        drain the prefix index — a dead engine serves nothing, and the
        pool/gauge must read fully free afterwards (post-close
        accounting, dashboards, leak checks)."""
        if not self.paged:
            return
        while self._blocked:
            self._blocked.popleft().stream._finish(exc=exc)
        if self._prefix is not None:
            self._prefix.release_all()

    def _fail_all(self, exc):
        """Worker crashed mid-step (the cache may hold donated/invalid
        buffers): fail every live stream and queued request with a
        :class:`ReplicaFailedError` — retryable replica death, NOT a
        deliberate close — and close the engine; a broken engine must
        reject, not wedge."""
        failure = exc if isinstance(exc, ReplicaFailedError) \
            else ReplicaFailedError(
                f"generation worker died: {type(exc).__name__}: {exc}",
                cause=exc)
        if not isinstance(exc, ReplicaFailedError):
            failure.__cause__ = exc
        self._failure = failure
        self._closed = True
        tracing.flight.dump("engine.fail_all",
                            error=f"{type(exc).__name__}: {exc}")
        for i, s in enumerate(self._slots):
            if s is not None:
                s.stream._finish(exc=failure)
                self._release_slot_refs(s)
                self._slots[i] = None
        self._n_active = 0
        self._n_sampling = 0
        self._teardown_paged(failure)
        if self._worker is not None:
            self._worker._stopped = True  # a still-looping worker (an
            # injected failure, not a real crash) exits at its next poll
            try:
                while True:
                    r = self._worker._queue.get_nowait()
                    r.stream._finish(exc=failure)
            except queue.Empty:
                pass
        _live_engines.discard(self)

"""Host-side bookkeeping for the paged KV cache: the page allocator
and the shared-prefix index.

The DEVICE side of paging lives in ``gluon/model_zoo/gpt.py``
(``init_paged_cache`` + the jitted prefill/decode/peek/bind/copy
closures) and ``ops/attention.py`` (``paged_decode_attention``). This
module owns the HOST side — which physical page belongs to whom:

- :class:`PagePool` — a free list plus per-page refcounts over the
  ``n_pages`` physical pages of one engine's pool. Page 0 is the
  reserved SCRAP page (free table entries point at it; redirected
  writes land in it) and is never handed out. A page is writable by a
  slot only while its refcount is exactly 1 — a refcount above 1 means
  the page is shared (other slots and/or the prefix index hold it) and
  a writer must copy first (COW).
- :class:`PrefixIndex` — maps prompt-token BLOCKS (one block = one
  page) to the immutable pages that already hold their K/V. Two
  structures: a block-hash *chain* (vLLM-style: block ``i``'s key
  folds block ``i-1``'s key, so a chain hit is a shared *prefix*, not
  a coincidence of content) resolving any number of leading full
  pages, and a *full-prompt* digest table resolving an entire prompt —
  including a partial final page — to its page row, which is what lets
  an identical request skip prefill completely (the engine ``peek``s
  its first token off the cached K/V). Registered pages are retained
  (refcount +1) by the index so prefixes survive their original
  request; records are LRU-evicted when the engine needs pages back.

Thread-safety: both classes are engine-internal and only touched under
the engine's ``_gen_lock`` (admission/step boundaries); they do no
locking of their own.

Telemetry (docs/OBSERVABILITY.md): counters
``serving.generate.pages.{allocated,shared,cow_copies,freed}``, gauge
``serving.generate.pages.free``.
"""
from __future__ import annotations

import collections
import hashlib

from .. import telemetry, tracing

__all__ = ["PagePool", "PrefixIndex"]

#: physical page 0 — scrap target for redirected writes, never allocated
SCRAP_PAGE = 0


class PagePool:
    """Free list + refcounts over one engine's physical KV pages."""

    def __init__(self, n_pages: int):
        if int(n_pages) < 2:
            raise ValueError("PagePool needs >= 2 pages (page 0 is "
                             "the reserved scrap page)")
        self.n_pages = int(n_pages)
        # LIFO free list: recently-freed pages are re-used first (their
        # pool rows are the likeliest still resident in cache/HBM)
        self._free = list(range(self.n_pages - 1, 0, -1))
        self._refs = {}
        self._gauge()

    def _gauge(self):
        telemetry.gauge("serving.generate.pages.free", len(self._free))

    @property
    def free_count(self) -> int:
        return len(self._free)

    def refcount(self, pid: int) -> int:
        return self._refs.get(pid, 0)

    def alloc(self, n: int):
        """Allocate ``n`` pages (refcount 1 each) or None if the pool
        cannot cover them — the caller decides whether to evict cached
        prefixes and retry or to defer admission."""
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for pid in out:
            self._refs[pid] = 1
        telemetry.counter("serving.generate.pages.allocated", n)
        self._gauge()
        return out

    def retain(self, pid: int):
        """Add one reference to an allocated page (a new slot or the
        prefix index sharing it)."""
        if pid == SCRAP_PAGE:
            raise ValueError("scrap page 0 cannot be retained")
        if pid not in self._refs:
            raise ValueError(f"retain of unallocated page {pid}")
        self._refs[pid] += 1
        telemetry.counter("serving.generate.pages.shared")

    def release(self, pid: int) -> bool:
        """Drop one reference; returns True when the page was freed
        back to the pool."""
        n = self._refs.get(pid)
        if n is None:
            raise ValueError(f"release of unallocated page {pid}")
        if n > 1:
            self._refs[pid] = n - 1
            return False
        del self._refs[pid]
        self._free.append(pid)
        telemetry.counter("serving.generate.pages.freed")
        self._gauge()
        return True


class _Record:
    """One registered prompt: the chain entries its pages BACK (an
    entry resolving a block to a different record's physical page is
    not listed — this record is not keeping it alive), every page it
    retains (full blocks + partial tail), and its length."""

    __slots__ = ("keys", "pages", "length")

    def __init__(self, keys, pages, length):
        self.keys = keys
        self.pages = pages
        self.length = length


class PrefixIndex:
    """Block-hash chain + full-prompt digest over immutable KV pages."""

    def __init__(self, pool: PagePool, page_size: int,
                 max_records: int = 128):
        self._pool = pool
        self._ps = int(page_size)
        self.max_records = int(max_records)
        #: (parent_key, block_digest) -> [child_key, page_id, users]
        self._chain: dict = {}
        #: prompt digest -> _Record, in LRU order (oldest first)
        self._records: "collections.OrderedDict[bytes, _Record]" = \
            collections.OrderedDict()

    def __len__(self):
        return len(self._records)

    @staticmethod
    def _digest(parent: bytes, block_bytes: bytes) -> bytes:
        return hashlib.blake2b(parent + block_bytes,
                               digest_size=16).digest()

    def _blocks(self, prompt):
        """Chain keys of the prompt's FULL blocks: [(parent, digest)]
        with the running parent key folded in."""
        ps = self._ps
        key = b"root"
        out = []
        for i in range(len(prompt) // ps):
            d = self._digest(key, prompt[i * ps:(i + 1) * ps].tobytes())
            out.append((key, d))
            key = d
        return out

    def match(self, prompt):
        """Longest cached prefix of ``prompt``: returns ``(pages,
        n_tokens)`` — the physical pages already holding the K/V of the
        first ``n_tokens`` tokens (NOT yet retained: the caller retains
        them per consumer). A full-prompt digest hit resolves the
        entire prompt including a partial final page; otherwise the
        block chain resolves leading full pages."""
        full = hashlib.blake2b(prompt.tobytes(), digest_size=16).digest()
        rec = self._records.get(full)
        if rec is not None and rec.length == len(prompt):
            self._records.move_to_end(full)
            return list(rec.pages), rec.length
        pages = []
        for key in self._blocks(prompt):
            e = self._chain.get(key)
            if e is None:
                break
            pages.append(e[1])
        return pages, len(pages) * self._ps

    def register(self, prompt, page_row):
        """Publish a freshly-prefilled prompt's pages as shareable:
        retain every page covering the prompt (full blocks from
        ``page_row`` plus the partial tail page, which from here on is
        immutable — the owning slot COWs before its first decode
        write), create/refcount the chain entries, and record the
        full-prompt digest. Idempotent per prompt digest. Evicts the
        LRU record past ``max_records``."""
        full = hashlib.blake2b(prompt.tobytes(), digest_size=16).digest()
        if full in self._records:
            self._records.move_to_end(full)
            return False
        ps = self._ps
        n_pages = (len(prompt) + ps - 1) // ps
        pages = [int(page_row[i]) for i in range(n_pages)]
        used_keys = []
        for key, pid in zip(self._blocks(prompt), pages):
            e = self._chain.get(key)
            if e is None:
                self._chain[key] = [key[1], pid, 1]
                used_keys.append(key)
            elif e[1] == pid:
                e[2] += 1
                used_keys.append(key)
            # else: the chain already resolves this block to a DIFFERENT
            # physical page (two same-prefix prompts raced registration,
            # each prefilled privately). This record's copy stays
            # unpublished for the block — counting it as a user of the
            # other page's entry would keep that entry alive past its
            # backing record's eviction and let match() hand out a page
            # the pool has already freed.
        for pid in pages:
            self._pool.retain(pid)
        self._records[full] = _Record(used_keys, pages, len(prompt))
        while len(self._records) > self.max_records:
            self.evict_lru()
        return True

    def evict_lru(self) -> bool:
        """Drop the least-recently-used record: release its page
        references (pages free once no active slot holds them) and
        retire chain entries nobody else references. Returns False on
        an empty index."""
        if not self._records:
            return False
        _full, rec = self._records.popitem(last=False)
        tracing.flight.record("paging.prefix_evict",
                              pages=len(rec.pages), tokens=rec.length)
        for key in rec.keys:
            e = self._chain.get(key)
            if e is not None:
                e[2] -= 1
                if e[2] <= 0:
                    del self._chain[key]
        for pid in rec.pages:
            self._pool.release(pid)
        return True

    def release_all(self):
        """Drop every record (engine close)."""
        while self.evict_lru():
            pass

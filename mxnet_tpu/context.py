"""Device contexts.

Parity with the reference's Context (include/mxnet/base.h:92 and
python/mxnet/context.py:24-249) mapped onto JAX's device model:

- ``cpu()``   -> a JAX CPU device (host).
- ``tpu(i)``  -> the i-th JAX accelerator device.
- ``gpu(i)``  -> alias of ``tpu(i)``; kept so reference-style scripts
  (`ctx=mx.gpu(0)`) run unchanged on TPU. `num_gpus()` reports the
  accelerator count for the same reason.
- ``cpu_pinned`` / ``cpu_shared`` -> the CPU device. On TPU, host staging
  is managed by PJRT itself (dma-mapped transfer buffers), so pinned
  memory is not a distinct user-visible pool; the spellings are kept for
  API parity.

There is no global device-id namespace like CUDA's: devices are JAX
device objects. A Context is a thin named handle around one.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax


_BACKEND_PROBE_TIMEOUT_S = float(
    __import__("os").environ.get("MXTPU_BACKEND_TIMEOUT", "90"))
_backend_probe_cache: list = []  # [platform_or_None] once resolved
_backend_probe_lock = threading.Lock()
_backend_probe_thread: dict = {}  # {"t": Thread} while a probe runs


def _accelerator_platform():
    """Return the preferred accelerator platform name, or None (cpu only).

    Time-boxed: the axon TPU plugin's PJRT init can hang indefinitely
    when its tunnel is down, and ``jax.default_backend()`` blocks inside
    that init. The probe runs on a daemon thread with a
    ``MXTPU_BACKEND_TIMEOUT`` (default 90s) deadline; on timeout we warn
    and report CPU for this call — the thread keeps waiting, so a
    late-arriving backend is picked up by subsequent calls. Reference
    parity: context selection never blocks on an absent device
    (/root/reference/python/mxnet/context.py:24-249).

    Honesty note: the hung probe thread holds jax's global backend
    lock, so once this timeout fires, any subsequent jax operation in
    this process will still block until the tunnel recovers. The
    time-box converts a silent infinite hang into a diagnosed one —
    full immunity requires pinning MXTPU_PLATFORM=cpu before import,
    which skips the accelerator probe entirely.
    """
    if _backend_probe_cache:
        return _backend_probe_cache[0]

    # ONE probe thread process-wide: while init is hung, later calls
    # join the same in-flight thread (and pay at most one full
    # deadline each) instead of each leaking a fresh stuck thread.
    with _backend_probe_lock:
        t = _backend_probe_thread.get("t")
        if t is None:
            def probe():
                try:
                    backend = jax.default_backend()
                except Exception:  # pragma: no cover - no backend
                    backend = "cpu"
                _backend_probe_cache[:] = [
                    None if backend == "cpu" else backend]

            t = threading.Thread(target=probe, daemon=True,
                                 name="mxtpu-backend-probe")
            _backend_probe_thread["t"] = t
            t.start()
    t.join(_BACKEND_PROBE_TIMEOUT_S)
    if _backend_probe_cache:
        return _backend_probe_cache[0]
    import warnings
    warnings.warn(
        f"jax backend init did not finish within "
        f"{_BACKEND_PROBE_TIMEOUT_S:.0f}s (accelerator tunnel down?). "
        f"Reporting CPU, but jax operations in this process may still "
        f"block on the wedged backend init — restart with "
        f"MXTPU_PLATFORM=cpu to skip the accelerator probe entirely "
        f"(MXTPU_BACKEND_TIMEOUT changes this deadline).",
        RuntimeWarning, stacklevel=3)
    return None


class Context:
    """A device context. devtype: 'cpu', 'tpu' ('gpu' is accepted as an
    alias for 'tpu'), 'cpu_pinned', 'cpu_shared'."""

    _default_ctx = threading.local()

    devtype2str = {1: "cpu", 2: "tpu", 3: "cpu_pinned", 5: "cpu_shared"}
    devstr2type = {"cpu": 1, "tpu": 2, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5}

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in self.devstr2type:
            raise ValueError(f"unknown device type {device_type!r}")
        if device_type == "gpu":
            device_type = "tpu"
        self.device_typeid = self.devstr2type[device_type]
        self.device_id = device_id
        self._old_ctx: Optional["Context"] = None

    # -- identity ---------------------------------------------------------
    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- jax mapping ------------------------------------------------------
    @property
    def jax_device(self) -> jax.Device:
        """The concrete jax.Device this context names.

        Contexts name PROCESS-LOCAL devices (the reference's device ids
        are per-worker too) — under multi-process jax, jax.devices()
        lists the whole job's devices, most of them non-addressable."""
        def _local(platform):
            try:
                return jax.local_devices(backend=platform)
            except RuntimeError:
                # backend not initialized/present: fall back to
                # process-local devices of that platform
                return [d for d in jax.local_devices()
                        if d.platform == platform]

        if self.device_typeid == 2:
            plat = _accelerator_platform()
            if plat is None:
                # No accelerator attached (e.g. CPU test meshes): tpu(i)
                # degrades to the i-th host device so code is portable.
                devs = _local("cpu")
            else:
                devs = _local(plat)
        else:
            devs = _local("cpu")
        if self.device_id >= len(devs):
            raise ValueError(
                f"context {self} out of range: only {len(devs)} "
                f"device(s) of that type are visible"
            )
        return devs[self.device_id]

    # -- default-context management (thread-local, parity with reference) -
    @classmethod
    def _current(cls) -> "Context":
        ctx = getattr(cls._default_ctx, "value", None)
        if ctx is None:
            ctx = default_context()
            cls._default_ctx.value = ctx
        return ctx

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx
        return False

    def empty_cache(self):
        """Release cached device memory back to the allocator.

        The reference's GPU pooled storage manager exposes ReleaseAll
        (src/storage/storage.cc); on PJRT the backing allocator (BFC) is
        internal, so this clears JAX's live-executable caches instead.
        """
        jax.clear_caches()

    def memory_info(self):
        """(free_bytes, total_bytes) for this context's device (parity:
        mx.context.gpu_memory_info, python/mxnet/context.py:24-249;
        backed by PJRT memory stats).

        On backends without allocator stats (CPU PJRT), total falls
        back to host memory and free = total - live jax allocations.
        """
        dev = self.jax_device
        stats = None
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend without stats
            stats = None
        if stats:
            total = int(stats.get("bytes_limit",
                                  stats.get("bytes_reservable_limit", 0)))
            in_use = int(stats.get("bytes_in_use", 0))
            if total:
                return (total - in_use, total)
        # host fallback: total from /proc, in-use from live arrays
        try:
            with open("/proc/meminfo") as f:
                total = next(int(l.split()[1]) * 1024 for l in f
                             if l.startswith("MemTotal"))
        except (OSError, StopIteration):
            total = 0
        in_use = sum(b.nbytes for b in jax.live_arrays()
                     if b.device == dev)
        return (max(total - in_use, 0), total)


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id: int = 0) -> Context:
    return Context("cpu_pinned", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias of tpu() for source compatibility with reference scripts."""
    return Context("tpu", device_id)


def num_gpus() -> int:
    """Number of accelerator devices visible (parity: mx.context.num_gpus)."""
    plat = _accelerator_platform()
    if plat is None:
        return 0
    return len(jax.devices(plat))


def num_tpus() -> int:
    return num_gpus()


def default_context() -> Context:
    """tpu(0) when an accelerator is attached, else cpu(0)."""
    return tpu(0) if _accelerator_platform() is not None else cpu(0)


def current_context() -> Context:
    return Context._current()


def gpu_memory_info(device_id: int = 0):
    """(free, total) bytes on accelerator `device_id` (parity:
    mx.context.gpu_memory_info — 'gpu' means 'the accelerator')."""
    return tpu(device_id).memory_info()


def tpu_memory_info(device_id: int = 0):
    return tpu(device_id).memory_info()

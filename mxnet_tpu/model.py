"""Legacy model checkpoint helpers.

Parity target: ``python/mxnet/model.py`` (``save_checkpoint``
``model.py:189``, ``load_params`` ``model.py:221``, ``load_checkpoint``
``model.py:238``). Writes the reference's on-disk layout —
``prefix-symbol.json`` plus ``prefix-NNNN.params`` in the legacy binary
NDArray format with ``arg:``/``aux:`` key prefixes — so checkpoints
round-trip with reference-ecosystem tooling.
"""
from __future__ import annotations

from . import legacy_serialization as _legacy

__all__ = ["save_checkpoint", "load_params", "load_checkpoint",
           "BatchEndParam"]

from .callback import BatchEndParam  # noqa: E402,F401  (historic home)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save ``prefix-symbol.json`` + ``prefix-{epoch:04d}.params``.

    ``remove_amp_cast`` is accepted for signature parity; AMP casts in
    this framework live in the dispatch funnel, never in the saved
    graph, so there is nothing to strip.
    """
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    _legacy.save_legacy(param_name, save_dict)


def load_params(prefix, epoch):
    """Load ``prefix-{epoch:04d}.params`` → (arg_params, aux_params)."""
    loaded = _legacy.load_legacy(f"{prefix}-{epoch:04d}.params")
    if not isinstance(loaded, dict):
        raise ValueError("checkpoint params file has no names; "
                         "not a save_checkpoint artifact")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:  # tolerate unprefixed keys like the reference loader
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    """Load symbol + params saved by :func:`save_checkpoint`.

    Returns ``(symbol, arg_params, aux_params)``.
    """
    from . import symbol as sym
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params

"""Legacy model checkpoint helpers.

Parity target: ``python/mxnet/model.py`` (``save_checkpoint``
``model.py:189``, ``load_params`` ``model.py:221``, ``load_checkpoint``
``model.py:238``). Writes the reference's on-disk layout —
``prefix-symbol.json`` plus ``prefix-NNNN.params`` in the legacy binary
NDArray format with ``arg:``/``aux:`` key prefixes — so checkpoints
round-trip with reference-ecosystem tooling.
"""
from __future__ import annotations

from . import legacy_serialization as _legacy

__all__ = ["save_checkpoint", "load_params", "load_checkpoint",
           "BatchEndParam"]

from .callback import BatchEndParam  # noqa: E402,F401  (historic home)


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save ``prefix-symbol.json`` + ``prefix-{epoch:04d}.params``.

    ``remove_amp_cast`` is accepted for signature parity; AMP casts in
    this framework live in the dispatch funnel, never in the saved
    graph, so there is nothing to strip.
    """
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in (arg_params or {}).items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    _legacy.save_legacy(param_name, save_dict)


def load_params(prefix, epoch):
    """Load ``prefix-{epoch:04d}.params`` → (arg_params, aux_params).

    Accepts both the legacy binary written by save_checkpoint and the
    gluon ``save_parameters`` format written by HybridBlock.export."""
    from . import utils_io
    fname = f"{prefix}-{epoch:04d}.params"
    # utils_io.load sniffs the legacy magic and falls back to npz —
    # covers both save_checkpoint and gluon save_parameters artifacts
    loaded = utils_io.load(fname)
    if not isinstance(loaded, dict):
        raise ValueError("checkpoint params file has no names; "
                         "not a save_checkpoint artifact")
    arg_params, aux_params = {}, {}
    for k, v in loaded.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        elif "running_" in k or "moving_" in k:
            # gluon-format (unprefixed) aux states: normalization
            # running statistics are exactly the reference's aux set
            aux_params[k] = v
        else:  # tolerate unprefixed keys like the reference loader
            arg_params[k] = v
    return arg_params, aux_params


class ExportedSymbol:
    """Stand-in symbol for a HybridBlock.export artifact: the graph
    IR is a compiled StableHLO program (``-symbol.mxir``), not an op
    DAG, so it cannot be recomposed — but load_checkpoint callers can
    still inspect it and feed it to ``gluon.SymbolBlock.imports`` via
    ``json_path``."""

    def __init__(self, json_path, manifest):
        self.json_path = json_path
        self.manifest = manifest

    def tojson(self):
        import json as _json
        return _json.dumps(self.manifest)

    def save(self, fname):
        """Re-save manifest + copy the .mxir artifact next to the new
        prefix so save_checkpoint(load_checkpoint(...)) round-trips."""
        import json as _json
        import os as _os
        import shutil as _shutil
        with open(fname, "w") as f:
            _json.dump(self.manifest, f)
        art = self.manifest.get("artifact")
        if art:
            src = _os.path.join(_os.path.dirname(self.json_path), art)
            dst = _os.path.join(_os.path.dirname(_os.path.abspath(
                fname)), art)
            if _os.path.abspath(src) != dst and _os.path.exists(src):
                _shutil.copyfile(src, dst)

    def list_arguments(self):
        return list(self.manifest.get("param_names", []))

    def __repr__(self):
        return (f"ExportedSymbol(StableHLO artifact "
                f"{self.manifest.get('artifact')!r})")


def load_checkpoint(prefix, epoch):
    """Load symbol + params saved by :func:`save_checkpoint` OR by
    ``HybridBlock.export`` (whose -symbol.json is a StableHLO
    manifest; returned as :class:`ExportedSymbol`).

    Returns ``(symbol, arg_params, aux_params)``.
    """
    import json as _json

    from . import symbol as sym
    path = f"{prefix}-symbol.json"
    try:
        symbol = sym.load(path)
    except ValueError:
        with open(path) as f:
            d = _json.load(f)
        if "artifact" not in d:
            raise
        symbol = ExportedSymbol(path, d)
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params

"""mx.visualization — network inspection (parity:
python/mxnet/visualization.py print_summary/plot_network).

`print_summary` works on a Symbol (layer table with output shapes and
parameter counts); `plot_network` emits Graphviz DOT text — rendering
is the caller's concern (the environment carries no graphviz binding),
which matches how the reference returns a `graphviz.Digraph`.
"""
from __future__ import annotations

import json

import numpy as onp

__all__ = ["print_summary", "plot_network"]


def _sym_nodes(symbol):
    return symbol._nodes, {nid for nid, _ in symbol._outputs}


def print_summary(symbol, shape=None, line_length=120,
                  positions=(.44, .64, .74, 1.), data_names=("data",
                                                             "label")):
    """Print a per-node table for a Symbol (parity:
    visualization.print_summary). `shape`: dict arg_name -> shape used
    for shape inference (all arguments, since inference is whole-graph);
    `data_names` marks which arguments are inputs rather than
    parameters. Gluon Blocks should use `block.summary(x)`."""
    nodes, _ = _sym_nodes(symbol)
    shapes = {}
    if shape is not None:
        arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
        for name, s in zip(symbol.list_arguments(), arg_shapes):
            shapes[name] = s
        # per-node output shapes: re-infer each interior node's output
        # by treating it as a head (cheap: eval_shape, no FLOPs)
        try:
            from .symbol.symbol import Symbol as _Sym
            for nid, node in enumerate(nodes):
                if node.op == "null" or node.name in shapes:
                    continue
                sub = _Sym(nodes, [(nid, 0)])
                _, outs, _ = sub.infer_shape(**shape)
                if outs:
                    shapes[node.name] = outs[0]
        except Exception:  # noqa: BLE001 — summary stays best-effort
            pass

    positions = [int(line_length * p) for p in positions]
    headers = ["Layer (type)", "Output Shape", "Param #",
               "Previous Layer"]

    def row(fields):
        line = ""
        for i, f in enumerate(fields):
            line = line[:positions[i] - len(str(f)) - 1]
            line += str(f) + " " * max(
                positions[i] - len(line) - len(str(f)), 1)
        print(line[:line_length])

    print("=" * line_length)
    row(headers)
    print("=" * line_length)
    total = 0
    data_names = set(data_names)
    for node in nodes:
        if node.op == "null" and node.name not in data_names:
            sh = shapes.get(node.name, ())
            n_params = int(onp.prod(sh)) if sh else 0
        else:
            sh = shapes.get(node.name, "")
            n_params = 0
        total += n_params
        prev = ", ".join(nodes[i].name for i, _ in node.inputs)
        row([f"{node.name} ({node.op})", sh or "", n_params, prev])
    print("=" * line_length)
    print(f"Total params: {total}")
    print("=" * line_length)
    return total


def plot_network(symbol, title="plot", save_format="pdf",
                 shape=None, node_attrs=None, hide_weights=True):
    """Return Graphviz DOT text for a Symbol's DAG (parity:
    visualization.plot_network, which returns a graphviz.Digraph)."""
    nodes, out_ids = _sym_nodes(symbol)
    lines = [f'digraph "{title}" {{',
             '  node [shape=box, style=filled, fillcolor="#8dd3c7"];']
    skip = set()
    if hide_weights:
        for i, node in enumerate(nodes):
            if node.op == "null" and (
                    node.name.endswith(("weight", "bias", "gamma",
                                        "beta", "running_mean",
                                        "running_var"))):
                skip.add(i)
    for i, node in enumerate(nodes):
        if i in skip:
            continue
        color = "#fb8072" if node.op == "null" else (
            "#80b1d3" if i in out_ids else "#8dd3c7")
        label = node.name if node.op == "null" else \
            f"{node.op}\\n{node.name}"
        attrs = json.dumps(node.attrs) if node.attrs else ""
        tooltip = f', tooltip="{attrs}"' if attrs else ""
        lines.append(
            f'  n{i} [label="{label}", fillcolor="{color}"{tooltip}];')
    for i, node in enumerate(nodes):
        if i in skip:
            continue
        for src, _ in node.inputs:
            if src in skip:
                continue
            lines.append(f"  n{src} -> n{i};")
    lines.append("}")
    return "\n".join(lines)

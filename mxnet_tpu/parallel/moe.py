"""Expert-parallel mixture-of-experts FFN (the 'ep' mesh axis).

Beyond-reference capability (the reference has no MoE; SURVEY §2.3
reserves the axis): a top-1 gated, fixed-capacity MoE feed-forward
whose experts shard over the mesh axis ``ep``. Token routing is the
Mesh-TensorFlow dispatch/combine formulation — one-hot dispatch
tensors keep every shape static for XLA — and tokens physically move
to their expert's device through ``lax.all_to_all`` over ICI, the
TPU-native equivalent of the NCCL all-to-all an expert-parallel GPU
framework would issue.

Data flow per device (shard_map over ('dp', 'ep')):
    x_local (T, D)
      gate -> top-1 expert + position-in-expert (capacity C)
      dispatch (T, E, C) one-hot
      expert_in = einsum(dispatch, x)            (E, C, D)
      all_to_all over 'ep': (E, C, D) -> (E/ep, C*ep, D)
      expert FFN with the E/ep local experts
      all_to_all back: (E/ep, C*ep, H) -> (E, C, H)
      out = einsum(combine, expert_out)          (T, D)
Tokens overflowing an expert's capacity drop (standard top-1 MoE
behavior); the gate is differentiable through the combine weights.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def moe_ffn(x, gate_w, w_up, w_down, mesh, capacity_factor=1.5,
            dp_axis="dp", ep_axis="ep"):
    """Expert-parallel top-1 MoE FFN.

    x (B, T, D) sharded over dp; gate_w (D, E); w_up (E, D, H) and
    w_down (E, H, D) sharded over ep on the expert axis. Returns
    (B, T, D) with the same sharding as x.
    """
    E = gate_w.shape[-1]
    ep = mesh.shape[ep_axis]
    assert E % ep == 0, f"experts {E} must divide ep={ep}"

    def local(xb, gw, wu, wd):
        B, T, D = xb.shape
        tokens = xb.reshape(B * T, D)
        n_tok = tokens.shape[0]
        cap = max(1, int(capacity_factor * n_tok / E))

        logits = tokens @ gw                       # (N, E)
        probs = jax.nn.softmax(logits, axis=-1)
        expert = jnp.argmax(probs, axis=-1)        # (N,)
        gate = jnp.take_along_axis(
            probs, expert[:, None], axis=-1)[:, 0]  # (N,)

        onehot = jax.nn.one_hot(expert, E, dtype=tokens.dtype)  # (N,E)
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot       # (N,E)
        keep = (pos < cap) & (onehot > 0)
        pos_oh = jax.nn.one_hot(pos.astype(jnp.int32), cap,
                                dtype=tokens.dtype)             # (N,E,C)
        dispatch = pos_oh * keep[..., None].astype(tokens.dtype)
        combine = dispatch * gate[:, None, None]

        expert_in = jnp.einsum("nec,nd->ecd", dispatch, tokens)
        # tokens travel to their expert's device (ICI all-to-all)
        expert_in = lax.all_to_all(expert_in, ep_axis,
                                   split_axis=0, concat_axis=1,
                                   tiled=True)     # (E/ep, C*ep, D)
        h = jnp.einsum("ecd,edh->ech", expert_in, wu)
        h = jax.nn.relu(h)
        out = jnp.einsum("ech,ehd->ecd", h, wd)    # (E/ep, C*ep, D)
        out = lax.all_to_all(out, ep_axis,
                             split_axis=1, concat_axis=0,
                             tiled=True)           # (E, C, D)
        y = jnp.einsum("nec,ecd->nd", combine, out)
        return y.reshape(B, T, D)

    from .._shard_compat import shard_map
    fn = shard_map(
        local, mesh=mesh, check_rep=False,
        in_specs=(P(dp_axis, None, None), P(None, None),
                  P(ep_axis, None, None), P(ep_axis, None, None)),
        out_specs=P(dp_axis, None, None))
    return fn(x, gate_w, w_up, w_down)

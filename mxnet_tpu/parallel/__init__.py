"""parallel — device meshes, sharding, and collectives.

This is the TPU-native replacement for the reference's communication
stack (SURVEY.md §2.3): CommDevice/NCCL/ps-lite collapse into XLA
collectives over a named jax.sharding.Mesh. The mesh axes convention:

- 'dp' — data parallel (batch sharding; gradient psum rides ICI)
- 'tp' — tensor/model parallel (weight sharding)
- 'pp' — pipeline stages (lax.scan over stages / shard_map)
- 'sp' — sequence/context parallel (long-context; ring attention)
- 'ep' — expert parallel (MoE all-to-all)

`set_mesh`/`get_mesh` hold the process-global mesh (like the
reference's global kvstore). `shard`/`replicate` produce
NamedShardings; `shard_batch`/`shard_params` place NDArrays.
"""
from __future__ import annotations

import contextlib
from typing import Optional, Sequence

import numpy as onp
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..ndarray.ndarray import NDArray
from .. import engine

P = PartitionSpec

_global_mesh: Optional[Mesh] = None

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_PP = "pp"
AXIS_SP = "sp"
AXIS_EP = "ep"


def make_mesh(shape=None, axis_names=None, devices=None) -> Mesh:
    """Build a Mesh. Default: all local devices on a 1-D 'dp' axis."""
    devices = devices if devices is not None else jax.devices()
    if shape is None:
        shape = (len(devices),)
        axis_names = axis_names or (AXIS_DP,)
    axis_names = tuple(axis_names or
                       (AXIS_DP, AXIS_TP, AXIS_PP, AXIS_SP, AXIS_EP)[:len(shape)])
    arr = onp.asarray(devices).reshape(shape)
    return Mesh(arr, axis_names)


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _global_mesh


@contextlib.contextmanager
def mesh_scope(mesh: Mesh):
    global _global_mesh
    prev = _global_mesh
    _global_mesh = mesh
    try:
        yield mesh
    finally:
        _global_mesh = prev


def sharding(spec: PartitionSpec, mesh: Mesh = None) -> NamedSharding:
    mesh = mesh or _global_mesh
    if mesh is None:
        raise RuntimeError("no mesh set; call parallel.set_mesh first")
    return NamedSharding(mesh, spec)


def replicate(value: NDArray, mesh: Mesh = None) -> NDArray:
    """Replicate an array over the mesh (parity: kvstore broadcast)."""
    s = sharding(P(), mesh)
    value._install(jax.device_put(value._data, s))
    return value


def shard_batch(value: NDArray, axis=0, mesh: Mesh = None,
                axis_name=AXIS_DP) -> NDArray:
    """Shard the batch axis over the 'dp' mesh axis."""
    spec = [None] * value.ndim
    spec[axis] = axis_name
    s = sharding(P(*spec), mesh)
    value._install(jax.device_put(value._data, s))
    return value


def shard_params(params, rules=None, mesh: Mesh = None):
    """Place gluon Parameters onto the mesh.

    rules: list of (regex, PartitionSpec); first match wins; default
    replicated. Parameter.sharding records the spec for pjit wiring.
    """
    import re
    mesh = mesh or _global_mesh
    compiled = [(re.compile(pat), spec) for pat, spec in (rules or [])]
    for name, p in params.items():
        spec = P()
        for pat, s in compiled:
            if pat.search(name):
                spec = s
                break
        p.sharding = spec
        if p._data is not None:
            p._data._install(jax.device_put(p._data._data,
                                            NamedSharding(mesh, spec)))


def strip_axis(entry, axis_name):
    """One PartitionSpec entry with ``axis_name`` removed (None when
    nothing remains) — the reduced-away axis of a collective's output
    spec. Shared by ``allreduce`` and the kvstore
    ``reduce_scatter``/``all_gather`` pair so the spec semantics
    cannot drift between them."""
    if entry is None:
        return None
    if isinstance(entry, (tuple, list)):
        rem = tuple(a for a in entry if a != axis_name)
        return rem if rem else None
    return None if entry == axis_name else entry


def on_mesh(data, mesh: Mesh):
    """``(data, spec)`` with ``data`` guaranteed to live on ``mesh``
    (a value from elsewhere is replicated onto it first) — the
    imperative collectives' shared input convention."""
    sh = getattr(data, "sharding", None)
    if not (isinstance(sh, NamedSharding) and sh.mesh == mesh):
        data = jax.device_put(data, NamedSharding(mesh, P()))
        sh = data.sharding
    return data, sh.spec


def allreduce(value: NDArray, op="sum", mesh: Mesh = None,
              axis_name=AXIS_DP) -> NDArray:
    """Imperative cross-device reduction: a REAL psum/pmax/pmin over
    `axis_name` via shard_map (XLA AllReduce on ICI), not a layout
    change. Each mesh-axis participant contributes its local block;
    every participant receives the elementwise reduction. For an array
    sharded on `axis_name` the result's global shape is the block
    shape (shards are summed together); for a replicated array every
    device's copy counts once (sum = n * x).

    Under pjit/hybridize, reductions belong INSIDE the compiled
    program; this entry point is for the imperative KVStore/debug path
    (parity: kvstore push+pull semantics).
    """
    mesh = mesh or _global_mesh
    if mesh is None or mesh.shape.get(axis_name, 1) == 1:
        return value
    from .._shard_compat import shard_map

    reducer = {"sum": jax.lax.psum, "max": jax.lax.pmax,
               "min": jax.lax.pmin}[op]
    data, spec = on_mesh(value._data, mesh)
    # output stays sharded over the OTHER axes; only `axis_name` is
    # reduced away
    out_spec = P(*[strip_axis(e, axis_name) for e in spec])
    fn = shard_map(lambda x: reducer(x, axis_name), mesh=mesh,
                   in_specs=spec, out_specs=out_spec)
    out = fn(data)
    value._install(out)
    return value


def num_partitions(mesh: Mesh = None, axis_name=AXIS_DP) -> int:
    mesh = mesh or _global_mesh
    if mesh is None:
        return 1
    return mesh.shape.get(axis_name, 1)


def local_device_count() -> int:
    return jax.local_device_count()


def process_index() -> int:
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Multi-host init (parity: the reference's DMLC_* env bootstrap →
    jax.distributed; DCN collectives then ride the same mesh).

    Falls back to the MXNET_TPU_COORDINATOR/NUM_PROCS/PROC_ID env vars
    set by tools/launch.py local mode (the fake-pod test launcher)."""
    import os
    if coordinator_address is None:
        coordinator_address = os.environ.get("MXNET_TPU_COORDINATOR")
        if coordinator_address is not None:
            nproc = os.environ.get("MXNET_TPU_NUM_PROCS")
            pid = os.environ.get("MXNET_TPU_PROC_ID")
            if pid is None:
                # mpi launcher: MPI assigns ranks; honor its env
                pid = os.environ.get("OMPI_COMM_WORLD_RANK",
                                     os.environ.get("PMI_RANK"))
            if nproc is None or pid is None:
                raise RuntimeError(
                    "MXNET_TPU_COORDINATOR is set but MXNET_TPU_NUM_PROCS"
                    "/MXNET_TPU_PROC_ID are not; all three are required "
                    "(tools/launch.py sets them together)")
            num_processes = int(nproc)
            process_id = int(pid)
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(coordinator_address=coordinator_address,
                      num_processes=num_processes, process_id=process_id)
    jax.distributed.initialize(**kwargs)


from .train_step import TrainStep  # noqa: E402,F401
from .moe import moe_ffn  # noqa: E402,F401  (expert parallel, 'ep')
from .pipeline import pipeline_apply  # noqa: E402,F401  ('pp')
from .checkpoint import save_sharded, load_sharded  # noqa: E402,F401
from . import partition  # noqa: E402,F401  (SPMD logical-axis layer)
from .partition import Partitioner  # noqa: E402,F401

"""DEPRECATED shim — sharded checkpointing moved to
:mod:`mxnet_tpu.checkpoint`.

``save_sharded``/``load_sharded`` keep their signatures and on-restore
placement semantics (mesh + ``(regex, PartitionSpec)`` rules), but now
delegate to the checkpoint subsystem: shards + a manifest with an
atomic ``COMMITTED`` marker, optimizer counters folded INTO the
manifest (the old ``opt_counters.json`` sidecar — which silently
dropped lr-scheduler state — is gone), and integrity verification on
read. Directories written by the old Orbax wrapper (no
``manifest.json``) are still restorable: ``load_sharded`` falls back
to an Orbax/TensorStore read, including the legacy sidecar.

Scope note: the new format is single-controller — ``save_sharded``
host-gathers each array and writes from process 0 only (non-zero
processes no-op), whereas the old Orbax path coordinated per-process
shard writes. Multi-host jobs with non-addressable arrays should
checkpoint through a future multi-host backend of
``mxnet_tpu.checkpoint`` (the ``fs=`` seam), not this shim.

New code should use
``mxnet_tpu.checkpoint.CheckpointManager`` /
``save_training_state``/``restore_training_state`` directly — those
add async save, retention, corrupt fallback, and full training-state
capture (docs/CHECKPOINT.md).
"""
from __future__ import annotations

import os
import warnings

__all__ = ["save_sharded", "load_sharded"]


def _warn_deprecated(name):
    warnings.warn(
        f"parallel.{name} is deprecated; use mxnet_tpu.checkpoint "
        "(CheckpointManager / save_training_state / "
        "restore_training_state) instead", DeprecationWarning,
        stacklevel=3)


def save_sharded(directory, net, step=None, force=True):
    """Write a committed checkpoint of ``net`` (and optionally the
    optimizer states + counters of a ``TrainStep``) under
    ``directory``. Deprecated: delegates to
    ``mxnet_tpu.checkpoint.write_checkpoint``."""
    import jax
    from .. import checkpoint as ckpt
    _warn_deprecated("save_sharded")
    directory = os.path.abspath(directory)
    if jax.process_count() > 1 and jax.process_index() != 0:
        # single-controller write: only process 0 touches the files
        # (every process writing the same shard names would race); the
        # old per-process Orbax coordination is out of the shim's scope
        return directory
    tree, meta = ckpt.capture_training_state(
        net=net, train_step=step, include_rng=False)
    ckpt.write_checkpoint(directory, ckpt.snapshot_tree(tree),
                          metadata=meta)
    return directory


def _legacy_opt_counters(directory, step):
    """Read the old wrapper's ``opt_counters.json`` sidecar (kept only
    for restoring checkpoints written before the manifest subsumed
    it)."""
    import json
    opt = getattr(step, "optimizer", None)
    path = os.path.join(directory, "opt_counters.json")
    if opt is None or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            payload = json.load(f)
        num_update = payload["num_update"]
        begin = payload["begin_num_update"]
        index_counts = {
            int(k): v for k, v in payload["index_update_count"].items()}
    except (ValueError, OSError, KeyError, TypeError, AttributeError) as e:
        warnings.warn(f"ignoring unreadable opt_counters.json: {e!r}")
        return
    opt.num_update = num_update
    opt.begin_num_update = begin
    opt._index_update_count = index_counts


def _load_legacy_orbax(directory, net, step, target_sharding):
    """Restore a checkpoint directory written by the pre-subsystem
    Orbax wrapper (identified by its missing ``manifest.json``):
    rebuild the abstract tree from the live net/TrainStep the way the
    old module did, let Orbax/TensorStore read each device's shards,
    install, and apply the legacy ``opt_counters.json`` sidecar."""
    import jax

    try:
        import orbax.checkpoint as ocp
    except ImportError as e:
        from .. import checkpoint as ckpt
        raise ckpt.CheckpointError(
            f"{directory} has no manifest.json (a legacy Orbax "
            f"checkpoint?) and orbax is not importable: {e!r}") from e

    params = net.collect_params()
    live = {name: p.data()._data for name, p in params.items()}

    def _abstract(name, x):
        return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                    sharding=target_sharding(name, x))

    abstract = {"params": {n: _abstract(n, x) for n, x in live.items()}}
    if step is not None and \
            getattr(step, "_opt_states", None) is not None:
        abstract["opt_states"] = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            tuple(step._opt_states))

    restored = ocp.StandardCheckpointer().restore(directory, abstract)
    for name, val in restored["params"].items():
        params[name].data()._install(val)
    if step is not None:
        if "opt_states" in restored:
            step._opt_states = list(restored["opt_states"])
        _legacy_opt_counters(directory, step)
    return net


def load_sharded(directory, net, step=None, mesh=None, rules=None):
    """Restore a ``save_sharded`` checkpoint into ``net`` (and
    ``step``). ``mesh`` + ``rules`` (list of ``(regex,
    PartitionSpec)``) choose the target placement; defaults to each
    array's current sharding, so a train-resume on the same mesh needs
    no arguments. Deprecated: delegates to
    ``mxnet_tpu.checkpoint.read_checkpoint``; directories written by
    the old Orbax wrapper (no ``manifest.json``) fall back to an
    Orbax/TensorStore read."""
    import re
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .. import checkpoint as ckpt

    _warn_deprecated("load_sharded")
    directory = os.path.abspath(directory)
    compiled = [(re.compile(pat), spec) for pat, spec in (rules or [])]

    def _target_sharding(name, live):
        if mesh is not None:
            for pat, spec in compiled:
                if pat.search(name):
                    return NamedSharding(mesh, spec)
            sh = getattr(live, "sharding", None)
            if isinstance(sh, NamedSharding) and \
                    sh.mesh.shape == mesh.shape:
                return sh
            return NamedSharding(mesh, P())
        sh = getattr(live, "sharding", None)
        return sh if isinstance(sh, NamedSharding) else None

    if not os.path.exists(os.path.join(directory, ckpt.MANIFEST_FILE)):
        # a directory written by the pre-subsystem Orbax wrapper has
        # no manifest — restore it the way the old code did
        return _load_legacy_orbax(directory, net, step,
                                  _target_sharding)
    tree, meta = ckpt.read_checkpoint(directory)

    params = net.collect_params()
    for name, arr in tree.get("params", {}).items():
        if name not in params:
            warnings.warn(f"checkpoint parameter {name!r} not in net; "
                          "skipped")
            continue
        p = params[name]
        if p._data is None:
            # deferred shape inference, no forward yet: the checkpoint
            # shape finishes the init (set_data), then placement below
            from ..numpy import array as _host_nd
            p.set_data(_host_nd(arr))
        live = p.data()._data
        new = jnp.asarray(arr, live.dtype)
        target = _target_sharding(name, live)
        if target is not None:
            new = jax.device_put(new, target)
        p.data()._install(new)

    if step is not None:
        saved = tree.get("opt_states")
        if saved is not None:
            live = getattr(step, "_opt_states", None)

            def _place(x, l):
                if not isinstance(x, (jnp.ndarray,)) and \
                        not hasattr(x, "shape"):
                    return x
                out = jnp.asarray(x)
                sh = getattr(l, "sharding", None)
                if isinstance(sh, NamedSharding):
                    out = jax.device_put(out, sh)
                return out

            restored = []
            for i, s in enumerate(saved):
                l = live[i] if live is not None and i < len(live) \
                    else None
                try:
                    restored.append(jax.tree_util.tree_map(_place, s, l)
                                    if l is not None else
                                    jax.tree_util.tree_map(
                                        lambda x: _place(x, None), s))
                except ValueError:
                    restored.append(jax.tree_util.tree_map(
                        lambda x: _place(x, None), s))
            step._opt_states = restored
        opt_meta = meta.get("optimizer")
        if opt_meta is not None:
            from ..checkpoint.state import _apply_optimizer_meta
            _apply_optimizer_meta(step.optimizer, opt_meta)
        else:
            _legacy_opt_counters(directory, step)
    return net

"""Sharded (multi-host) checkpointing for mesh-parallel training.

The reference's checkpoint story is single-host files
(`save_checkpoint`/`load_checkpoint`, gluon save/load_parameters —
SURVEY.md §5 "Checkpoint / resume"); its distributed recovery is
"checkpoint + relaunch". This module keeps that recovery model but
makes the checkpoint itself mesh-native: every process writes only its
own parameter shards through Orbax/TensorStore, and restore places
shards directly onto the target `jax.sharding.Mesh` — no gather to
host 0, no full-model memory spike, works across pod slices.

API shape follows gluon (`save_parameters`/`load_parameters`), scaled
up:

    from mxnet_tpu import parallel
    parallel.save_sharded(dir, net, step=trainstep)   # params+opt
    parallel.load_sharded(dir, net, step=trainstep, mesh=mesh)
"""
from __future__ import annotations

import os

import jax

__all__ = ["save_sharded", "load_sharded"]


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _tree_for(net, step):
    """params (+ optimizer states when a TrainStep is given) as a
    plain pytree of raw jax arrays keyed by parameter name."""
    params = {name: p.data()._data
              for name, p in net.collect_params().items()}
    tree = {"params": params}
    if step is not None and getattr(step, "_opt_states", None) is not None:
        tree["opt_states"] = jax.tree.map(
            lambda x: x, tuple(step._opt_states))
    return tree


_COUNTERS_FILE = "opt_counters.json"


def _save_opt_counters(directory, step):
    """Persist the optimizer's step counters next to the shards.

    Adam-family bias correction and lr_scheduler position both key off
    `num_update`; restoring warm moments with t reset to ~1 inflates
    the effective lr right after resume. Tiny host-side state, so a
    JSON sidecar (process 0 only) rather than a sharded array.
    """
    import json
    opt = getattr(step, "optimizer", None)
    if opt is None or jax.process_index() != 0:
        return
    payload = {
        "num_update": int(opt.num_update),
        "begin_num_update": int(opt.begin_num_update),
        "index_update_count": {
            str(k): int(v) for k, v in opt._index_update_count.items()},
    }
    path = os.path.join(directory, _COUNTERS_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)  # atomic: never leave a truncated sidecar


def _load_opt_counters(directory, step):
    import json
    opt = getattr(step, "optimizer", None)
    path = os.path.join(directory, _COUNTERS_FILE)
    if opt is None or not os.path.exists(path):
        return
    try:
        with open(path) as f:
            payload = json.load(f)
        num_update = payload["num_update"]
        begin = payload["begin_num_update"]
        index_counts = {
            int(k): v for k, v in payload["index_update_count"].items()}
    except (ValueError, OSError, KeyError, TypeError, AttributeError) as e:
        # counters are an optional extra — a damaged or foreign-format
        # sidecar must not fail the restore of intact orbax shards
        import warnings
        warnings.warn(f"ignoring unreadable {_COUNTERS_FILE}: {e!r}")
        return
    opt.num_update = num_update
    opt.begin_num_update = begin
    opt._index_update_count = index_counts


def save_sharded(directory, net, step=None, force=True):
    """Write a sharded checkpoint of `net` (and optionally the
    optimizer states of a `TrainStep`) under `directory`.

    Each process persists only the shards it owns; safe to call from
    every process of a multi-host job (Orbax coordinates the commit).
    """
    directory = os.path.abspath(directory)
    ckptr = _checkpointer()
    ckptr.save(directory, _tree_for(net, step), force=force)
    ckptr.wait_until_finished()
    if step is not None:
        _save_opt_counters(directory, step)
    return directory


def load_sharded(directory, net, step=None, mesh=None, rules=None):
    """Restore a `save_sharded` checkpoint into `net` (and `step`).

    `mesh` + `rules` (list of ``(regex, PartitionSpec)``) choose the
    target placement; defaults to each array's current sharding, so a
    train-resume on the same mesh needs no arguments. Restoring onto a
    *different* mesh shape is supported: TensorStore reads exactly the
    shards each device needs.
    """
    import re
    from jax.sharding import NamedSharding, PartitionSpec as P

    directory = os.path.abspath(directory)
    compiled = [(re.compile(pat), spec) for pat, spec in (rules or [])]

    def _target_sharding(name, arr):
        if mesh is not None:
            for pat, spec in compiled:
                if pat.search(name):
                    return NamedSharding(mesh, spec)
            if getattr(arr, "sharding", None) is not None and \
                    isinstance(arr.sharding, NamedSharding) and \
                    arr.sharding.mesh.shape == mesh.shape:
                return arr.sharding
            return NamedSharding(mesh, P())
        return getattr(arr, "sharding", None)

    live = _tree_for(net, step)

    def _abstract(path_name, x):
        sh = _target_sharding(path_name, x)
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)

    abstract = {"params": {
        name: _abstract(name, x) for name, x in live["params"].items()}}
    if "opt_states" in live:
        abstract["opt_states"] = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=getattr(x, "sharding", None))
            if hasattr(x, "shape") else x,
            live["opt_states"])

    ckptr = _checkpointer()
    restored = ckptr.restore(directory, abstract)

    params = net.collect_params()
    for name, val in restored["params"].items():
        params[name].data()._install(val)
    if step is not None and "opt_states" in restored:
        step._opt_states = list(restored["opt_states"])
    if step is not None:
        _load_opt_counters(directory, step)
    return net

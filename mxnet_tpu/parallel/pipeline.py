"""Pipeline parallelism over the 'pp' mesh axis (GPipe-style).

Beyond-reference capability (the reference is data-parallel only;
SURVEY §2.3 reserves the axis): a stack of S identical-signature
stages runs with stage s's weights resident on pp-device s, and
microbatches stream through the pipeline with activations moving
stage-to-stage via ``lax.ppermute`` over ICI — the TPU-native
equivalent of P2P sends in a GPU pipeline engine.

Schedule: the classic S + M - 1 tick loop. On tick t, device s
computes its stage for the microbatch that entered at tick t - s
(garbage warm-up/drain ticks are masked out). Everything is
lax.fori_loop + static shapes, so the whole pipeline — including its
backward pass, since ppermute is differentiable — is ONE XLA program
and composes with jax.grad / the fused TrainStep.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def pipeline_apply(stage_fn, stage_params, x, mesh, n_microbatch,
                   pp_axis="pp", dp_axis=None):
    """Run `stage_fn` S times in pipeline over the 'pp' axis.

    stage_fn(params_slice, h) -> h'   (same shape in and out)
    stage_params: pytree whose leaves have leading axis S (one slice
        per stage), sharded over pp.
    x (B, ...) — the batch; split into `n_microbatch` equal
        microbatches along axis 0. Pass dp_axis to also shard the
        batch over a data-parallel mesh axis (dp × pp hybrid).
    Returns stage_fn^S(x) — the composition of all S stages.
    """
    S = mesh.shape[pp_axis]
    B = x.shape[0]
    dp = mesh.shape[dp_axis] if dp_axis else 1
    assert (B // dp) % n_microbatch == 0, (B, dp, n_microbatch)
    mb = B // dp // n_microbatch
    bad = [l.shape[0] for l in jax.tree.leaves(stage_params)
           if l.shape[0] != S]
    if bad:
        raise ValueError(
            f"stage_params leading axis must equal the pp mesh size "
            f"{S}; got {bad} — a mismatched stack would silently drop "
            "stages (each device keeps only its first slice)")

    def local(params_local, x_all):
        # params_local: leaves (1, ...) — this device's stage slice
        p_here = jax.tree.map(lambda a: a[0], params_local)
        idx = lax.axis_index(pp_axis)
        micro = x_all.reshape((n_microbatch, mb) + x_all.shape[1:])

        right = [(i, (i + 1) % S) for i in range(S)]

        def tick(t, carry):
            buf, outs = carry
            # stage 0 ingests microbatch t (while it exists)
            feed_t = jnp.clip(t, 0, n_microbatch - 1)
            inject = micro[feed_t]
            h_in = jnp.where(idx == 0, inject, buf)
            h_out = stage_fn(p_here, h_in)
            # the last stage's result for microbatch t-(S-1) lands now
            done_t = t - (S - 1)
            store = jnp.clip(done_t, 0, n_microbatch - 1)
            valid = jnp.logical_and(done_t >= 0,
                                    done_t <= n_microbatch - 1)
            last = idx == S - 1
            outs = lax.cond(
                valid & last,
                lambda o: o.at[store].set(h_out),
                lambda o: o, outs)
            # activations advance one stage over ICI
            buf = lax.ppermute(h_out, pp_axis, right)
            return buf, outs

        buf0 = jnp.zeros_like(micro[0])
        outs0 = jnp.zeros_like(micro)
        _, outs = lax.fori_loop(0, S + n_microbatch - 1, tick,
                                (buf0, outs0))
        # only the last pp device holds real outputs; replicate them
        # across 'pp' with a masked psum (differentiable)
        mask = (idx == S - 1).astype(outs.dtype)
        outs = lax.psum(outs * mask, pp_axis)
        return outs.reshape((-1,) + x_all.shape[1:])

    from .._shard_compat import shard_map
    p_specs = jax.tree.map(lambda _: P(pp_axis), stage_params)
    x_spec = P(dp_axis) if dp_axis else P()
    fn = shard_map(local, mesh=mesh, check_rep=False,
                   in_specs=(p_specs, x_spec),
                   out_specs=x_spec)
    return fn(stage_params, x)

"""Fused training step — forward + backward + optimizer in ONE XLA program.

The reference overlaps backward with gradient pushes through engine
dependencies (SURVEY.md §3.4: priority = -key so push(layer N) overlaps
backward(layer N-1)). On TPU the equivalent — and stronger — guarantee
comes from compiling the whole training step into a single XLA program:
XLA's latency-hiding scheduler overlaps the gradient all-reduce over the
'dp' mesh axis with remaining backward compute, and buffer donation
makes the parameter/optimizer-state update fully in-place.

This is the throughput path used by bench.py and the multi-chip
dryrun; the imperative Trainer path (gluon/trainer.py) remains for
step-by-step parity with the reference's
`autograd.record → backward → trainer.step` flow.
"""
from __future__ import annotations

import contextlib as _contextlib
import re

_nullcontext = _contextlib.nullcontext

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import numpy as onp

from .. import autograd
from .. import bucketing as _bucketing
from .. import compile_cache
from .. import engine
from .. import telemetry
from ..ndarray.ndarray import NDArray
from ..random_state import next_key, trace_rng
from ..gluon import _deferred
from ..gluon.block import _flatten_arrays, _rebuild, CachedOp
from . import get_mesh, AXIS_DP


def _as_tuple(x):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


_LAYER_RE = re.compile(r"layers\.(\d+)\.")


def _layer_groups(diff_names, frozen_names):
    """Group parameter positions by transformer-layer index for the
    gather/compute overlap chain.

    Returns an ordered list of groups, each a list of ``('d'|'f',
    position)`` entries indexing into the step's diff/frozen data
    tuples. Params whose name carries a ``layers.<N>.`` prefix land in
    group ``N``; everything else (embeddings, final LN, output head)
    lands in a leading group — their gathers are small and issuing
    them up front keeps the per-layer chain clean. Returns None when
    fewer than two groups exist (nothing to stagger)."""
    groups = {}
    for tag, names in (("d", diff_names), ("f", frozen_names)):
        for pos, name in enumerate(names):
            m = _LAYER_RE.search(name)
            key = int(m.group(1)) if m else -1
            groups.setdefault(key, []).append((tag, pos))
    ordered = [groups[k] for k in sorted(groups)]
    return ordered if len(ordered) >= 2 else None


class TrainStep:
    """Compile `loss_fn(net(data), label)` + grad + optimizer update into
    one jitted, donation-friendly XLA program, optionally sharded over a
    `jax.sharding.Mesh`.

    Parameters
    ----------
    net : HybridBlock (or any Block whose forward is trace-safe)
    loss_fn : callable(out, label) -> NDArray loss (gluon.loss.* works)
    optimizer : mxnet_tpu.optimizer.Optimizer instance or name string
    mesh : optional Mesh; defaults to parallel.get_mesh()
    batch_axis : mesh axis name the leading batch dim is sharded over
    param_rules : list of (regex, PartitionSpec) giving tensor-parallel
        placements by parameter name; unmatched params are replicated.
        With ``layout=`` set this is the ESCAPE HATCH: a matching rule
        overrides the layout's logical-axis resolution for that
        parameter.
    layout : str or parallel.partition.Partitioner, optional
        Named SPMD layout over the mesh — ``"dp"`` (pure data
        parallel, the default behavior), ``"tp"`` (tensor parallel by
        logical axes), ``"fsdp"`` (params + optimizer state sharded
        over the batch axis; XLA all-gathers each layer's weights
        inside the step — overlapped with compute by the
        latency-hiding scheduler — and reduces gradients straight
        into the owning shard: reduce-scatter semantics, ``(N-1)/N``
        of the allreduce bytes per direction). Parameters resolve
        through their ``logical_axes`` metadata (gpt.py annotates the
        GPT family; un-annotated params stay replicated — use
        ``param_rules`` for those). Requires a mesh.
    bucketing : BucketingPolicy, optional
        Pad odd batches (the last partial batch of every epoch) up to
        a bucket so they reuse an existing compiled entry instead of
        forcing a rebuild; padded rows are masked out of the loss.
        None (default) inherits the process-global
        `mxnet_tpu.bucketing` policy; ``False`` opts this step out of
        even the global policy (exact unpadded behavior).
    compute_dtype : str, optional
        ``"bfloat16"`` runs forward/backward math in bf16 while the
        MASTER weights, gradients, and optimizer state stay fp32:
        params and floating inputs are cast to bf16 INSIDE the
        differentiated loss (so the cast's transpose returns fp32
        cotangents to the masters), the loss is reported in fp32, and
        LN/softmax accumulate fp32 via the ``ops.nn.accum_dtype``
        policy. None / ``"float32"`` (default) is bitwise-identical
        to today's fp32 path. Composes with every layout: the casts
        sit downstream of the gather pins.
    overlap_gather : bool
        On gather-compute layouts (``tp_fsdp``), chain
        ``lax.optimization_barrier`` across per-layer parameter groups
        so layer ``k``'s compute cannot be scheduled before layer
        ``k+1``'s all-gather has issued — double-buffering the ZeRO
        weight gathers against the matmuls instead of trusting the
        latency-hiding scheduler to find the overlap. Numerically the
        barrier is identity (losses stay bitwise equal to dp);
        structurally it is visible as ``opt-barrier`` ops in
        ``compiled_hlo``. Default True; ignored on layouts that do
        not gather in-step.
    """

    def __init__(self, net, loss_fn, optimizer, optimizer_params=None,
                 mesh=None, batch_axis=AXIS_DP, param_rules=None,
                 layout=None, donate=True, bucketing=None,
                 compute_dtype=None, overlap_gather=True):
        from .. import optimizer as opt_mod
        self.net = net
        self.loss_fn = loss_fn
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self.optimizer = optimizer
        self._explicit_mesh = mesh
        self.batch_axis = batch_axis
        self.param_rules = [(re.compile(pat), spec)
                            for pat, spec in (param_rules or [])]
        self._layout = layout
        self._partitioner = None
        #: analytic gradient-sync wire bytes per step for the resolved
        #: layout (kvstore.collective_wire_bytes model); set at build
        self.comm_bytes_per_step = 0
        self.donate = donate
        if compute_dtype is None or str(compute_dtype) == "float32":
            self.compute_dtype = "float32"
            self._cast_dt = None
        elif str(compute_dtype) == "bfloat16":
            self.compute_dtype = "bfloat16"
            self._cast_dt = jnp.bfloat16
        else:
            raise ValueError(
                f"TrainStep compute_dtype must be None, 'float32' or "
                f"'bfloat16', got {compute_dtype!r}")
        self.overlap_gather = bool(overlap_gather)
        # False is a distinct value: "no bucketing, not even the
        # global policy" (as_policy would collapse it to None = inherit)
        self.bucketing = False if bucketing is False \
            else _bucketing.as_policy(bucketing)
        self._entries = {}
        self._opt_states = None  # shared across signatures: a shape
        self._mp_flags = None    # change (last odd batch) must NOT
        #                          reset Adam/momentum accumulators

    # -- helpers -------------------------------------------------------
    @property
    def mesh(self):
        return self._explicit_mesh or get_mesh()

    @property
    def partitioner(self):
        """The resolved layout Partitioner (built lazily: the mesh may
        be the process-global one set after construction). None when
        no ``layout=`` was requested."""
        if self._layout is None:
            return None
        if self._partitioner is None:
            from . import partition as _partition
            if isinstance(self._layout, _partition.Partitioner):
                self._partitioner = self._layout
            else:
                if self.mesh is None:
                    raise RuntimeError(
                        f"TrainStep(layout={self._layout!r}) needs a "
                        f"mesh: pass mesh= or parallel.set_mesh first")
                self._partitioner = _partition.Partitioner(
                    self._layout, mesh=self.mesh,
                    batch_axis=self.batch_axis)
        return self._partitioner

    def _spec_for(self, name):
        for pat, spec in self.param_rules:
            if pat.search(name):
                return spec
        return P()

    # -- build ---------------------------------------------------------
    def _build(self, data_leaves, data_spec, label_leaves, label_spec):
        net, loss_fn = self.net, self.loss_fn
        params_dict = net.collect_params()
        if any(p._data is None for p in params_dict.values()):
            CachedOp(net)._abstract_init(list(data_leaves),
                                         data_spec)
            params_dict = net.collect_params()

        part = self.partitioner
        if part is not None:
            # resolve every parameter's logical axes to a spec over
            # the mesh (p.sharding), param_rules overriding per name —
            # the pjit wiring below consumes p.sharding as before
            part.annotate(params_dict, override_rules=self.param_rules)

        names = list(params_dict.keys())
        params = [params_dict[n] for n in names]
        diff_idx = [i for i, p in enumerate(params)
                    if p.grad_req != "null"]
        frozen_idx = [i for i, p in enumerate(params)
                      if p.grad_req == "null"]
        diff_nds = [params[i].data() for i in diff_idx]
        frozen_nds = [params[i].data() for i in frozen_idx]
        all_nds = diff_nds + frozen_nds

        opt = self.optimizer
        if self._opt_states is None:
            self._opt_states = [
                opt.create_state_multi_precision(k, diff_nds[k])
                for k in range(len(diff_idx))]
            self._mp_flags = [opt._use_mp(w) for w in diff_nds]
        states = self._opt_states
        mp_flags = self._mp_flags

        out_box = {}
        # capture only the contexts — closing over the leaf NDArrays
        # would pin the build-time batch buffers in HBM for the
        # lifetime of this cached entry
        data_ctxs = [l.ctx for l in data_leaves]
        label_ctxs = [l.ctx for l in label_leaves]

        def forward_loss(key, diff_datas, frozen_datas,
                         input_datas, label_datas, n_valid):
            saved = [nd._data for nd in all_nds]
            scope = _deferred.trace_scope()
            rec = autograd._RecordingScope(False, True)
            with scope, rec, trace_rng(key):
                for nd, d in zip(diff_nds, diff_datas):
                    nd._data = d
                for nd, d in zip(frozen_nds, frozen_datas):
                    nd._data = d
                try:
                    in_nds = [NDArray(d, ctx=c)
                              for d, c in zip(input_datas, data_ctxs)]
                    lab_nds = [NDArray(d, ctx=c)
                               for d, c in zip(label_datas, label_ctxs)]
                    args = _rebuild(data_spec, in_nds)
                    out = net.forward(*args)
                    labels = _rebuild(label_spec, lab_nds)
                    if loss_fn is not None:
                        loss = loss_fn(out, *labels)
                    else:
                        loss = out
                    if loss.ndim > 0:
                        # mean over the VALID rows only: bucketing pads
                        # a partial batch up to a stable signature and
                        # passes n_valid < batch; the where (not a
                        # multiply) keeps a non-finite padded-row loss
                        # from poisoning the sum via 0*inf. With
                        # n_valid == batch this is exactly loss.mean().
                        ld = loss._data
                        mask = jnp.arange(ld.shape[0]) < n_valid
                        mask = mask.reshape((ld.shape[0],)
                                            + (1,) * (ld.ndim - 1))
                        per_row = ld.size // ld.shape[0]
                        denom = jnp.maximum(n_valid, 1) * per_row
                        loss = NDArray(
                            jnp.where(mask, ld, 0).sum() / denom,
                            ctx=loss.ctx)
                    else:
                        # loss_fn reduced to a scalar itself: there is
                        # no per-row axis left to mask — dispatch warns
                        # if this entry ever receives a padded batch
                        out_box["scalar_loss"] = True
                finally:
                    for nd, s in zip(all_nds, saved):
                        nd._data = s
            out_box["aux_targets"] = [nd for nd, _ in scope.state_updates]
            # pin aux (BN running stats) to the target's STORED dtype:
            # a bf16 compute_dtype forward must not narrow the fp32
            # stat buffers (that would change the entry's avals and
            # drift the accumulators)
            aux = tuple(jnp.asarray(t, nd._data.dtype)
                        for nd, t in scope.state_updates)
            return loss._data, aux

        opt_cls = type(opt)
        n_diff = len(diff_nds)

        # gather-compute layouts (tp_fsdp): weights AND gradients are
        # pinned replicated INSIDE the step — the forward all-gathers
        # each weight before use (ZeRO-3) and the backward reduces the
        # gradient fully before the sharded optimizer update slices
        # it. Without the gradient pin, the 2-D output shardings
        # back-propagate tp splits into the backward contractions and
        # the partial-sum order drifts the updates a ulp per step away
        # from dp (losses stop being bitwise-comparable). The sharded
        # placements remain the STORAGE layout via in/out_shardings.
        gather_rep = None
        if part is not None and part.gather_compute \
                and self.mesh is not None:
            gather_rep = NamedSharding(self.mesh, P())

        # gather/compute overlap: per-layer barrier chain staggering
        # layer k+1's weight all-gather against layer k's compute
        overlap_groups = None
        if gather_rep is not None and self.overlap_gather:
            overlap_groups = _layer_groups(
                [names[i] for i in diff_idx],
                [names[i] for i in frozen_idx])

        cast_dt = self._cast_dt

        def _cast_leaves(datas):
            return tuple(d.astype(cast_dt)
                         if jnp.issubdtype(d.dtype, jnp.floating)
                         else d for d in datas)

        def step_fn(key, diff_datas, frozen_datas, opt_states, hypers,
                    input_datas, label_datas, n_valid):
            if gather_rep is not None:
                diff_datas = tuple(
                    jax.lax.with_sharding_constraint(d, gather_rep)
                    for d in diff_datas)
                frozen_datas = tuple(
                    jax.lax.with_sharding_constraint(d, gather_rep)
                    for d in frozen_datas)
            if overlap_groups is not None:
                # chain pairwise: bundling layer k's (post-gather)
                # weights with layer k+1's inside one barrier makes
                # every consumer of layer k's weights depend on layer
                # k+1's gather — XLA must issue gather k+1 no later
                # than compute k (the prefetch). Identity on values.
                dd, fz = list(diff_datas), list(frozen_datas)
                for prev, nxt in zip(overlap_groups,
                                     overlap_groups[1:]):
                    pick = prev + nxt
                    vals = tuple(dd[p] if t == "d" else fz[p]
                                 for t, p in pick)
                    vals = jax.lax.optimization_barrier(vals)
                    for (t, p), v in zip(pick, vals):
                        if t == "d":
                            dd[p] = v
                        else:
                            fz[p] = v
                # re-pin: the SPMD partitioner propagates shardings
                # THROUGH the barrier and would otherwise re-shard its
                # outputs back to the storage layout, silently undoing
                # the gather-compute pin (and its bitwise-vs-dp
                # guarantee)
                diff_datas = tuple(
                    jax.lax.with_sharding_constraint(d, gather_rep)
                    for d in dd)
                frozen_datas = tuple(
                    jax.lax.with_sharding_constraint(d, gather_rep)
                    for d in fz)

            def loss_f(dd):
                fz, ins = frozen_datas, input_datas
                if cast_dt is not None:
                    # cast INSIDE the differentiated function: the
                    # astype's transpose casts cotangents back, so
                    # grads land fp32 on the fp32 masters
                    dd = _cast_leaves(dd)
                    fz = _cast_leaves(fz)
                    ins = _cast_leaves(ins)
                loss, aux = forward_loss(key, dd, fz, ins,
                                         label_datas, n_valid)
                if cast_dt is not None:
                    loss = loss.astype(jnp.float32)
                return loss, aux

            (loss, aux), grads = jax.value_and_grad(
                loss_f, has_aux=True)(diff_datas)
            if gather_rep is not None:
                grads = tuple(
                    jax.lax.with_sharding_constraint(g, gather_rep)
                    for g in grads)
            new_ws, new_ss = [], []
            for k in range(n_diff):
                w, g, s, h = (diff_datas[k], grads[k], opt_states[k],
                              hypers[k])
                if mp_flags[k]:
                    nw, ns = opt_cls._step_mp(w, g, s, h)
                else:
                    nw, ns = opt_cls._step(
                        w, jnp.asarray(g, w.dtype), s, h)
                new_ws.append(nw)
                new_ss.append(ns)
            return tuple(new_ws), tuple(new_ss), loss, aux

        mesh = self.mesh
        jit_kwargs = {}
        if self.donate:
            jit_kwargs["donate_argnums"] = (1, 3)
        if mesh is not None:
            rep = NamedSharding(mesh, P())
            diff_sh = []
            for k, i in enumerate(diff_idx):
                spec = getattr(params[i], "sharding", None)
                if spec is None:
                    spec = self._spec_for(names[i])
                diff_sh.append(NamedSharding(mesh, spec))
            frozen_sh = []
            for i in frozen_idx:
                spec = getattr(params[i], "sharding", None)
                if spec is None:
                    spec = self._spec_for(names[i])
                frozen_sh.append(NamedSharding(mesh, spec))
            state_sh = []
            for k in range(n_diff):
                w = diff_nds[k]
                wsh = diff_sh[k]
                wshape = tuple(w.shape)

                def leaf_sh(s, _wsh=wsh, _wshape=wshape):
                    shp = getattr(s, "shape", None)
                    return _wsh if shp is not None and tuple(shp) == _wshape \
                        else rep
                state_sh.append(jax.tree.map(leaf_sh, states[k]))

            # the PRIMARY input's leading dim defines the batch; other
            # leaves (e.g. RNN states shaped (layers, batch, hidden))
            # may carry it elsewhere — shard the axis that matches, or
            # replicate when none/ambiguous (dim0 wins ties: the
            # conventional batch-major layout)
            bsz = next((l.shape[0] for l in data_leaves if l.ndim),
                       None)

            def batch_sh(leaf):
                spec = [None] * leaf.ndim
                if leaf.ndim > 0 and bsz is not None:
                    if leaf.shape[0] == bsz:
                        spec[0] = self.batch_axis
                    else:
                        hits = [i for i, d in enumerate(leaf.shape)
                                if d == bsz]
                        if len(hits) == 1:
                            spec[hits[0]] = self.batch_axis
                return NamedSharding(mesh, P(*spec))

            data_sh = tuple(batch_sh(l) for l in data_leaves)
            label_sh = tuple(batch_sh(l) for l in label_leaves)
            hyper_sh = [jax.tree.map(lambda _: rep, opt._hyper(k))
                        for k in range(n_diff)]
            jit_kwargs["in_shardings"] = (
                rep, tuple(diff_sh), tuple(frozen_sh),
                tuple(state_sh), hyper_sh, data_sh, label_sh, rep)
            # aux (BN stats) shardings: let XLA decide (None subtree)
            jit_kwargs["out_shardings"] = (tuple(diff_sh),
                                           tuple(state_sh), rep, None)
            # place current param values onto the mesh
            for k in range(n_diff):
                d = diff_nds[k]._data
                if not _placed_as(d, diff_sh[k]):
                    diff_nds[k]._data = jax.device_put(d, diff_sh[k])
                states[k] = jax.tree.map(
                    lambda s, sh: jax.device_put(s, sh)
                    if hasattr(s, "shape") else s,
                    states[k], state_sh[k])
            for j in range(len(frozen_nds)):
                d = frozen_nds[j]._data
                if not _placed_as(d, frozen_sh[j]):
                    frozen_nds[j]._data = jax.device_put(d, frozen_sh[j])
            # layout accounting: the analytic grad-sync wire bytes of
            # the resolved layout (the bench A/B's comm metric) and the
            # MEASURED per-device param+optimizer footprint (the "fits
            # one device's share of HBM" gate walks real shards)
            from . import partition as _partition
            spec_map = {names[i]: diff_sh[k].spec
                        for k, i in enumerate(diff_idx)}
            self.comm_bytes_per_step = _partition.grad_sync_bytes(
                spec_map, {names[i]: params[i] for i in diff_idx},
                mesh, self.batch_axis)
            telemetry.gauge("parallel.train_step.comm_bytes_per_step",
                            self.comm_bytes_per_step)
            telemetry.gauge(
                "parallel.partition.bytes_per_device",
                _partition.per_device_bytes(
                    [nd._data for nd in diff_nds]
                    + [nd._data for nd in frozen_nds] + list(states)))
        else:
            data_sh = label_sh = None

        entry = {
            "data_sh": data_sh,
            "label_sh": label_sh,
            "jit": jax.jit(step_fn, **jit_kwargs),
            "step_fn": step_fn,
            "jit_kwargs": jit_kwargs,
            "params": params,
            "diff_idx": diff_idx,
            "diff_nds": diff_nds,
            "frozen_nds": frozen_nds,
            "out_box": out_box,
            "data_spec": data_spec,
            "label_spec": label_spec,
        }
        return entry

    # -- bulk (scan) path ----------------------------------------------
    def _build_chain(self, entry):
        """jit a lax.scan of step_fn over a leading steps axis.

        TPU-native equivalent of the reference engine's bulk mode
        (`MXNET_EXEC_BULK_EXEC_*`, BulkAppend/BulkFlush in
        src/engine/threaded_engine.h:507): instead of fusing engine
        pushes, N whole training steps compile into ONE XLA program —
        zero per-step host dispatch. BN running stats thread through
        the scan carry; Adam-style bias-correction counters advance
        per scanned step. LR schedules are evaluated at launch and
        held constant across the chain (document-level divergence:
        schedules step at chain granularity).
        """
        step_fn = entry["step_fn"]
        frozen_nds = entry["frozen_nds"]
        out_box = entry["out_box"]
        # aux target positions are resolved AT TRACE TIME inside the
        # scan body: out_box["aux_targets"] is only populated when
        # step_fn is first traced, which for a fresh entry happens
        # during this very chain trace
        aux_pos_box = {}

        def _aux_positions():
            if "pos" not in aux_pos_box:
                frozen_ids = [id(nd) for nd in frozen_nds]
                aux_pos_box["pos"] = [
                    frozen_ids.index(id(nd))
                    if id(nd) in frozen_ids else -1
                    for nd in out_box.get("aux_targets", [])]
            return aux_pos_box["pos"]

        def chain_fn(key, diff, frozen, states, hypers, datas, labels,
                     n_valids):
            n = datas[0].shape[0]

            def body(carry, xs):
                key, diff, frozen, states, t_off = carry
                ks = jax.random.split(key)
                key, sub = ks[0], ks[1]
                d, l, nv = xs
                hy = [{**h, "t": h["t"] + t_off} for h in hypers]
                new_ws, new_ss, loss, aux = step_fn(
                    sub, diff, frozen, states, hy, d, l, nv)
                frozen2 = list(frozen)
                for pos, a in zip(_aux_positions(), aux):
                    if pos >= 0:
                        frozen2[pos] = a
                return ((key, tuple(new_ws), tuple(frozen2),
                         tuple(new_ss), t_off + 1), (loss, aux))

            (key, diff, frozen, states, _), (losses, auxs) = \
                jax.lax.scan(body, (key, diff, frozen, states,
                                    jnp.int32(0)),
                             (datas, labels, n_valids))
            last_aux = jax.tree.map(lambda a: a[n - 1], auxs)
            return diff, frozen, states, losses, last_aux

        kw = {}
        chain_data_sh = chain_label_sh = None
        base = entry["jit_kwargs"]
        if self.donate:
            kw["donate_argnums"] = (1, 2, 3)
        if "in_shardings" in base:
            (rep, diff_sh, frozen_sh, state_sh, hyper_sh,
             data_sh, label_sh, _nv_sh) = base["in_shardings"]
            mesh = self.mesh

            def lift(sh):
                # same placement with a replicated leading steps axis
                return NamedSharding(mesh, P(None, *sh.spec))

            chain_data_sh = tuple(lift(s) for s in data_sh)
            chain_label_sh = tuple(lift(s) for s in label_sh)
            kw["in_shardings"] = (
                rep, diff_sh, frozen_sh, state_sh, hyper_sh,
                chain_data_sh, chain_label_sh, rep)
            kw["out_shardings"] = (diff_sh, frozen_sh, state_sh,
                                   rep, None)
        return {"jit": jax.jit(chain_fn, **kw),
                "aux_positions": _aux_positions,
                "data_sh": chain_data_sh,
                "label_sh": chain_label_sh,
                "dispatched": False}

    # -- bucketing / signatures ----------------------------------------
    def _effective_policy(self):
        if self.bucketing is False:
            return None
        return self.bucketing if self.bucketing is not None \
            else _bucketing.get_policy()

    @staticmethod
    def _sig(data_leaves, label_leaves, data_spec, label_spec):
        return (tuple((l.shape, str(l.dtype)) for l in data_leaves),
                tuple((l.shape, str(l.dtype)) for l in label_leaves),
                repr(data_spec), repr(label_spec))

    def _apply_bucketing(self, data_leaves, label_leaves, pad):
        """Resolve the pad count for one batch: an explicit ``pad``
        argument wins, then pad marks left by the data pipeline, then
        the active bucketing policy (which pads the leaves here).
        Returns (data_leaves, label_leaves, pad)."""
        if pad is not None:
            return list(data_leaves), list(label_leaves), int(pad)
        pad = max([_bucketing.get_pad(l)
                   for l in list(data_leaves) + list(label_leaves)]
                  or [0])
        if pad:
            return list(data_leaves), list(label_leaves), pad
        policy = self._effective_policy()
        bsz = next((l.shape[0] for l in data_leaves if l.ndim), None)
        if policy is not None and bsz is not None:
            target = policy.bucket(bsz)
            if target > bsz:
                telemetry.counter("parallel.train_step.bucket_pad")
                data_leaves, pad = _bucketing.pad_leaves(
                    data_leaves, target, bsz)
                label_leaves, _ = _bucketing.pad_leaves(
                    label_leaves, target, bsz)
                return data_leaves, label_leaves, pad
        return list(data_leaves), list(label_leaves), 0

    def _get_entry(self, data_leaves, data_spec, label_leaves,
                   label_spec):
        sig = self._sig(data_leaves, label_leaves, data_spec, label_spec)
        entry = self._entries.get(sig)
        if entry is None:
            telemetry.counter("parallel.train_step.build")
            t0 = telemetry.clock()
            entry = self._build(data_leaves, data_spec, label_leaves,
                                label_spec)
            telemetry.duration_since("parallel.train_step.build", t0)
            self._entries[sig] = entry
        return sig, entry

    def _check_maskable(self, entry, pad):
        """A padded batch whose loss_fn already reduced to a scalar
        cannot be masked — the padded rows WILL contribute. Surface
        that loudly instead of silently breaking the bit-identical
        guarantee."""
        if pad and entry["out_box"].get("scalar_loss") \
                and not getattr(self, "_warned_scalar_loss", False):
            import warnings
            self._warned_scalar_loss = True
            warnings.warn(
                "TrainStep received a padded batch but loss_fn returns "
                "a scalar (already reduced over the batch): padded rows "
                "cannot be masked out of the loss and WILL affect "
                "training. Return a per-sample loss (gluon.loss.* "
                "default) to make padding exact, or disable bucketing "
                "for this step (bucketing=False).")

    def run_chain(self, data, label, pad=None):
        """Run `data.shape[0]` chained training steps in one compiled
        XLA program (bulk mode). `data`/`label` carry a leading steps
        axis: ``(n_steps, batch, ...)``. ``pad`` (int or length-
        ``n_steps`` sequence) marks trailing padded rows per step;
        their loss contribution is masked out. Returns the per-step
        losses as an NDArray of shape ``(n_steps,)``."""
        data_t, label_t = _as_tuple(data), _as_tuple(label)
        data_leaves, data_spec = _flatten_arrays(data_t)
        label_leaves, label_spec = _flatten_arrays(label_t)
        n_steps = data_leaves[0].shape[0]

        # per-batch entry (strip the steps axis for the signature)
        one_data = [l[0] for l in data_leaves]
        one_label = [l[0] for l in label_leaves]
        sig, entry = self._get_entry(one_data, data_spec, one_label,
                                     label_spec)
        chain_key = ("chain", sig, n_steps)
        chain = self._entries.get(chain_key)
        if chain is None:
            # chain_build times the (cheap) trace-graph construction;
            # the first dispatch below carries the XLA compile and is
            # recorded separately as chain_compile — same split as
            # __call__'s build vs compile (a warm chain re-keyed by
            # n_steps must not book its whole run as compile time)
            telemetry.counter("parallel.train_step.chain_build")
            t0 = telemetry.clock()
            chain = self._build_chain(entry)
            telemetry.duration_since("parallel.train_step.chain_build",
                                     t0)
            self._entries[chain_key] = chain

        opt = self.optimizer
        n_diff = len(entry["diff_nds"])
        # count the first chained step BEFORE reading hypers (Adam's
        # bias correction needs t>=1), then the remaining n-1; the
        # scan body advances t by its step offset
        opt._update_count(list(range(n_diff)))
        hypers = [opt._hyper(k) for k in range(n_diff)]
        for _ in range(n_steps - 1):
            opt._update_count(list(range(n_diff)))

        bsz = next((l.shape[1] for l in data_leaves if l.ndim > 1),
                   None) or 1
        if pad is None:
            pads = onp.zeros((n_steps,), onp.int32)
        else:
            pads = onp.broadcast_to(
                onp.asarray(pad, onp.int32), (n_steps,))
        n_valids = (bsz - pads).astype(onp.int32)

        data_datas = [l._data for l in data_leaves]
        label_datas = [l._data for l in label_leaves]
        if chain["data_sh"] is not None:
            data_datas = [d if _placed_as(d, sh)
                          else jax.device_put(d, sh) for d, sh in
                          zip(data_datas, chain["data_sh"])]
            label_datas = [d if _placed_as(d, sh)
                           else jax.device_put(d, sh) for d, sh in
                           zip(label_datas, chain["label_sh"])]

        first_dispatch = not chain["dispatched"]
        t0 = telemetry.clock()
        new_ws, new_fr, new_ss, losses, last_aux = chain["jit"](
            next_key(),
            tuple(nd._data for nd in entry["diff_nds"]),
            tuple(nd._data for nd in entry["frozen_nds"]),
            tuple(self._opt_states), hypers,
            tuple(data_datas), tuple(label_datas), n_valids)
        chain["dispatched"] = True
        telemetry.duration_since(
            "parallel.train_step.chain_compile" if first_dispatch else
            "parallel.train_step.run_chain", t0)
        telemetry.counter("parallel.train_step.chained_steps", n_steps)
        if self.comm_bytes_per_step and telemetry.enabled():
            telemetry.counter("parallel.train_step.comm_bytes",
                              self.comm_bytes_per_step * n_steps)
        self._check_maskable(entry, int(pads.max()) if len(pads) else 0)

        for nd, nw in zip(entry["diff_nds"], new_ws):
            nd._data = nw
        for nd, nf in zip(entry["frozen_nds"], new_fr):
            nd._data = nf
        self._opt_states = list(new_ss)
        targets = entry["out_box"].get("aux_targets", [])
        aux_positions = chain["aux_positions"]
        with autograd.pause():
            for nd, pos, new in zip(targets, aux_positions(), last_aux):
                if pos < 0:  # not threaded through frozen: install last
                    nd._install(new)
        engine.sample_memory()
        return NDArray(engine.track(losses))

    # -- call ----------------------------------------------------------
    def __call__(self, data, label, pad=None):
        """Run one training step; returns the (scalar NDArray) loss.

        ``pad`` marks the trailing rows of the batch as padding (their
        loss contribution is masked out — see bucketing.py). When None,
        pad marks left on the arrays by the data pipeline apply, and
        an active bucketing policy pads odd batches here so they reuse
        an existing compiled entry."""
        data_leaves, data_spec = _flatten_arrays(_as_tuple(data))
        label_leaves, label_spec = _flatten_arrays(_as_tuple(label))
        data_leaves, label_leaves, pad = self._apply_bucketing(
            data_leaves, label_leaves, pad)
        _, entry = self._get_entry(data_leaves, data_spec,
                                   label_leaves, label_spec)
        opt = self.optimizer
        n_diff = len(entry["diff_nds"])
        opt._update_count(list(range(n_diff)))
        hypers = [opt._hyper(k) for k in range(n_diff)]

        data_datas = [l._data for l in data_leaves]
        label_datas = [l._data for l in label_leaves]
        if entry["data_sh"] is not None:
            # skip leaves a DeviceFeed already placed on the entry's
            # shardings — the H2D happened off the dispatch path
            data_datas = [d if _placed_as(d, sh)
                          else jax.device_put(d, sh) for d, sh in
                          zip(data_datas, entry["data_sh"])]
            label_datas = [d if _placed_as(d, sh)
                           else jax.device_put(d, sh) for d, sh in
                           zip(label_datas, entry["label_sh"])]

        bsz = next((l.shape[0] for l in data_leaves if l.ndim), 1)
        n_valid = onp.int32(bsz - pad)
        diff_datas = tuple(nd._data for nd in entry["diff_nds"])
        args = (next_key(), diff_datas,
                tuple(nd._data for nd in entry["frozen_nds"]),
                tuple(self._opt_states), hypers,
                tuple(data_datas), tuple(label_datas), n_valid)
        # dispatch is async and entry["jit"] is lazily compiled: its
        # FIRST dispatch (even when the entry was built by an earlier
        # run_chain) pays trace + XLA compile — unless warmup() AOT-
        # compiled the entry, in which case dispatch goes through the
        # precompiled executable; steady-state 'run' measures enqueue
        # latency (the host-side cost the reference's engine-push
        # timing captured)
        first_dispatch = not entry.get("jit_dispatched")
        t0 = telemetry.clock()
        out = None
        if entry.get("aot") is not None:
            try:
                out = entry["aot"](*args)
            except (TypeError, ValueError):
                # aval mismatch vs. the warmed signature (e.g. weak
                # types): fall back to the lazy jit path for good.
                # That jit has never dispatched (warmup marked the
                # entry dispatched for the AOT path), so the fallback
                # pays a real trace+compile — label it as one
                telemetry.counter("parallel.train_step.aot_fallback")
                entry["aot"] = None
                first_dispatch = True
        if out is None:
            with compile_cache.measure() if first_dispatch \
                    else _nullcontext():
                out = entry["jit"](*args)
        new_ws, new_ss, loss, aux = out
        entry["jit_dispatched"] = True
        telemetry.duration_since(
            "parallel.train_step.compile" if first_dispatch else
            "parallel.train_step.run", t0)
        if self.comm_bytes_per_step and telemetry.enabled():
            telemetry.counter("parallel.train_step.comm_bytes",
                              self.comm_bytes_per_step)
        self._check_maskable(entry, pad)

        for nd, nw in zip(entry["diff_nds"], new_ws):
            nd._data = nw
        self._opt_states = list(new_ss)
        targets = entry["out_box"].get("aux_targets", [])
        with autograd.pause():
            for nd, new in zip(targets, aux):
                nd._install(new)
        engine.sample_memory()
        return NDArray(engine.track(loss))

    # -- introspection -------------------------------------------------
    def compiled_hlo(self, data, label, optimized=True):
        """Compiled HLO text of the entry serving this batch signature
        — the bench's structural-evidence hook: ``bench.py --shard``
        feeds it to ``partition.hlo_collectives`` to show the fsdp
        program really contains the per-layer all-gathers (and the dp
        program contains none). Build the entry (run one step) first;
        this lowers/compiles a fresh executable for inspection, so
        call it OUTSIDE any timed window.

        ``optimized=False`` returns the LOWERED (pre-optimization)
        StableHLO instead — the hook for asserting program STRUCTURE
        the backend is allowed to fold, e.g. the ``overlap_gather``
        chain's ``optimization_barrier`` ops (the CPU backend erases
        ``opt-barrier`` late in its pipeline; TPU keeps it)."""
        data_leaves, data_spec = _flatten_arrays(_as_tuple(data))
        label_leaves, label_spec = _flatten_arrays(_as_tuple(label))
        data_leaves, label_leaves, _pad = self._apply_bucketing(
            data_leaves, label_leaves, None)
        _, entry = self._get_entry(data_leaves, data_spec,
                                   label_leaves, label_spec)
        opt = self.optimizer
        n_diff = len(entry["diff_nds"])
        hypers = [opt._hyper(k) for k in range(n_diff)]
        abstract = [jax.ShapeDtypeStruct(l.shape, l.dtype)
                    for l in data_leaves]
        labstract = [jax.ShapeDtypeStruct(l.shape, l.dtype)
                     for l in label_leaves]
        bsz = next((l.shape[0] for l in data_leaves if l.ndim), 1)
        lowered = entry["jit"].lower(
            next_key(),
            tuple(nd._data for nd in entry["diff_nds"]),
            tuple(nd._data for nd in entry["frozen_nds"]),
            tuple(self._opt_states), hypers,
            tuple(abstract), tuple(labstract), onp.int32(bsz))
        if not optimized:
            return lowered.as_text()
        return lowered.compile().as_text()

    # -- AOT warmup ----------------------------------------------------
    def warmup(self, shapes, dtype="float32", label_dtype="int32"):
        """AOT-compile training-step entries ahead of the first step.

        ``shapes`` is a list of ``(data_shapes, label_shapes)``
        signatures; each side is one shape tuple or a tuple/list of
        them, and a ``(shape, dtype)`` pair overrides the default
        dtype per leaf::

            step.warmup([((64, 16), (64,))])               # one entry
            step.warmup([((b, 16), (b,)) for b in (32, 64)])

        Each signature builds its entry (if missing) and compiles it
        via ``jit.lower(...).compile()`` — moving trace + XLA compile
        off the first training step. With ``MXTPU_COMPILE_CACHE_DIR``
        set the compile replays from the persistent cache, so a
        restarted process warms up at disk-read speed. Telemetry:
        ``parallel.train_step.warmup`` (count),
        ``parallel.train_step.aot_compile`` (ms), plus the
        ``compile_cache.*`` hit/miss counters."""
        import jax.numpy as _jnp

        def _leafspecs(side, default_dtype):
            if isinstance(side, (list, tuple)) and side and \
                    isinstance(side[0], (list, tuple)):
                items = list(side)
                # distinguish the (shape, dtype) pair form from a list
                # of shapes: a pair has a str dtype second element
                if len(side) == 2 and isinstance(side[1], str):
                    items = [side]
            else:
                items = [side]
            out = []
            for it in items:
                if (isinstance(it, (list, tuple)) and len(it) == 2
                        and isinstance(it[1], str)):
                    out.append((tuple(it[0]), it[1]))
                else:
                    out.append((tuple(it), default_dtype))
            return out

        compiled = []
        for data_side, label_side in shapes:
            data_leaves = [NDArray(_jnp.zeros(s, dt)) for s, dt in
                           _leafspecs(data_side, dtype)]
            label_leaves = [NDArray(_jnp.zeros(s, dt)) for s, dt in
                            _leafspecs(label_side, label_dtype)]
            # bucket the template exactly like dispatch will, so
            # warming the real odd-tail shape warms the entry dispatch
            # actually uses (not a never-hit unpadded signature)
            data_leaves, label_leaves, _ = self._apply_bucketing(
                data_leaves, label_leaves, None)
            _, dspec = _flatten_arrays(tuple(data_leaves))
            _, lspec = _flatten_arrays(tuple(label_leaves))
            sig, entry = self._get_entry(data_leaves, dspec,
                                         label_leaves, lspec)
            telemetry.counter("parallel.train_step.warmup")
            if entry.get("aot") is not None:
                compiled.append(sig)
                continue
            opt = self.optimizer
            n_diff = len(entry["diff_nds"])
            # hypers carry the CURRENT counters; their avals (strong
            # numpy scalars) are what matters for the compiled
            # signature, not the values
            hypers = [opt._hyper(k) for k in range(n_diff)]
            abstract = [jax.ShapeDtypeStruct(l.shape, l.dtype)
                        for l in data_leaves]
            labstract = [jax.ShapeDtypeStruct(l.shape, l.dtype)
                         for l in label_leaves]
            bsz = next((l.shape[0] for l in data_leaves if l.ndim), 1)
            t0 = telemetry.clock()
            lowered = entry["jit"].lower(
                next_key(),
                tuple(nd._data for nd in entry["diff_nds"]),
                tuple(nd._data for nd in entry["frozen_nds"]),
                tuple(self._opt_states), hypers,
                tuple(abstract), tuple(labstract), onp.int32(bsz))
            with compile_cache.measure():
                entry["aot"] = lowered.compile()
            telemetry.duration_since("parallel.train_step.aot_compile",
                                     t0)
            # first *training* dispatch is now a plain enqueue
            entry["jit_dispatched"] = True
            compiled.append(sig)
        return compiled

    # -- async feed support --------------------------------------------
    def prepare_batch(self, data, label, pad=None):
        """Pad (bucketing) + device-place one batch ahead of dispatch.

        Called by `io.DeviceFeed` from its worker thread: applies the
        same bucketing/pad resolution as ``__call__``, then
        ``device_put``s each leaf onto the matching compiled entry's
        ``data_sh``/``label_sh`` shardings so the dispatch path skips
        the H2D transfer. Batches whose entry is not built yet come
        back host-resident (the first step's build handles them).
        Returns ``(data, label)`` with the input nesting preserved."""
        data_t, label_t = _as_tuple(data), _as_tuple(label)
        data_leaves, data_spec = _flatten_arrays(data_t)
        label_leaves, label_spec = _flatten_arrays(label_t)
        data_leaves, label_leaves, pad = self._apply_bucketing(
            data_leaves, label_leaves, pad)
        sig = self._sig(data_leaves, label_leaves, data_spec,
                        label_spec)
        entry = self._entries.get(sig)
        if entry is not None and entry["data_sh"] is not None:
            def place(leaves, shs):
                out = []
                for l, sh in zip(leaves, shs):
                    if _placed_as(l._data, sh):
                        out.append(l)
                    else:
                        nd = NDArray(jax.device_put(l._data, sh),
                                     ctx=l.ctx)
                        out.append(nd)
                return out

            data_leaves = place(data_leaves, entry["data_sh"])
            label_leaves = place(label_leaves, entry["label_sh"])
        else:
            # no mesh shardings (single device) — still move any
            # host-resident leaf onto the default device off the
            # dispatch path; leaves already backed by a jax.Array were
            # placed when they were created
            def to_device(leaves):
                return [l if isinstance(l._data, jax.Array)
                        else NDArray(jax.device_put(l._data), ctx=l.ctx)
                        for l in leaves]

            data_leaves = to_device(data_leaves)
            label_leaves = to_device(label_leaves)
        if pad:
            for l in data_leaves + label_leaves:
                _bucketing.mark_pad(l, pad)
        new_data = _rebuild(data_spec, data_leaves)
        new_label = _rebuild(label_spec, label_leaves)
        if not isinstance(data, (list, tuple)):
            new_data = new_data[0]
        if not isinstance(label, (list, tuple)):
            new_label = new_label[0]
        return new_data, new_label


def _placed_as(data, sh):
    try:
        return isinstance(data, jax.Array) and data.sharding == sh
    except Exception:
        return False

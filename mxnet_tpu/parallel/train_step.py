"""Fused training step — forward + backward + optimizer in ONE XLA program.

The reference overlaps backward with gradient pushes through engine
dependencies (SURVEY.md §3.4: priority = -key so push(layer N) overlaps
backward(layer N-1)). On TPU the equivalent — and stronger — guarantee
comes from compiling the whole training step into a single XLA program:
XLA's latency-hiding scheduler overlaps the gradient all-reduce over the
'dp' mesh axis with remaining backward compute, and buffer donation
makes the parameter/optimizer-state update fully in-place.

This is the throughput path used by bench.py and the multi-chip
dryrun; the imperative Trainer path (gluon/trainer.py) remains for
step-by-step parity with the reference's
`autograd.record → backward → trainer.step` flow.
"""
from __future__ import annotations

import re

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import autograd
from .. import engine
from .. import telemetry
from ..ndarray.ndarray import NDArray
from ..random_state import next_key, trace_rng
from ..gluon import _deferred
from ..gluon.block import _flatten_arrays, _rebuild, CachedOp
from . import get_mesh, AXIS_DP


def _as_tuple(x):
    if isinstance(x, (list, tuple)):
        return tuple(x)
    return (x,)


class TrainStep:
    """Compile `loss_fn(net(data), label)` + grad + optimizer update into
    one jitted, donation-friendly XLA program, optionally sharded over a
    `jax.sharding.Mesh`.

    Parameters
    ----------
    net : HybridBlock (or any Block whose forward is trace-safe)
    loss_fn : callable(out, label) -> NDArray loss (gluon.loss.* works)
    optimizer : mxnet_tpu.optimizer.Optimizer instance or name string
    mesh : optional Mesh; defaults to parallel.get_mesh()
    batch_axis : mesh axis name the leading batch dim is sharded over
    param_rules : list of (regex, PartitionSpec) giving tensor-parallel
        placements by parameter name; unmatched params are replicated.
    """

    def __init__(self, net, loss_fn, optimizer, optimizer_params=None,
                 mesh=None, batch_axis=AXIS_DP, param_rules=None,
                 donate=True):
        from .. import optimizer as opt_mod
        self.net = net
        self.loss_fn = loss_fn
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self.optimizer = optimizer
        self._explicit_mesh = mesh
        self.batch_axis = batch_axis
        self.param_rules = [(re.compile(pat), spec)
                            for pat, spec in (param_rules or [])]
        self.donate = donate
        self._entries = {}
        self._opt_states = None  # shared across signatures: a shape
        self._mp_flags = None    # change (last odd batch) must NOT
        #                          reset Adam/momentum accumulators

    # -- helpers -------------------------------------------------------
    @property
    def mesh(self):
        return self._explicit_mesh or get_mesh()

    def _spec_for(self, name):
        for pat, spec in self.param_rules:
            if pat.search(name):
                return spec
        return P()

    # -- build ---------------------------------------------------------
    def _build(self, data_leaves, data_spec, label_leaves, label_spec):
        net, loss_fn = self.net, self.loss_fn
        params_dict = net.collect_params()
        if any(p._data is None for p in params_dict.values()):
            CachedOp(net)._abstract_init(list(data_leaves),
                                         data_spec)
            params_dict = net.collect_params()

        names = list(params_dict.keys())
        params = [params_dict[n] for n in names]
        diff_idx = [i for i, p in enumerate(params)
                    if p.grad_req != "null"]
        frozen_idx = [i for i, p in enumerate(params)
                      if p.grad_req == "null"]
        diff_nds = [params[i].data() for i in diff_idx]
        frozen_nds = [params[i].data() for i in frozen_idx]
        all_nds = diff_nds + frozen_nds

        opt = self.optimizer
        if self._opt_states is None:
            self._opt_states = [
                opt.create_state_multi_precision(k, diff_nds[k])
                for k in range(len(diff_idx))]
            self._mp_flags = [opt._use_mp(w) for w in diff_nds]
        states = self._opt_states
        mp_flags = self._mp_flags

        out_box = {}
        # capture only the contexts — closing over the leaf NDArrays
        # would pin the build-time batch buffers in HBM for the
        # lifetime of this cached entry
        data_ctxs = [l.ctx for l in data_leaves]
        label_ctxs = [l.ctx for l in label_leaves]

        def forward_loss(key, diff_datas, frozen_datas,
                         input_datas, label_datas):
            saved = [nd._data for nd in all_nds]
            scope = _deferred.trace_scope()
            rec = autograd._RecordingScope(False, True)
            with scope, rec, trace_rng(key):
                for nd, d in zip(diff_nds, diff_datas):
                    nd._data = d
                for nd, d in zip(frozen_nds, frozen_datas):
                    nd._data = d
                try:
                    in_nds = [NDArray(d, ctx=c)
                              for d, c in zip(input_datas, data_ctxs)]
                    lab_nds = [NDArray(d, ctx=c)
                               for d, c in zip(label_datas, label_ctxs)]
                    args = _rebuild(data_spec, in_nds)
                    out = net.forward(*args)
                    labels = _rebuild(label_spec, lab_nds)
                    if loss_fn is not None:
                        loss = loss_fn(out, *labels)
                    else:
                        loss = out
                    if loss.ndim > 0:
                        loss = loss.mean()
                finally:
                    for nd, s in zip(all_nds, saved):
                        nd._data = s
            out_box["aux_targets"] = [nd for nd, _ in scope.state_updates]
            aux = tuple(t for _, t in scope.state_updates)
            return loss._data, aux

        opt_cls = type(opt)
        n_diff = len(diff_nds)

        def step_fn(key, diff_datas, frozen_datas, opt_states, hypers,
                    input_datas, label_datas):
            def loss_f(dd):
                return forward_loss(key, dd, frozen_datas,
                                    input_datas, label_datas)

            (loss, aux), grads = jax.value_and_grad(
                loss_f, has_aux=True)(diff_datas)
            new_ws, new_ss = [], []
            for k in range(n_diff):
                w, g, s, h = (diff_datas[k], grads[k], opt_states[k],
                              hypers[k])
                if mp_flags[k]:
                    nw, ns = opt_cls._step_mp(w, g, s, h)
                else:
                    nw, ns = opt_cls._step(
                        w, jnp.asarray(g, w.dtype), s, h)
                new_ws.append(nw)
                new_ss.append(ns)
            return tuple(new_ws), tuple(new_ss), loss, aux

        mesh = self.mesh
        jit_kwargs = {}
        if self.donate:
            jit_kwargs["donate_argnums"] = (1, 3)
        if mesh is not None:
            rep = NamedSharding(mesh, P())
            diff_sh = []
            for k, i in enumerate(diff_idx):
                spec = getattr(params[i], "sharding", None)
                if spec is None:
                    spec = self._spec_for(names[i])
                diff_sh.append(NamedSharding(mesh, spec))
            frozen_sh = []
            for i in frozen_idx:
                spec = getattr(params[i], "sharding", None)
                if spec is None:
                    spec = self._spec_for(names[i])
                frozen_sh.append(NamedSharding(mesh, spec))
            state_sh = []
            for k in range(n_diff):
                w = diff_nds[k]
                wsh = diff_sh[k]
                wshape = tuple(w.shape)

                def leaf_sh(s, _wsh=wsh, _wshape=wshape):
                    shp = getattr(s, "shape", None)
                    return _wsh if shp is not None and tuple(shp) == _wshape \
                        else rep
                state_sh.append(jax.tree.map(leaf_sh, states[k]))

            # the PRIMARY input's leading dim defines the batch; other
            # leaves (e.g. RNN states shaped (layers, batch, hidden))
            # may carry it elsewhere — shard the axis that matches, or
            # replicate when none/ambiguous (dim0 wins ties: the
            # conventional batch-major layout)
            bsz = next((l.shape[0] for l in data_leaves if l.ndim),
                       None)

            def batch_sh(leaf):
                spec = [None] * leaf.ndim
                if leaf.ndim > 0 and bsz is not None:
                    if leaf.shape[0] == bsz:
                        spec[0] = self.batch_axis
                    else:
                        hits = [i for i, d in enumerate(leaf.shape)
                                if d == bsz]
                        if len(hits) == 1:
                            spec[hits[0]] = self.batch_axis
                return NamedSharding(mesh, P(*spec))

            data_sh = tuple(batch_sh(l) for l in data_leaves)
            label_sh = tuple(batch_sh(l) for l in label_leaves)
            hyper_sh = [jax.tree.map(lambda _: rep, opt._hyper(k))
                        for k in range(n_diff)]
            jit_kwargs["in_shardings"] = (
                rep, tuple(diff_sh), tuple(frozen_sh),
                tuple(state_sh), hyper_sh, data_sh, label_sh)
            # aux (BN stats) shardings: let XLA decide (None subtree)
            jit_kwargs["out_shardings"] = (tuple(diff_sh),
                                           tuple(state_sh), rep, None)
            # place current param values onto the mesh
            for k in range(n_diff):
                d = diff_nds[k]._data
                if not _placed_as(d, diff_sh[k]):
                    diff_nds[k]._data = jax.device_put(d, diff_sh[k])
                states[k] = jax.tree.map(
                    lambda s, sh: jax.device_put(s, sh)
                    if hasattr(s, "shape") else s,
                    states[k], state_sh[k])
            for j in range(len(frozen_nds)):
                d = frozen_nds[j]._data
                if not _placed_as(d, frozen_sh[j]):
                    frozen_nds[j]._data = jax.device_put(d, frozen_sh[j])
        else:
            data_sh = label_sh = None

        entry = {
            "data_sh": data_sh,
            "label_sh": label_sh,
            "jit": jax.jit(step_fn, **jit_kwargs),
            "step_fn": step_fn,
            "jit_kwargs": jit_kwargs,
            "params": params,
            "diff_idx": diff_idx,
            "diff_nds": diff_nds,
            "frozen_nds": frozen_nds,
            "out_box": out_box,
            "data_spec": data_spec,
            "label_spec": label_spec,
        }
        return entry

    # -- bulk (scan) path ----------------------------------------------
    def _build_chain(self, entry):
        """jit a lax.scan of step_fn over a leading steps axis.

        TPU-native equivalent of the reference engine's bulk mode
        (`MXNET_EXEC_BULK_EXEC_*`, BulkAppend/BulkFlush in
        src/engine/threaded_engine.h:507): instead of fusing engine
        pushes, N whole training steps compile into ONE XLA program —
        zero per-step host dispatch. BN running stats thread through
        the scan carry; Adam-style bias-correction counters advance
        per scanned step. LR schedules are evaluated at launch and
        held constant across the chain (document-level divergence:
        schedules step at chain granularity).
        """
        step_fn = entry["step_fn"]
        frozen_nds = entry["frozen_nds"]
        out_box = entry["out_box"]
        # aux target positions are resolved AT TRACE TIME inside the
        # scan body: out_box["aux_targets"] is only populated when
        # step_fn is first traced, which for a fresh entry happens
        # during this very chain trace
        aux_pos_box = {}

        def _aux_positions():
            if "pos" not in aux_pos_box:
                frozen_ids = [id(nd) for nd in frozen_nds]
                aux_pos_box["pos"] = [
                    frozen_ids.index(id(nd))
                    if id(nd) in frozen_ids else -1
                    for nd in out_box.get("aux_targets", [])]
            return aux_pos_box["pos"]

        def chain_fn(key, diff, frozen, states, hypers, datas, labels):
            n = datas[0].shape[0]

            def body(carry, xs):
                key, diff, frozen, states, t_off = carry
                ks = jax.random.split(key)
                key, sub = ks[0], ks[1]
                d, l = xs
                hy = [{**h, "t": h["t"] + t_off} for h in hypers]
                new_ws, new_ss, loss, aux = step_fn(
                    sub, diff, frozen, states, hy, d, l)
                frozen2 = list(frozen)
                for pos, a in zip(_aux_positions(), aux):
                    if pos >= 0:
                        frozen2[pos] = a
                return ((key, tuple(new_ws), tuple(frozen2),
                         tuple(new_ss), t_off + 1), (loss, aux))

            (key, diff, frozen, states, _), (losses, auxs) = \
                jax.lax.scan(body, (key, diff, frozen, states,
                                    jnp.int32(0)), (datas, labels))
            last_aux = jax.tree.map(lambda a: a[n - 1], auxs)
            return diff, frozen, states, losses, last_aux

        kw = {}
        chain_data_sh = chain_label_sh = None
        base = entry["jit_kwargs"]
        if self.donate:
            kw["donate_argnums"] = (1, 2, 3)
        if "in_shardings" in base:
            (rep, diff_sh, frozen_sh, state_sh, hyper_sh,
             data_sh, label_sh) = base["in_shardings"]
            mesh = self.mesh

            def lift(sh):
                # same placement with a replicated leading steps axis
                return NamedSharding(mesh, P(None, *sh.spec))

            chain_data_sh = tuple(lift(s) for s in data_sh)
            chain_label_sh = tuple(lift(s) for s in label_sh)
            kw["in_shardings"] = (
                rep, diff_sh, frozen_sh, state_sh, hyper_sh,
                chain_data_sh, chain_label_sh)
            kw["out_shardings"] = (diff_sh, frozen_sh, state_sh,
                                   rep, None)
        return (jax.jit(chain_fn, **kw), _aux_positions,
                chain_data_sh, chain_label_sh)

    def run_chain(self, data, label):
        """Run `data.shape[0]` chained training steps in one compiled
        XLA program (bulk mode). `data`/`label` carry a leading steps
        axis: ``(n_steps, batch, ...)``. Returns the per-step losses
        as an NDArray of shape ``(n_steps,)``."""
        data_t, label_t = _as_tuple(data), _as_tuple(label)
        data_leaves, data_spec = _flatten_arrays(data_t)
        label_leaves, label_spec = _flatten_arrays(label_t)
        n_steps = data_leaves[0].shape[0]

        # per-batch entry (strip the steps axis for the signature)
        one_data = [l[0] for l in data_leaves]
        one_label = [l[0] for l in label_leaves]
        sig = (tuple((l.shape, str(l.dtype)) for l in one_data),
               tuple((l.shape, str(l.dtype)) for l in one_label),
               repr(data_spec), repr(label_spec))
        entry = self._entries.get(sig)
        if entry is None:
            telemetry.counter("parallel.train_step.build")
            t0 = telemetry.clock()
            entry = self._build(one_data, data_spec, one_label,
                                label_spec)
            telemetry.duration_since("parallel.train_step.build", t0)
            self._entries[sig] = entry
        chain_key = ("chain", sig, n_steps)
        chain = self._entries.get(chain_key)
        chain_fresh = chain is None
        if chain_fresh:
            telemetry.counter("parallel.train_step.chain_build")
            chain = self._build_chain(entry)
            self._entries[chain_key] = chain
        chain_jit, aux_positions, chain_data_sh, chain_label_sh = chain

        opt = self.optimizer
        n_diff = len(entry["diff_nds"])
        # count the first chained step BEFORE reading hypers (Adam's
        # bias correction needs t>=1), then the remaining n-1; the
        # scan body advances t by its step offset
        opt._update_count(list(range(n_diff)))
        hypers = [opt._hyper(k) for k in range(n_diff)]
        for _ in range(n_steps - 1):
            opt._update_count(list(range(n_diff)))

        data_datas = [l._data for l in data_leaves]
        label_datas = [l._data for l in label_leaves]
        if chain_data_sh is not None:
            data_datas = [jax.device_put(d, sh) for d, sh in
                          zip(data_datas, chain_data_sh)]
            label_datas = [jax.device_put(d, sh) for d, sh in
                          zip(label_datas, chain_label_sh)]

        t0 = telemetry.clock()
        new_ws, new_fr, new_ss, losses, last_aux = chain_jit(
            next_key(),
            tuple(nd._data for nd in entry["diff_nds"]),
            tuple(nd._data for nd in entry["frozen_nds"]),
            tuple(self._opt_states), hypers,
            tuple(data_datas), tuple(label_datas))
        telemetry.duration_since(
            "parallel.train_step.chain_compile" if chain_fresh else
            "parallel.train_step.run_chain", t0)
        telemetry.counter("parallel.train_step.chained_steps", n_steps)

        for nd, nw in zip(entry["diff_nds"], new_ws):
            nd._data = nw
        for nd, nf in zip(entry["frozen_nds"], new_fr):
            nd._data = nf
        self._opt_states = list(new_ss)
        targets = entry["out_box"].get("aux_targets", [])
        with autograd.pause():
            for nd, pos, new in zip(targets, aux_positions(), last_aux):
                if pos < 0:  # not threaded through frozen: install last
                    nd._install(new)
        engine.sample_memory()
        return NDArray(engine.track(losses))

    # -- call ----------------------------------------------------------
    def __call__(self, data, label):
        """Run one training step; returns the (scalar NDArray) loss."""
        data_leaves, data_spec = _flatten_arrays(_as_tuple(data))
        label_leaves, label_spec = _flatten_arrays(_as_tuple(label))
        sig = (tuple((l.shape, str(l.dtype)) for l in data_leaves),
               tuple((l.shape, str(l.dtype)) for l in label_leaves),
               repr(data_spec), repr(label_spec))
        entry = self._entries.get(sig)
        if entry is None:
            telemetry.counter("parallel.train_step.build")
            t0 = telemetry.clock()
            entry = self._build(data_leaves, data_spec,
                                label_leaves, label_spec)
            telemetry.duration_since("parallel.train_step.build", t0)
            self._entries[sig] = entry
        opt = self.optimizer
        n_diff = len(entry["diff_nds"])
        opt._update_count(list(range(n_diff)))
        hypers = [opt._hyper(k) for k in range(n_diff)]

        data_datas = [l._data for l in data_leaves]
        label_datas = [l._data for l in label_leaves]
        if entry["data_sh"] is not None:
            data_datas = [jax.device_put(d, sh) for d, sh in
                          zip(data_datas, entry["data_sh"])]
            label_datas = [jax.device_put(d, sh) for d, sh in
                          zip(label_datas, entry["label_sh"])]

        diff_datas = tuple(nd._data for nd in entry["diff_nds"])
        # dispatch is async and entry["jit"] is lazily compiled: its
        # FIRST dispatch (even when the entry was built by an earlier
        # run_chain) pays trace + XLA compile; steady-state 'run'
        # measures enqueue latency (the host-side cost the reference's
        # engine-push timing captured)
        first_dispatch = not entry.get("jit_dispatched")
        t0 = telemetry.clock()
        new_ws, new_ss, loss, aux = entry["jit"](
            next_key(), diff_datas, tuple(nd._data for nd in
                                          entry["frozen_nds"]),
            tuple(self._opt_states), hypers,
            tuple(data_datas), tuple(label_datas))
        entry["jit_dispatched"] = True
        telemetry.duration_since(
            "parallel.train_step.compile" if first_dispatch else
            "parallel.train_step.run", t0)

        for nd, nw in zip(entry["diff_nds"], new_ws):
            nd._data = nw
        self._opt_states = list(new_ss)
        targets = entry["out_box"].get("aux_targets", [])
        with autograd.pause():
            for nd, new in zip(targets, aux):
                nd._install(new)
        engine.sample_memory()
        return NDArray(engine.track(loss))


def _placed_as(data, sh):
    try:
        return isinstance(data, jax.Array) and data.sharding == sh
    except Exception:
        return False

"""SPMD sharding layer — logical-axis partitioning over the device mesh.

The T5X-style ``Partitioner`` (SNIPPETS.md [1]/[3]): parameters carry
NAMED LOGICAL AXES (``"embed"``, ``"mlp"``, ``"heads"``, ``"kv"``,
``"vocab"``, ``"batch"``), an ORDERED rule list maps each logical axis
to a mesh axis (or to ``None`` = replicated), and every parameter
resolves to a per-leaf ``PartitionSpec`` / ``NamedSharding`` over the
process mesh. Everything upstream (``TrainStep``, the serving
engines, the checkpoint restore path) consumes the resolved specs —
the rules are the ONE place a layout is described.

Resolution semantics (per parameter, dims in order):

- the FIRST rule whose logical axis matches the dim wins;
- a mesh axis may be used at most ONCE per parameter (you cannot
  shard two dims of one array over the same devices);
- a mesh axis that does not DIVIDE the dim size falls through to the
  next matching rule, and ultimately to replication — with a one-shot
  warning, because a silently-replicated "sharded" layout is how a
  model quietly stops fitting;
- a dim with no logical name, or no matching rule, stays replicated.

Built-in layouts:

- ``"dp"`` — pure data parallel (every param replicated; batch over
  ``dp``). The pre-partitioner behavior, kept as the explicit
  baseline.
- ``"tp"`` — tensor parallel: attention q/k/v/out sharded over ``tp``
  by heads, ffn1/ffn2 over ``tp`` by the mlp dim, embeddings and
  lm_head over the vocab dim; activations replicated within a TP
  group. One model spread across the mesh — the multi-device serving
  layout.
- ``"fsdp"`` — fully-sharded data parallel (ZeRO-3 style): every
  parameter AND its optimizer state sharded over ``dp`` along its
  first shardable dim; inside the compiled step XLA all-gathers each
  layer's weights right before use (the gathers overlap compute under
  the latency-hiding scheduler) and reduces gradients straight into
  the owning shard — reduce-scatter semantics, ``(N-1)/N`` of the
  bytes per direction of the full allreduce the ``"dp"`` layout pays
  (see ``kvstore.collective_wire_bytes`` for the byte model).
- ``"tp_fsdp"`` — the 2-D composition over a ``(dp, tp)`` mesh:
  every parameter (and its optimizer state) shards over BOTH axes —
  the tp-sharded dim (heads/mlp/vocab) over ``tp`` and the embed dim
  over ``dp`` — so per-device param+optimizer bytes shrink by the
  whole mesh size, strictly below either 1-D layout. Compute keeps
  the fsdp (ZeRO) discipline: the step all-gathers each weight
  before use and the gradient reduce-scatters back into the owning
  shard over the fsdp axis / all-reduces over the tp axis
  (``gather_compute`` — ``TrainStep`` pins the in-step weight AND
  gradient placements so the math is the dense program's, which is
  what makes tp_fsdp losses BITWISE equal to dp on a deterministic
  backend).

Per-device footprint is MEASURED, not modeled: ``per_device_bytes``
walks real ``jax.Array`` shards, so the bench gate "this model's
param+optimizer footprint exceeds one device's share" is checked
against what the runtime actually placed.
"""
from __future__ import annotations

import contextlib
import re
import warnings
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import telemetry

P = PartitionSpec

__all__ = [
    "LOGICAL_AXES", "LAYOUTS", "Partitioner", "current_layout",
    "set_layout", "layout_scope", "grad_sync_bytes",
    "per_device_bytes", "hlo_collectives",
]

#: the logical-axis vocabulary (gpt.py annotates its parameters with
#: these; "kv" is the per-head feature dim — replicated in both
#: built-in layouts, named so a future head-dim layout is one rule)
LOGICAL_AXES = ("embed", "mlp", "heads", "kv", "vocab", "batch")

#: tensor parallel: weights split across 'tp' by heads / mlp / vocab,
#: activations (the "embed" residual stream) replicated within the TP
#: group, batch over 'dp'
TP_RULES = (
    ("heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("kv", None),
    ("embed", None),
    ("batch", "dp"),
)

#: fully-sharded data parallel: every parameter sharded over 'dp'
#: along its first shardable dim (ordering puts the big dims first so
#: q/k/v shard by heads, ffn1 by mlp, embeddings by vocab; out_proj/
#: ffn2 fall through to their "embed" dim). Optimizer state follows
#: the weight sharding (TrainStep maps same-shape state leaves to the
#: weight's spec).
FSDP_RULES = (
    ("vocab", "dp"),
    ("heads", "dp"),
    ("mlp", "dp"),
    ("embed", "dp"),
    ("kv", None),
    ("batch", "dp"),
)

#: pure data parallel — the explicit baseline: no parameter sharding
DP_RULES = (
    ("batch", "dp"),
)

#: 2-D tp×fsdp: the big projection dim over 'tp', the embed dim over
#: 'dp' — a 2-D param shards over the WHOLE mesh (ordered first-match
#: per dim, each mesh axis used once per param). Storage-only layout:
#: TrainStep's gather_compute path all-gathers weights in-step and
#: reduce-scatters grads back, so the math stays the dense program's.
TP_FSDP_RULES = (
    ("heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("embed", "dp"),
    ("kv", None),
    ("batch", "dp"),
)

LAYOUTS = {"dp": DP_RULES, "tp": TP_RULES, "fsdp": FSDP_RULES,
           "tp_fsdp": TP_FSDP_RULES}

#: layouts whose in-step COMPUTE must run on the gathered (replicated)
#: weights and gradients — the ZeRO discipline made explicit. 1-D fsdp
#: gets there through GSPMD's own propagation (PR 12's committed
#: bitwise result); the 2-D layout must pin it, because the 2-D output
#: shardings otherwise back-propagate tp splits into the backward
#: contractions and the partial-sum order drifts a ulp per step.
_GATHER_COMPUTE_LAYOUTS = ("tp_fsdp",)


def _axis_size(mesh: Mesh, axis) -> int:
    return int(mesh.shape.get(axis, 1)) if axis is not None else 1


class Partitioner:
    """Resolve named logical axes to mesh placements.

    Parameters
    ----------
    layout : str or sequence
        ``"dp"`` / ``"tp"`` / ``"fsdp"``, or an explicit ordered rule
        list ``[(logical_axis, mesh_axis_or_None), ...]``.
    mesh : jax.sharding.Mesh, optional
        Defaults to the process-global ``parallel.get_mesh()`` at
        resolution time.
    batch_axis : str
        Mesh axis the data batch is sharded over (default: whatever
        the ``"batch"`` rule names, falling back to ``"dp"``).
    """

    def __init__(self, layout="dp", mesh: Optional[Mesh] = None,
                 batch_axis=None):
        if isinstance(layout, str):
            if layout not in LAYOUTS:
                raise ValueError(
                    f"unknown layout {layout!r} (choose from "
                    f"{sorted(LAYOUTS)} or pass an explicit rule list)")
            self.layout = layout
            rules = LAYOUTS[layout]
        else:
            self.layout = "custom"
            rules = tuple(layout)
        for r in rules:
            if (not isinstance(r, (tuple, list)) or len(r) != 2
                    or not isinstance(r[0], str)):
                raise ValueError(
                    f"malformed rule {r!r}: want (logical_axis, "
                    f"mesh_axis_or_None)")
        self.rules = tuple((str(l), a) for l, a in rules)
        self._explicit_mesh = mesh
        if batch_axis is None:
            batch_axis = next((a for l, a in self.rules
                               if l == "batch" and a is not None), "dp")
        self.batch_axis = batch_axis
        self._warned = set()

    # -- mesh ----------------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        from . import get_mesh
        mesh = self._explicit_mesh or get_mesh()
        if mesh is None:
            raise RuntimeError(
                "Partitioner needs a mesh: pass mesh= or call "
                "parallel.set_mesh() first")
        return mesh

    # -- resolution ----------------------------------------------------
    def spec_for(self, logical_axes, shape, name="<param>") -> PartitionSpec:
        """Resolve one array's logical axes to a ``PartitionSpec``.

        ``logical_axes`` is a tuple of logical names (or ``None``) per
        dim; ``None``/unmatched dims stay replicated. First matching
        rule wins per dim; each mesh axis is used at most once per
        array; a non-dividing mesh axis falls through to the next
        matching rule and finally to replication (one-shot warning)."""
        if logical_axes is None:
            return P()
        mesh = self.mesh
        logical_axes = tuple(logical_axes)
        if len(logical_axes) != len(shape):
            raise ValueError(
                f"{name}: logical axes {logical_axes} do not match "
                f"shape {tuple(shape)}")
        used = set()
        entries = []
        for d, (lax_name, dim) in enumerate(zip(logical_axes, shape)):
            pick = None
            if lax_name is not None:
                for rule_axis, mesh_axis in self.rules:
                    if rule_axis != lax_name or mesh_axis is None:
                        continue
                    if mesh_axis in used:
                        continue
                    n = _axis_size(mesh, mesh_axis)
                    if n <= 1:
                        continue
                    if int(dim) % n != 0:
                        # warn ONCE per (logical axis, mesh axis) pair
                        # — a model with 50 odd-sized heads params
                        # must not emit 50 copies of the same fact
                        # (the first offender is named in the message)
                        key = (lax_name, mesh_axis)
                        if key not in self._warned:
                            self._warned.add(key)
                            warnings.warn(
                                f"partition: {name} dim {d} "
                                f"({lax_name}={dim}) is not divisible "
                                f"by mesh axis {mesh_axis!r} "
                                f"(size {n}); falling back to "
                                f"replication for this dim (warned "
                                f"once per ({lax_name!r}, "
                                f"{mesh_axis!r}) pair)")
                        continue
                    pick = mesh_axis
                    break
            if pick is not None:
                used.add(pick)
            entries.append(pick)
        while entries and entries[-1] is None:
            entries.pop()
        return P(*entries)

    def sharding(self, spec: PartitionSpec) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def batch_spec(self, ndim: int, axis: int = 0) -> PartitionSpec:
        entries = [None] * ndim
        entries[axis] = self.batch_axis
        return P(*entries)

    # -- parameter annotation ------------------------------------------
    def annotate(self, params, override_rules=None):
        """Resolve and record each parameter's spec (``p.sharding``).

        ``params`` is a ``{name: Parameter}`` dict (``collect_params``
        output). A parameter's logical axes come from its
        ``logical_axes`` attribute (gpt.py sets them); parameters
        without metadata stay replicated. ``override_rules`` is the
        ``TrainStep(param_rules=)`` escape hatch — a list of
        ``(compiled_regex_or_pattern, PartitionSpec)`` whose first
        match wins over the logical-axis resolution for that
        parameter. Returns ``{name: PartitionSpec}``."""
        compiled = []
        for pat, spec in (override_rules or []):
            if isinstance(pat, str):
                pat = re.compile(pat)
            compiled.append((pat, spec))
        out = {}
        n_sharded = 0
        for name, p in params.items():
            spec = None
            for pat, s in compiled:
                if pat.search(name):
                    spec = s
                    break
            if spec is None:
                # prefer the MATERIALIZED shape: a deferred Parameter's
                # declared shape may carry unknown (-1/0) dims, which
                # must not pretend to divide a mesh axis
                if p._data is not None:
                    shape = tuple(p._data.shape)
                else:
                    shape = getattr(p, "shape", None)
                if shape is None or any(int(d) <= 0 for d in shape):
                    spec = P()
                else:
                    spec = self.spec_for(
                        getattr(p, "logical_axes", None), shape, name)
            p.sharding = spec
            out[name] = spec
            if any(e is not None for e in spec):
                n_sharded += 1
        telemetry.gauge("parallel.partition.params_sharded", n_sharded)
        return out

    def place(self, params, override_rules=None):
        """Annotate AND move each materialized parameter onto its
        resolved ``NamedSharding`` (replicated params land replicated
        over the mesh). Records the measured per-device parameter
        bytes. Returns the spec dict."""
        specs = self.annotate(params, override_rules=override_rules)
        mesh = self.mesh
        for name, p in params.items():
            if p._data is None:
                continue
            sh = NamedSharding(mesh, specs[name])
            d = p._data._data
            if not (isinstance(d, jax.Array)
                    and getattr(d, "sharding", None) == sh):
                p._data._install(jax.device_put(d, sh))
        telemetry.gauge(
            "parallel.partition.bytes_per_device",
            per_device_bytes([p._data._data for p in params.values()
                              if p._data is not None]))
        return specs

    # -- KV-cache placement (serving TP) -------------------------------
    def cache_spec(self, shape, num_heads) -> PartitionSpec:
        """Spec for one KV-cache leaf: shard the heads axis (the dim
        equal to ``num_heads`` at position 1 — dense caches are
        ``(B, H, S, Dh)``, paged pools ``(n_pages, H, ps, Dh)``, scale
        tables ``(B|n_pages, H)``) over the axis the ``"heads"`` rule
        names; everything else (tables, lengths) replicated."""
        tp_axis = next((a for l, a in self.rules
                        if l == "heads" and a is not None), None)
        if tp_axis is None or _axis_size(self.mesh, tp_axis) <= 1:
            return P()
        if len(shape) >= 2 and int(shape[1]) == int(num_heads) \
                and int(num_heads) % _axis_size(self.mesh, tp_axis) == 0:
            entries = [None] * len(shape)
            entries[1] = tp_axis
            return P(*entries)
        return P()

    #: cache-pytree keys whose leaves shard by heads (dense caches,
    #: paged pools, and their int8 scale tables). The page TABLE and
    #: the ``len`` vector are host-logic state and stay replicated
    #: even when their shapes coincide with a heads dim (a (B, P_max)
    #: table with P_max == num_heads must never shard).
    _CACHE_SHARDED_KEYS = frozenset(("k", "v", "k_scale", "v_scale"))

    def cache_shardings(self, cache, num_heads):
        """Pytree of ``NamedSharding``s matching a generation-cache
        pytree (``init_cache``/``init_paged_cache`` layout): K/V
        buffers (and their int8 scale tables) shard over the heads
        axis; the page table and lengths replicate — keyed by the
        pytree path, not by shape coincidence."""
        mesh = self.mesh
        rep = NamedSharding(mesh, P())

        def leaf_sh(path, leaf):
            keys = {getattr(p, "key", None) for p in path}
            if keys & self._CACHE_SHARDED_KEYS:
                return NamedSharding(
                    mesh, self.cache_spec(tuple(leaf.shape), num_heads))
            return rep

        return jax.tree_util.tree_map_with_path(leaf_sh, cache)

    def place_cache(self, cache, num_heads):
        """Commit a cache pytree onto the mesh with the heads axis
        sharded (the serving-TP analog of ``GenerationEngine._commit``
        — the explicit target keeps the arrays COMMITTED, which the
        pjit executable cache keys on)."""
        return jax.device_put(cache,
                              self.cache_shardings(cache, num_heads))

    # -- in-step compute discipline ------------------------------------
    @property
    def gather_compute(self) -> bool:
        """True when the layout's in-step compute must run on the
        GATHERED weights and gradients (``TrainStep`` pins replicated
        in-step placements): the 2-D ``tp_fsdp`` layout, whose 2-D
        output shardings would otherwise back-propagate tp splits
        into the backward contractions and drift the losses a ulp
        per step away from dp."""
        return self.layout in _GATHER_COMPUTE_LAYOUTS

    # -- grad-sync selection -------------------------------------------
    @property
    def grad_collective(self) -> str:
        """``"reduce_scatter"`` when this layout shards parameters (and
        therefore optimizer state) over the batch/dp axis — the
        gradient can be reduced straight into the owning shard and the
        updated shard all-gathered, ``(N-1)/N`` of the bytes per
        direction of a full allreduce. ``"allreduce"`` otherwise."""
        for rule_axis, mesh_axis in self.rules:
            if rule_axis == "batch":
                continue
            if mesh_axis is not None and mesh_axis == self.batch_axis:
                return "reduce_scatter"
        return "allreduce"

    # -- comm accounting -----------------------------------------------
    def comm_bytes_per_step(self, specs, params) -> int:
        """Analytic per-step gradient-sync wire bytes for this layout
        (see :func:`grad_sync_bytes`)."""
        return grad_sync_bytes(specs, params, self.mesh,
                               self.batch_axis)


def grad_sync_bytes(specs, params, mesh: Mesh, batch_axis="dp") -> int:
    """Per-step gradient-sync wire bytes for a resolved layout, under
    the byte model ``kvstore.collective_wire_bytes`` documents (full
    bytes per direction for allreduce; ``(N-1)/N`` per direction for
    reduce-scatter + all-gather — the fsdp path). ``specs`` maps
    param name -> resolved ``PartitionSpec``; ``params`` maps name ->
    Parameter (only ``grad_req != "null"`` params sync). A param
    sharded over the batch axis syncs by reduce-scatter + all-gather
    (its optimizer state lives sharded); everything else (replicated
    or tp-sharded) syncs its grad by allreduce over the batch axis."""
    from .. import kvstore as _kv
    n_dp = _axis_size(mesh, batch_axis)
    total = 0
    for name, p in params.items():
        if p.grad_req == "null" or p._data is None:
            continue
        nbytes = int(p._data._data.nbytes)
        spec = specs.get(name) or P()
        flat = [a for e in spec if e is not None
                for a in (e if isinstance(e, (tuple, list)) else (e,))]
        if batch_axis in flat:
            # 2-D layouts: a param ALSO sharded over a non-batch axis
            # (tp) reduce-scatters only its tp-shard's bytes over the
            # fsdp axis — each tp group syncs 1/tp of the payload —
            # but the in-step REGATHER (the ZeRO gather-compute
            # discipline: the weight must be replicated before use)
            # then also all-gathers the full payload over each
            # non-batch axis. Net effect at 2x2: tp_fsdp wire bytes
            # per param equal fsdp's — ZeRO comm is ~independent of
            # the sharding factor; the 2-D win is MEMORY, and the
            # model must not invent a comm saving that the executed
            # HLO (more all-gathers, not fewer) does not show.
            shard = nbytes
            for e in flat:
                if e != batch_axis:
                    shard //= max(_axis_size(mesh, e), 1)
            total += _kv.collective_wire_bytes(
                "reduce_scatter", shard, n_dp)
            total += _kv.collective_wire_bytes(
                "all_gather", shard, n_dp)
            for e in flat:
                if e != batch_axis:
                    total += _kv.collective_wire_bytes(
                        "all_gather", nbytes, _axis_size(mesh, e))
        elif n_dp > 1:
            shard = nbytes
            for e in flat:
                shard //= max(_axis_size(mesh, e), 1)
            total += _kv.collective_wire_bytes("allreduce", shard, n_dp)
    return total


# ---------------------------------------------------------------------------
# process-global active layout (grad_fusion consults it per bucket)
# ---------------------------------------------------------------------------
_current: Optional[Partitioner] = None


def current_layout() -> Optional[Partitioner]:
    """The process-global active layout, or None (pure DP)."""
    return _current


def set_layout(part: Optional[Partitioner]):
    global _current
    _current = part
    return part


@contextlib.contextmanager
def layout_scope(part: Optional[Partitioner]):
    global _current
    prev = _current
    _current = part
    try:
        yield part
    finally:
        _current = prev


# ---------------------------------------------------------------------------
# measurement helpers
# ---------------------------------------------------------------------------

def per_device_bytes(leaves, device=None) -> int:
    """MEASURED bytes one device holds for ``leaves`` (arrays or
    pytrees of arrays): walks each ``jax.Array``'s addressable shards
    and sums the ones on ``device`` (default: the first device of the
    first sharded leaf; single-device arrays count in full). This is
    what the "fits one device's share of HBM" bench gate reads."""
    flat = []
    for leaf in leaves:
        flat.extend(x for x in jax.tree.leaves(leaf)
                    if hasattr(x, "nbytes"))
    if device is None:
        for x in flat:
            if isinstance(x, jax.Array):
                try:
                    device = x.sharding._device_assignment[0]
                except Exception:
                    device = next(iter(x.devices()))
                break
    total = 0
    for x in flat:
        if isinstance(x, jax.Array):
            try:
                shards = x.addressable_shards
            except Exception:
                total += int(x.nbytes)
                continue
            total += sum(int(s.data.nbytes) for s in shards
                         if s.device == device)
        else:
            total += int(getattr(x, "nbytes", 0))
    return int(total)


_HLO_COLL = re.compile(
    r"=\s*(?:\(([^)]*)\)|([a-z0-9]+)\[([0-9,]*)\][^ ]*)\s*"
    r"(all-reduce|reduce-scatter|all-gather)(?:-start)?\(")
_HLO_TUPLE_ELT = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
                "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2,
                "u16": 2, "s8": 1, "u8": 1, "pred": 1}


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def hlo_collectives(compiled_text: str) -> dict:
    """Count the cross-device collectives in a compiled HLO module:
    ``{"all-reduce": {"count": n, "bytes": output_bytes}, ...}``.
    Structural evidence for the layout A/B — the DP program's grad
    sync is all-reduce; the FSDP program must show the per-layer
    all-gathers (XLA lowers the reduce-scatter half as
    reduce-scatter on TPU/GPU and as all-reduce + dynamic-slice on
    the CPU backend — either way the all-gathers only exist under the
    sharded layout)."""
    out = {}
    for m in _HLO_COLL.finditer(compiled_text):
        tuple_body, dtype, dims, kind = m.groups()
        if tuple_body is not None:
            nbytes = sum(_shape_bytes(dt, dm) for dt, dm
                         in _HLO_TUPLE_ELT.findall(tuple_body))
        else:
            nbytes = _shape_bytes(dtype, dims)
        slot = out.setdefault(kind, {"count": 0, "bytes": 0})
        slot["count"] += 1
        slot["bytes"] += nbytes
    return out

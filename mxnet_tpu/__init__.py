"""mxnet_tpu — a TPU-native deep learning framework with the
capabilities of Apache MXNet (reference: szha/mxnet).

Compute substrate: JAX/XLA (PJRT) — imperative NDArray ops dispatch
asynchronously through JAX eager; hybridized Gluon blocks compile to
single whole-graph XLA programs; data parallelism rides ICI/DCN via
jax.sharding meshes and XLA collectives. See SURVEY.md at the repo root
for the capability map against the reference.

Typical usage mirrors the reference:

    import mxnet_tpu as mx
    from mxnet_tpu import np, npx, autograd, gluon
"""
from __future__ import annotations

import os as _os

import jax as _jax

# Platform pinning (reference parity: context selection never blocks on
# an absent device — /root/reference/python/mxnet/context.py:24-249).
# The axon TPU plugin registers itself regardless of JAX_PLATFORMS and
# its PJRT init can hang indefinitely when the tunnel is down, so a
# plain `JAX_PLATFORMS=cpu` env var is not enough: the platform list
# must be pinned via jax.config BEFORE any backend probe.
# MXTPU_PLATFORM (ours) always wins; the JAX_PLATFORMS env var is
# honored best-effort but never overrides a jax_platforms value user
# code already set via jax.config.update before importing us.
_platform_pin = _os.environ.get("MXTPU_PLATFORM")
if not _platform_pin:
    # The axon plugin clobbers jax_platforms to exactly "axon,cpu" at
    # jax import time — that is why the JAX_PLATFORMS env var is dead
    # on this image. Re-assert the env var over the plugin's clobber,
    # but respect any OTHER value (one user code set via
    # jax.config.update before importing us — including an explicit
    # "axon" to force the TPU).
    _jp = _os.environ.get("JAX_PLATFORMS")
    _cfg = getattr(_jax.config, "jax_platforms", None)
    if _jp and (not _cfg or _cfg == _jp or _cfg == "axon,cpu"):
        _platform_pin = _jp
if _platform_pin:
    try:
        _jax.config.update("jax_platforms", _platform_pin)
    except Exception:  # pragma: no cover - older jax without the knob
        pass

# float64/int64 arrays are first-class in the reference, but a
# process-global x64 flag inflates every trace/compile and risks silent
# f64 on TPU hot paths (f64 is emulated there).  x64 is therefore
# opt-in via MXTPU_ENABLE_X64=1; the default keeps JAX's f32 world,
# which matches the reference's creation-op defaults (float32).

if _os.environ.get("MXTPU_ENABLE_X64", "") not in ("", "0"):
    _jax.config.update("jax_enable_x64", True)

from .base import MXNetError, __version__  # noqa: E402,F401
from .context import (  # noqa: E402,F401
    Context, cpu, cpu_pinned, gpu, tpu, num_gpus, num_tpus,
    current_context, default_context, gpu_memory_info,
)
from . import engine  # noqa: E402,F401
from .ndarray.ndarray import NDArray, waitall  # noqa: E402,F401
from . import ndarray  # noqa: E402,F401
from . import ndarray as nd  # noqa: E402,F401
from . import numpy  # noqa: E402,F401
from . import numpy as np  # noqa: E402,F401
from . import numpy_extension  # noqa: E402,F401
from . import numpy_extension as npx  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from .utils_io import save, load  # noqa: E402,F401
from .base import (  # noqa: E402,F401
    set_np, reset_np, is_np_array, is_np_shape, is_np_default_dtype)

# Subsystem modules land incrementally during the build; import what exists.
import importlib as _importlib

for _mod in ("initializer", "init", "optimizer", "lr_scheduler", "gluon",
             "kvstore", "parallel", "profiler", "runtime", "test_utils",
             "util", "recordio", "image", "io", "amp", "random", "symbol",
             "rtc", "contrib", "library", "visualization", "operator",
             "model", "callback", "name", "attribute", "registry",
             "error", "log", "misc", "dlpack", "executor", "telemetry",
             "tracing", "monitor", "bucketing", "compile_cache",
             "serving", "checkpoint", "resilience"):
    try:
        globals()[_mod] = _importlib.import_module(f".{_mod}", __name__)
    except ModuleNotFoundError as _e:
        if f"mxnet_tpu.{_mod}" not in str(_e):
            raise
del _importlib, _mod

# Persistent XLA compilation cache (MXTPU_COMPILE_CACHE_DIR): wire the
# jax.config knobs before the first compile so cold starts replay
# yesterday's executables from disk (docs/PERFORMANCE.md).
if "compile_cache" in globals():
    globals()["compile_cache"].configure()

if "attribute" in globals():
    AttrScope = globals()["attribute"].AttrScope

# reference short aliases (python/mxnet/__init__.py:55-95)
if "visualization" in globals():
    viz = globals()["visualization"]
if "random" in globals():
    rnd = globals()["random"]
if "kvstore" in globals():
    kv = globals()["kvstore"]

if "symbol" in globals():
    sym = globals()["symbol"]

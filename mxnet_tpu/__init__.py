"""mxnet_tpu — a TPU-native deep learning framework with the
capabilities of Apache MXNet (reference: szha/mxnet).

Compute substrate: JAX/XLA (PJRT) — imperative NDArray ops dispatch
asynchronously through JAX eager; hybridized Gluon blocks compile to
single whole-graph XLA programs; data parallelism rides ICI/DCN via
jax.sharding meshes and XLA collectives. See SURVEY.md at the repo root
for the capability map against the reference.

Typical usage mirrors the reference:

    import mxnet_tpu as mx
    from mxnet_tpu import np, npx, autograd, gluon
"""
from __future__ import annotations

import jax as _jax

# float64/int64 arrays are first-class in the reference, but a
# process-global x64 flag inflates every trace/compile and risks silent
# f64 on TPU hot paths (f64 is emulated there).  x64 is therefore
# opt-in via MXTPU_ENABLE_X64=1; the default keeps JAX's f32 world,
# which matches the reference's creation-op defaults (float32).
import os as _os

if _os.environ.get("MXTPU_ENABLE_X64", "") not in ("", "0"):
    _jax.config.update("jax_enable_x64", True)

from .base import MXNetError, __version__  # noqa: E402,F401
from .context import (  # noqa: E402,F401
    Context, cpu, cpu_pinned, gpu, tpu, num_gpus, num_tpus,
    current_context, default_context, gpu_memory_info,
)
from . import engine  # noqa: E402,F401
from .ndarray.ndarray import NDArray, waitall  # noqa: E402,F401
from . import ndarray  # noqa: E402,F401
from . import ndarray as nd  # noqa: E402,F401
from . import numpy  # noqa: E402,F401
from . import numpy as np  # noqa: E402,F401
from . import numpy_extension  # noqa: E402,F401
from . import numpy_extension as npx  # noqa: E402,F401
from . import autograd  # noqa: E402,F401
from .utils_io import save, load  # noqa: E402,F401
from .base import set_np, reset_np, is_np_array, is_np_shape  # noqa: E402,F401

# Subsystem modules land incrementally during the build; import what exists.
import importlib as _importlib

for _mod in ("initializer", "init", "optimizer", "lr_scheduler", "gluon",
             "kvstore", "parallel", "profiler", "runtime", "test_utils",
             "util", "recordio", "image", "io", "amp", "random", "symbol",
             "rtc", "contrib", "library", "visualization", "operator",
             "model", "callback", "name", "attribute", "registry",
             "error", "log", "misc"):
    try:
        globals()[_mod] = _importlib.import_module(f".{_mod}", __name__)
    except ModuleNotFoundError as _e:
        if f"mxnet_tpu.{_mod}" not in str(_e):
            raise
del _importlib, _mod

if "symbol" in globals():
    sym = globals()["symbol"]

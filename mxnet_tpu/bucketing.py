"""Shape bucketing — stable compile signatures for variable batch sizes.

Every distinct input-shape signature costs a full re-trace + XLA
compile in `CachedOp` and `TrainStep` (the `gluon.cachedop.build` /
`parallel.train_step.build` telemetry from PR 1 makes this visible:
the last odd batch of every epoch forces a rebuild). The reference
hides variable shapes behind its bucketing executors
(module/bucketing_module.py) and pad-reporting iterators
(`DataBatch.pad`). Here the policy is one object: map a batch size to
the nearest *bucket*, pad the batch up to it, and report how many
trailing rows are padding so the loss masks them out.

A policy is consulted in three places:

- `io.NDArrayIter(bucketing=...)` / `gluon.data.DataLoader(
  bucketing=...)` pad the final partial batch up to the bucket and
  mark the pad on the produced arrays;
- `gluon.block.CachedOp` pads inference batches to the bucket and
  slices outputs back (per-sample nets only — padded rows flow
  through BN batch stats etc.);
- `parallel.TrainStep` pads + masks padded rows out of the loss, so
  training results match the unpadded path exactly.

Padded rows REPLICATE the last valid row (never zeros/garbage): the
mask multiplies their loss by 0, and `0 * inf = nan` would poison the
sum if a padded row produced a non-finite loss.

A process-global policy can be installed with `set_policy` /
`policy_scope`, or via the ``MXTPU_BUCKETING`` env var:
``pow2`` | ``mult:8`` | ``16,32,64`` (explicit buckets) | ``0``/unset
(disabled).
"""
from __future__ import annotations

import contextlib
import os

__all__ = ["BucketingPolicy", "set_policy", "get_policy",
           "policy_scope", "mark_pad", "get_pad", "pad_leaves"]


class BucketingPolicy:
    """Map a batch size ``n`` to the smallest allowed bucket >= n.

    Parameters
    ----------
    buckets : sequence of int, optional
        Explicit allowed sizes. When given, `mode` is ignored;
        a size above the largest bucket maps to itself.
    mode : {"pow2", "multiple"}
        ``pow2`` rounds up to the next power of two; ``multiple``
        rounds up to the next multiple of `multiple`.
    multiple : int
        Granularity for ``mode="multiple"`` (8 matches the TPU
        sublane tiling — see docs/PERFORMANCE.md).
    min_size : int
        Floor for computed buckets (tiny tails share one bucket).
    max_size : int, optional
        Ceiling: a computed bucket above it clamps to
        ``max(n, max_size)``. Iterators pass their batch size here so
        the last partial batch never pads beyond a full batch.
    """

    def __init__(self, buckets=None, mode="pow2", multiple=8,
                 min_size=1, max_size=None):
        if buckets is not None:
            buckets = sorted(int(b) for b in buckets)
            if not buckets or buckets[0] < 1:
                raise ValueError(f"buckets must be positive, got {buckets}")
        elif mode not in ("pow2", "multiple"):
            raise ValueError(
                f"mode must be 'pow2' or 'multiple', got {mode!r}")
        if int(multiple) < 1 or int(min_size) < 1:
            raise ValueError("multiple and min_size must be >= 1")
        self.buckets = buckets
        self.mode = mode
        self.multiple = int(multiple)
        self.min_size = int(min_size)
        self.max_size = int(max_size) if max_size is not None else None

    def bucket(self, n: int) -> int:
        """Smallest allowed size >= n (never below n)."""
        n = int(n)
        if n < 1:
            return n
        if self.buckets is not None:
            target = next((b for b in self.buckets if b >= n), n)
        elif self.mode == "pow2":
            target = max(self.min_size, 1 << (n - 1).bit_length())
        else:
            m = self.multiple
            target = max(self.min_size, -(-n // m) * m)
        if self.max_size is not None and target > self.max_size:
            target = max(n, self.max_size)
        return target

    def sizes(self, max_size: int):
        """Every bucket size reachable for a batch in ``1..max_size``,
        sorted ascending — the warmup template list for a consumer
        that wants zero steady-state compiles (serving engine AOT
        warmup, `TrainStep.warmup`)."""
        return sorted({self.bucket(n) for n in range(1, int(max_size) + 1)})

    def clamped(self, batch_size: int) -> "BucketingPolicy":
        """Copy of this policy that never pads past ``batch_size``."""
        return BucketingPolicy(
            buckets=self.buckets, mode=self.mode, multiple=self.multiple,
            min_size=self.min_size,
            max_size=batch_size if self.max_size is None
            else min(self.max_size, batch_size))

    def __repr__(self):
        if self.buckets is not None:
            body = f"buckets={self.buckets}"
        else:
            body = f"mode={self.mode!r}, multiple={self.multiple}"
        return (f"BucketingPolicy({body}, min_size={self.min_size}, "
                f"max_size={self.max_size})")


def _from_env(spec: str):
    spec = (spec or "").strip()
    if spec in ("", "0", "off", "false", "none"):
        return None
    if spec == "pow2":
        return BucketingPolicy(mode="pow2")
    if spec.startswith("mult:"):
        return BucketingPolicy(mode="multiple", multiple=int(spec[5:]))
    return BucketingPolicy(buckets=[int(x) for x in spec.split(",")])


def as_policy(value):
    """Normalize a user-facing bucketing argument: None/False → None,
    True → env default (or pow2), str → env-style spec, policy → policy."""
    if value is None or value is False:
        return None
    if value is True:
        return get_policy() or BucketingPolicy(mode="pow2")
    if isinstance(value, str):
        return _from_env(value)
    if isinstance(value, BucketingPolicy):
        return value
    raise TypeError(f"bucketing must be a BucketingPolicy, bool, or "
                    f"env-style str, got {type(value).__name__}")


try:
    _policy = _from_env(os.environ.get("MXTPU_BUCKETING", ""))
except (ValueError, TypeError) as _e:
    # a malformed env var must not take down `import mxnet_tpu` for
    # programs that never touch bucketing
    import warnings as _warnings
    _warnings.warn(f"ignoring malformed MXTPU_BUCKETING="
                   f"{os.environ.get('MXTPU_BUCKETING')!r}: {_e}")
    _policy = None


def set_policy(policy):
    """Install the process-global policy (None disables). Returns the
    previous policy."""
    global _policy
    prev = _policy
    _policy = as_policy(policy) if not isinstance(policy, BucketingPolicy) \
        else policy
    return prev


def get_policy():
    return _policy


@contextlib.contextmanager
def policy_scope(policy):
    prev = set_policy(policy)
    try:
        yield get_policy()
    finally:
        set_policy(prev)


# -- pad marking -------------------------------------------------------
# The side channel between the data pipeline and the training step: a
# loader that padded a batch marks the produced NDArrays; TrainStep
# reads the mark and masks the padded rows out of the loss without the
# training loop having to thread `pad=` through by hand.

def mark_pad(arr, pad: int):
    """Record that the trailing ``pad`` rows of ``arr`` are padding."""
    try:
        arr._bucket_pad = int(pad)
    except AttributeError:
        pass
    return arr


def get_pad(arr) -> int:
    """Pad rows recorded on ``arr`` by the data pipeline (0 if none)."""
    return getattr(arr, "_bucket_pad", 0) or 0


def pad_leaves(leaves, target: int, batch: int | None = None):
    """Pad every NDArray leaf whose leading dim equals the batch up to
    ``target`` (replicating the last row); mark the pad on each padded
    leaf. Leaves carrying the batch elsewhere (or not at all) pass
    through untouched. Returns (new_leaves, pad)."""
    from .ndarray.ndarray import NDArray
    if batch is None:
        batch = next((l.shape[0] for l in leaves if l.ndim), None)
    if batch is None or target <= batch:
        return list(leaves), 0
    pad = target - batch
    out = []
    for l in leaves:
        if l.ndim and l.shape[0] == batch:
            import jax.numpy as jnp
            reps = jnp.broadcast_to(l._data[-1:],
                                    (pad,) + tuple(l.shape[1:]))
            padded = NDArray(jnp.concatenate([l._data, reps], axis=0),
                             ctx=l.ctx)
            out.append(mark_pad(padded, pad))
        else:
            out.append(l)
    return out, pad

"""NDArray serialization: mx.nd.save / mx.nd.load parity.

The reference uses a custom binary format (magic+version header,
NDArray::Save/Load, src/ndarray/ndarray.cc:1729,1852) plus .npy/.npz via
src/serialization/cnpy.cc. Here the container format IS .npz (zip of
.npy members) — portable, inspectable, and loadable by plain NumPy.
A dict saves keys verbatim; a list saves under reserved keys
``__list_N`` preserving order.
"""
from __future__ import annotations

import numpy as onp


def save(fname, data):
    from .numpy import array  # noqa: F401
    from .ndarray.ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        payload = {f"__list_{i}": d.asnumpy() for i, d in enumerate(data)}
    elif isinstance(data, dict):
        payload = {k: v.asnumpy() for k, v in data.items()}
    else:
        raise TypeError(f"cannot save {type(data)}")
    with open(fname, "wb") as f:
        onp.savez(f, **payload)


def load(fname):
    from .numpy import array

    with onp.load(fname, allow_pickle=False) as npz:
        keys = list(npz.files)
        if keys and all(k.startswith("__list_") for k in keys):
            keys.sort(key=lambda k: int(k[len("__list_"):]))
            return [array(npz[k]) for k in keys]
        return {k: array(npz[k]) for k in keys}

"""NDArray serialization: mx.nd.save / mx.nd.load parity.

The reference uses a custom binary format (magic+version header,
NDArray::Save/Load, src/ndarray/ndarray.cc:1729,1852 — with sparse
support) plus .npy/.npz via src/serialization/cnpy.cc. Here the
container format IS .npz (zip of .npy members) — portable,
inspectable, and loadable by plain NumPy. A dict saves keys verbatim;
a list saves under reserved keys ``__list_N`` preserving order.
Sparse arrays expand to ``<key>:<field>`` members with a ``__sparse__``
marker field carrying the stype.
"""
from __future__ import annotations

import numpy as onp

_SP = "\x01sparse\x01"  # member-name separator unlikely in user keys


def _encode(key, value, payload):
    from .ndarray.sparse import BaseSparseNDArray, RowSparseNDArray
    if isinstance(value, BaseSparseNDArray):
        payload[f"{key}{_SP}stype"] = onp.array(value.stype)
        payload[f"{key}{_SP}shape"] = onp.array(value.shape, onp.int64)
        payload[f"{key}{_SP}data"] = onp.asarray(value.data.asnumpy())
        payload[f"{key}{_SP}indices"] = onp.asarray(value.indices.asnumpy())
        if not isinstance(value, RowSparseNDArray):
            payload[f"{key}{_SP}indptr"] = onp.asarray(
                value.indptr.asnumpy())
    else:
        payload[key] = value.asnumpy()


def _decode_groups(npz):
    from .numpy import array
    from .ndarray import sparse as sp

    done = {}
    grouped = {}
    for k in npz.files:
        if _SP in k:
            base, field = k.split(_SP, 1)
            grouped.setdefault(base, {})[field] = npz[k]
        else:
            done[k] = array(npz[k])
    for base, fields in grouped.items():
        stype = str(fields["stype"])
        shape = tuple(int(s) for s in fields["shape"])
        if stype == "row_sparse":
            done[base] = sp.row_sparse_array(
                (fields["data"], fields["indices"]), shape=shape)
        else:
            done[base] = sp.csr_matrix(
                (fields["data"], fields["indices"], fields["indptr"]),
                shape=shape)
    return done


def save(fname, data):
    from .ndarray.ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    payload = {}
    if isinstance(data, (list, tuple)):
        for i, d in enumerate(data):
            _encode(f"__list_{i}", d, payload)
    elif isinstance(data, dict):
        for k, v in data.items():
            _encode(k, v, payload)
    else:
        raise TypeError(f"cannot save {type(data)}")
    with open(fname, "wb") as f:
        onp.savez(f, **payload)


def load(fname):
    # auto-detect the reference's legacy binary NDArray format
    from . import legacy_serialization as _legacy
    with open(fname, "rb") as f:
        head = f.read(8)
    if _legacy.is_legacy_file(head):
        return _legacy.load_legacy(fname)
    with onp.load(fname, allow_pickle=False) as npz:
        done = _decode_groups(npz)
        keys = list(done.keys())
        if keys and all(k.startswith("__list_") for k in keys):
            keys.sort(key=lambda k: int(k[len("__list_"):]))
            return [done[k] for k in keys]
        return done

"""INT8 post-training quantization (PTQ) with calibration.

Capability parity with the reference's quantization pillar:
- python driver:     python/mxnet/contrib/quantization.py:755 `quantize_net`
- calibration:       src/operator/quantization/calibrate.cc (entropy/KL),
                     _LayerOutputMinMaxCollector (naive min-max)
- graph rewrite:     src/operator/quantization/quantize_graph_pass.cc

TPU-first redesign: instead of an nnvm graph pass inserting
quantize/requantize nodes around oneDNN int8 kernels, quantizable Gluon
layers (Dense, Conv) are swapped for quantized twins whose forward is

    x_q   = clip(round(x / s_x), -127, 127)      -> int8
    acc   = dot/conv(x_q, w_q)  int8 x int8      -> int32  (MXU int8 path)
    out   = acc * (s_x * s_w) + bias             -> fp32   (dequantize)

`s_x` comes from calibration (naive min-max or entropy/KL-optimal
thresholds, same algorithms as the reference) or is computed in-graph
for `calib_mode='none'`. Weights are pre-quantized per-tensor or
per-output-channel (`quantize_granularity='channel-wise'`). After the
swap the net is still a HybridBlock: hybridizing produces ONE XLA
program with int8 convolutions/dots visible in the lowered HLO.
"""
from __future__ import annotations

import fnmatch
import logging

import numpy as onp
import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry
from ..ndarray.ndarray import NDArray
from ..ops import apply_op
from ..gluon.block import HybridBlock
from ..gluon.parameter import Constant
from ..gluon import nn as _nn

__all__ = ["CalibrationCollector", "quantize_net", "iter_quantized",
           "QuantizedDense", "QuantizedConv"]

_INT8_MAX = 127.0


# ---------------------------------------------------------------------------
# collectors
# ---------------------------------------------------------------------------
class CalibrationCollector:
    """Base calibration collector (parity:
    python/mxnet/contrib/quantization.py:163). Subclasses observe the
    INPUT of every to-be-quantized layer during calibration forwards and
    produce `{layer_name: (min, max)}` in `post_collect`."""

    def __init__(self):
        self.include_layers = None

    def collect(self, name, arr):
        raise NotImplementedError

    def post_collect(self):
        raise NotImplementedError


class _LayerInputMinMaxCollector(CalibrationCollector):
    """`calib_mode='naive'` — running min/max of each layer input
    (parity: _LayerOutputMinMaxCollector, quantization.py:294)."""

    def __init__(self, logger=None):
        super().__init__()
        self.min_max_dict = {}
        self.logger = logger

    def collect(self, name, arr):
        host = arr.asnumpy() if isinstance(arr, NDArray) else onp.asarray(arr)
        lo, hi = float(host.min()), float(host.max())
        if name in self.min_max_dict:
            olo, ohi = self.min_max_dict[name]
            self.min_max_dict[name] = (min(olo, lo), max(ohi, hi))
        else:
            self.min_max_dict[name] = (lo, hi)

    def post_collect(self):
        return self.min_max_dict


class _LayerHistogramCollector(CalibrationCollector):
    """`calib_mode='entropy'` — KL-divergence-optimal thresholds
    (parity: _LayerHistogramCollector, quantization.py:193, and the
    C++ entropy path src/operator/quantization/calibrate.cc)."""

    def __init__(self, num_bins=8001, logger=None):
        super().__init__()
        self.hist_dict = {}
        self.num_bins = num_bins
        self.logger = logger

    def collect(self, name, arr):
        host = arr.asnumpy() if isinstance(arr, NDArray) else onp.asarray(arr)
        th = float(max(abs(host.min()), abs(host.max()), 1e-12))
        if name not in self.hist_dict:
            hist, edges = onp.histogram(host, bins=self.num_bins,
                                        range=(-th, th))
            self.hist_dict[name] = (hist, edges, th)
            return
        old_hist, old_edges, old_th = self.hist_dict[name]
        if th <= old_th:
            hist, _ = onp.histogram(host, bins=len(old_hist),
                                    range=(-old_th, old_th))
            self.hist_dict[name] = (old_hist + hist, old_edges, old_th)
        else:
            # widen: extend symmetric bins in whole old-bin steps so old
            # counts land exactly in the middle of the new histogram
            old_bins = len(old_hist)
            step = 2 * old_th / old_bins
            grow = int((th - old_th) // step + 1)
            new_bins = old_bins + 2 * grow
            new_th = grow * step + old_th
            hist, edges = onp.histogram(host, bins=new_bins,
                                        range=(-new_th, new_th))
            hist[grow:new_bins - grow] += old_hist
            self.hist_dict[name] = (hist, edges, new_th)

    @staticmethod
    def get_optimal_threshold(hist, hist_edges, num_quantized_bins=255):
        """KL-optimal clip threshold for a symmetric histogram
        (the TensorRT/MXNet entropy-calibration algorithm, rewritten:
        slide a candidate clip window outward, compare the clipped
        reference distribution P against its `num_quantized_bins`-level
        quantization Q, keep the threshold minimizing KL(P||Q))."""
        num_bins = len(hist)
        assert num_bins % 2 == 1, "histogram must be symmetric (odd bins)"
        zero_bin = num_bins // 2
        half_q = num_quantized_bins // 2
        centers = (hist_edges[:-1] + hist_edges[1:]) / 2
        best_kl, best_th = onp.inf, float(abs(hist_edges[-1]))
        hist = hist.astype(onp.float64)
        eps = 1e-8
        for i in range(half_q, zero_bin + 1):
            lo, hi = zero_bin - i, zero_bin + i + 1
            sliced = hist[lo:hi]
            # P: clipped distribution — outlier mass collapses onto the
            # clip edges, so aggressive clipping inflates the edges
            p = sliced.copy()
            p[0] += hist[:lo].sum()
            p[-1] += hist[hi:].sum()
            nonzero = p > 0
            if nonzero.sum() == 0 or sliced.sum() == 0:
                continue
            # Q: the int8 model of the WINDOW ONLY (no outlier mass) —
            # each of the num_quantized_bins levels spreads its window
            # mass uniformly over its nonzero source bins. Clipping that
            # discards real mass therefore shows up as P≫Q at the edges
            # and is penalized by KL(P||Q).
            n = len(sliced)
            q = onp.zeros(n)
            chunk = n // num_quantized_bins
            for j in range(num_quantized_bins):
                s = j * chunk
                e = n if j == num_quantized_bins - 1 else (j + 1) * chunk
                mass = sliced[s:e].sum()
                count = nonzero[s:e].sum()
                if count:
                    q[s:e][nonzero[s:e]] = mass / count
            if q.sum() == 0:
                continue
            p_norm = p / p.sum() + eps
            q_norm = q / q.sum() + eps
            kl = float((p_norm * onp.log(p_norm / q_norm)).sum())
            if kl < best_kl:
                best_kl = kl
                best_th = float(abs(centers[hi - 1]))
        return best_th

    def post_collect(self):
        out = {}
        for name, (hist, edges, _th) in self.hist_dict.items():
            th = self.get_optimal_threshold(hist, edges)
            out[name] = (-th, th)
            if self.logger:
                self.logger.info("entropy threshold %s = %.5f", name, th)
        return out


# ---------------------------------------------------------------------------
# quantized kernels
# ---------------------------------------------------------------------------
def _quantize_weight(w, channel_axis, granularity):
    """fp32 weight -> (int8 weight, fp32 scale) with symmetric range."""
    if granularity == "channel-wise":
        axes = tuple(i for i in range(w.ndim) if i != channel_axis)
        absmax = onp.abs(w).max(axis=axes, keepdims=True)
    else:
        absmax = onp.abs(w).max()
    absmax = onp.maximum(absmax, 1e-12)
    scale = absmax / _INT8_MAX
    wq = onp.clip(onp.round(w / scale), -127, 127).astype(onp.int8)
    return wq, scale.astype(onp.float32)


def _quantize_act(x, scale):
    return jnp.clip(jnp.round(x / scale), -_INT8_MAX, _INT8_MAX) \
        .astype(jnp.int8)


def _dynamic_scale(x):
    """In-graph activation scale for ``calib_mode='none'``. The
    epsilon floor guards the all-zero activation batch: an unguarded
    ``absmax / 127`` scale of exactly 0 would turn ``_quantize_act``'s
    ``x / scale`` into 0/0 NaNs that ``clip`` happily keeps —
    quantizing zeros must yield zeros. Eager (non-hybridized) calls
    record a ``quantization.dynamic_scale`` duration; inside a trace
    the computation is staged, so there is nothing meaningful to
    time."""
    if x.size == 0:
        raise ValueError("cannot derive an int8 scale from an empty "
                         "activation")
    tracing = isinstance(x, jax.core.Tracer)
    t0 = None if tracing else telemetry.clock()
    scale = jnp.maximum(jnp.abs(x).max(), 1e-12) / _INT8_MAX
    if t0 is not None:
        telemetry.hist_since("quantization.dynamic_scale", t0)
    return scale


class QuantizedDense(HybridBlock):
    """int8 twin of nn.Dense (parity: quantized_fully_connected,
    src/operator/quantization/quantized_fully_connected.cc).

    The int8 weights, per-channel scales and bias are registered
    ``Constant`` parameters — NOT trace-baked closures — so a
    hybridized twin's CachedOp passes them as runtime arguments and a
    serving weight rollover (``requantize``) installs fresh buffers
    with ZERO retraces, exactly like the fp32 engines' swap."""

    def __init__(self, dense, in_range=None,
                 granularity="channel-wise"):
        super().__init__()
        self._units = dense._units
        self._flatten = dense._flatten
        self._granularity = granularity
        #: dotted source-layer name (set by quantize_net) — the key
        #: prefix a rollover checkpoint's fp32 weights carry, so
        #: InferenceEngine.load_weights can re-quantize in place
        self._src_name = None
        self.act = dense.act
        w = dense.weight.data().asnumpy()          # (units, in)
        wq, w_scale = _quantize_weight(w, 0, granularity)
        self.wq = Constant(wq, name="wq")
        self.w_scale = Constant(w_scale.reshape(-1).astype(onp.float32),
                                name="w_scale")
        self.qbias = (Constant(dense.bias.data().asnumpy(),
                               name="qbias")
                      if dense.bias is not None else None)
        for p in (self.wq, self.w_scale, self.qbias):
            if p is not None:
                p.initialize()
        # static input scale from calibration, or None -> in-graph
        self._in_scale = (max(abs(in_range[0]), abs(in_range[1]))
                          / _INT8_MAX if in_range is not None else None)

    def _install(self, const, host):
        """Swap a Constant's device buffer in place (placement
        preserved) — the trace sees the same runtime argument slot,
        so nothing recompiles."""
        nd = const.data()
        nd._data = jax.device_put(jnp.asarray(host), nd._data.sharding)
        const.value = host

    def requantize(self, weight, bias=None):
        """Recompute the int8 weights/scales from fresh fp32 arrays
        (the serving weight-rollover path). Shapes must match the
        original layer's; validation precedes any mutation so a bad
        checkpoint can never leave the twin half-swapped. The
        calibrated input scale is kept — re-calibration is the
        caller's decision, not a side effect of a rollover."""
        w = onp.asarray(weight, dtype=onp.float32)
        if w.shape != tuple(self.wq.shape):
            raise ValueError(
                f"requantize weight shape {w.shape} does not match "
                f"the quantized layer's {tuple(self.wq.shape)}")
        if (bias is None) != (self.qbias is None):
            raise ValueError(
                "requantize bias presence must match the quantized "
                "layer's")
        if bias is not None:
            b = onp.asarray(bias, dtype=onp.float32)
            if b.shape != tuple(self.qbias.shape):
                raise ValueError(
                    f"requantize bias shape {b.shape} does not match "
                    f"{tuple(self.qbias.shape)}")
        wq, w_scale = _quantize_weight(w, 0, self._granularity)
        self._install(self.wq, wq)
        self._install(self.w_scale,
                      w_scale.reshape(-1).astype(onp.float32))
        if bias is not None:
            self._install(self.qbias, b)
        return self

    def forward(self, x):
        wq = self.wq.data()._data
        w_scale = self.w_scale.data()._data
        bias = self.qbias.data()._data if self.qbias is not None \
            else None
        s_in = self._in_scale

        def fn(xr):
            xr2 = xr.reshape(xr.shape[0], -1) if self._flatten else xr
            s_x = jnp.float32(s_in) if s_in is not None \
                else _dynamic_scale(xr2)
            xq = _quantize_act(xr2, s_x)
            acc = lax.dot_general(xq, wq,
                                  (((xq.ndim - 1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (s_x * w_scale)
            if bias is not None:
                out = out + bias
            return out

        out = apply_op(fn, x, name="quantized_dense")
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        return f"QuantizedDense(int8, units={self._units})"


class QuantizedConv(HybridBlock):
    """int8 twin of nn.Conv1D/2D/3D (parity: quantized_conv,
    src/operator/quantization/quantized_conv.cc)."""

    def __init__(self, conv, in_range=None, granularity="channel-wise"):
        super().__init__()
        assert conv._op_name == "convolution", \
            "only forward convolutions can be quantized"
        self._granularity = granularity
        self._src_name = None   # see QuantizedDense._src_name
        self._kernel = conv._kernel
        self._stride = conv._stride
        self._pad = conv._pad
        self._dilate = conv._dilate
        self._groups = conv._groups
        self._layout = conv._layout
        self._channels = conv._channels
        self.act = conv.act
        w = conv.weight.data().asnumpy()
        ch_axis = 0  # weight layout puts out-channels first in both
        wq, w_scale = _quantize_weight(w, ch_axis, granularity)
        self.wq = Constant(wq, name="wq")
        self.w_scale = Constant(w_scale.reshape(-1).astype(onp.float32),
                                name="w_scale")
        self.qbias = (Constant(conv.bias.data().asnumpy(), name="qbias")
                      if conv.bias is not None else None)
        for p in (self.wq, self.w_scale, self.qbias):
            if p is not None:
                p.initialize()
        self._in_scale = (max(abs(in_range[0]), abs(in_range[1]))
                          / _INT8_MAX if in_range is not None else None)

    def forward(self, x):
        from ..ops import nn as _opsnn
        wq = self.wq.data()._data
        w_scale = self.w_scale.data()._data
        bias = self.qbias.data()._data if self.qbias is not None \
            else None
        s_in = self._in_scale
        nsp = len(self._kernel)
        stride = self._stride if isinstance(self._stride, tuple) \
            else (self._stride,) * nsp
        dilate = self._dilate if isinstance(self._dilate, tuple) \
            else (self._dilate,) * nsp
        pad = self._pad if isinstance(self._pad, tuple) \
            else (self._pad,) * nsp
        layout = self._layout
        nc = layout.startswith("NC")

        def fn(xr):
            s_x = jnp.float32(s_in) if s_in is not None \
                else _dynamic_scale(xr)
            xq = _quantize_act(xr, s_x)
            lhs, rhs, out_spec = _opsnn._conv_dims(layout)
            # reference weight layout: (O, I/g, *k) for NC*,
            # (O, *k, I/g) otherwise — same dim orders ops/nn.py uses
            wspec = rhs
            acc = lax.conv_general_dilated(
                xq, wq, stride,
                [(p, p) for p in pad],
                rhs_dilation=dilate,
                dimension_numbers=(lhs, wspec, out_spec),
                feature_group_count=self._groups,
                preferred_element_type=jnp.int32)
            scale = s_x * w_scale
            bshape = [1] * acc.ndim
            bshape[1 if nc else acc.ndim - 1] = -1
            out = acc.astype(jnp.float32) * scale.reshape(bshape)
            if bias is not None:
                out = out + bias.reshape(bshape)
            return out

        out = apply_op(fn, x, name="quantized_conv")
        if self.act is not None:
            out = self.act(out)
        return out

    # identical contracts (weight layout puts out-channels first in
    # both Dense and Conv, so axis-0 requantization carries over)
    _install = QuantizedDense._install
    requantize = QuantizedDense.requantize

    def __repr__(self):
        return (f"QuantizedConv(int8, channels={self._channels}, "
                f"kernel={self._kernel})")


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def _walk_quantizable(block, prefix=""):
    """Yield (parent, child_key, dotted_name, child) for quantizable
    leaves, depth-first (dotted names match collect_params keys)."""
    for key, child in list(block._children.items()):
        name = f"{prefix}{key}"
        if isinstance(child, _nn.Dense) or (
                isinstance(child, _nn.conv_layers._Conv)
                and child._op_name == "convolution"):
            yield block, key, name, child
        else:
            yield from _walk_quantizable(child, name + ".")


def _attr_name_for_child(parent, child):
    for attr, val in vars(parent).items():
        if val is child:
            return attr
    return None


def iter_quantized(block, prefix=""):
    """Yield ``(dotted_name, twin)`` for every QuantizedDense /
    QuantizedConv in ``block`` (depth-first, collect_params-style
    names) — how the serving engines detect an int8 net and find the
    twins a rollover must re-quantize."""
    for key, child in block._children.items():
        name = f"{prefix}{key}"
        if isinstance(child, (QuantizedDense, QuantizedConv)):
            yield name, child
        else:
            yield from iter_quantized(child, name + ".")


def quantize_net(network, quantized_dtype="auto", quantize_mode="full",
                 quantize_granularity="tensor-wise", exclude_layers=None,
                 exclude_layers_match=None, exclude_operators=None,
                 calib_data=None, data_shapes=None, calib_mode="none",
                 num_calib_batches=None, ctx=None,
                 LayerOutputCollector=None, logger=None):
    """Quantize a Gluon HybridBlock to int8 (parity:
    python/mxnet/contrib/quantization.py:755 `quantize_net`).

    Returns the same network with quantizable layers swapped for int8
    twins; hybridize it afterwards to compile one XLA program with int8
    contractions. `calib_mode`: 'none' (dynamic in-graph ranges),
    'naive' (min-max over `calib_data`), 'entropy' (KL-optimal
    thresholds over `calib_data`), 'custom' (user collector).
    """
    logger = logger or logging.getLogger(__name__)
    if quantized_dtype not in ("auto", "int8", "uint8"):
        raise ValueError(f"unsupported quantized_dtype {quantized_dtype!r}")
    if quantized_dtype == "uint8":
        raise ValueError("uint8 quantization is not supported on TPU; "
                         "the MXU int8 path is symmetric — use 'int8'")
    if quantize_granularity not in ("tensor-wise", "channel-wise"):
        raise ValueError(
            f"unsupported quantize_granularity {quantize_granularity!r}")
    if quantize_mode not in ("full", "smart"):
        raise ValueError(f"unsupported quantize_mode {quantize_mode!r}")
    if quantize_mode == "smart":
        logger.warning("quantize_mode='smart' is treated as 'full' here: "
                       "XLA fuses the dequantize boundaries itself, so "
                       "there is no oneDNN-style op-pattern whitelist to "
                       "be smart about")

    exclude_layers = set(exclude_layers or [])
    exclude_layers_match = list(exclude_layers_match or [])
    exclude_operators = set(exclude_operators or [])

    targets = []
    for parent, key, name, child in _walk_quantizable(network):
        if name in exclude_layers:
            continue
        if any(fnmatch.fnmatch(name, pat) or pat in name
               for pat in exclude_layers_match):
            continue
        opname = ("FullyConnected" if isinstance(child, _nn.Dense)
                  else "Convolution")
        if opname in exclude_operators:
            continue
        targets.append((parent, key, name, child))
    if not targets:
        raise ValueError("network has no quantizable layers")

    # Calibration must run eagerly: a compiled CachedOp replays the
    # whole graph without invoking child __call__, so hooks would never
    # fire (or would fire on tracers during the build). Deactivate
    # hybridization for the duration; the caller re-hybridizes the
    # quantized net.
    was_active = []
    for b in network._iter_blocks():
        if getattr(b, "_active", False):
            was_active.append(b)
            b._active = False
        if hasattr(b, "_clear_cached_op"):
            b._clear_cached_op()

    # Materialize deferred parameters before reading weights: the
    # reference runs a dummy forward from data_shapes
    # (quantization.py:829); calib_data's first batch works too.
    if any(not p._shape_known() or p._data is None
           for _, _, _, child in targets
           for p in child._reg_params.values()):
        if calib_data is not None:
            probe = next(iter(calib_data))
            probe = probe[0] if isinstance(probe, (list, tuple)) else probe
            network(probe)
        elif data_shapes is not None:
            from ..numpy import zeros
            network(*[zeros(tuple(s)) for s in data_shapes])
        else:
            raise ValueError(
                "network has uninitialized (deferred) parameters; provide "
                "calib_data or data_shapes so a shape-inferring forward "
                "can run first")

    # ---- calibration ----
    in_ranges = {}
    if calib_mode != "none":
        if calib_mode == "naive":
            collector = _LayerInputMinMaxCollector(logger=logger)
        elif calib_mode == "entropy":
            collector = _LayerHistogramCollector(logger=logger)
        elif calib_mode == "custom":
            if LayerOutputCollector is None:
                raise ValueError(
                    "calib_mode='custom' needs LayerOutputCollector")
            collector = LayerOutputCollector
        else:
            raise ValueError(f"unknown calib_mode {calib_mode!r}")
        collector.include_layers = [name for _, _, name, _ in targets]
        if calib_data is None:
            raise ValueError(
                f"calib_mode={calib_mode!r} requires calib_data")

        handles = []
        for _, _, name, child in targets:
            def make_hook(nm):
                def pre_hook(block, args):
                    collector.collect(nm, args[0])
                return pre_hook
            handles.append(
                child.register_forward_pre_hook(make_hook(name)))
        try:
            nb = 0
            for batch in calib_data:
                data = batch[0] if isinstance(batch, (list, tuple)) \
                    else batch
                network(data)
                nb += 1
                if num_calib_batches is not None and \
                        nb >= num_calib_batches:
                    break
            logger.info("calibrated on %d batches (%s)", nb, calib_mode)
        finally:
            for h in handles:
                h.detach()
        in_ranges = collector.post_collect()

    # ---- swap in quantized twins ----
    for parent, key, name, child in targets:
        rng = in_ranges.get(name)
        if isinstance(child, _nn.Dense):
            q = QuantizedDense(child, in_range=rng,
                              granularity=quantize_granularity)
        else:
            q = QuantizedConv(child, in_range=rng,
                              granularity=quantize_granularity)
        q._src_name = name
        parent._children[key] = q
        attr = _attr_name_for_child(parent, child)
        if attr is not None:
            object.__setattr__(parent, attr, q)
        logger.info("quantized %s -> %r", name, q)

    # restore hybridization on surviving blocks; caches are stale
    for b in was_active:
        b._active = True
    for b in network._iter_blocks():
        if hasattr(b, "_clear_cached_op"):
            b._clear_cached_op()
    return network

"""mx.contrib — optional subsystems (parity: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401

"""mx.contrib — optional subsystems (parity: python/mxnet/contrib/)."""
from . import quantization  # noqa: F401
from . import onnx  # noqa: F401
from . import tensorboard  # noqa: F401
from . import text  # noqa: F401

"""contrib.tensorboard — metric logging to TensorBoard event files
(parity: python/mxnet/contrib/tensorboard.py LogMetricsCallback).

The reference wraps the `tensorboard` package's SummaryWriter; this
environment has no tensorboard/tensorflow, so the writer emits the TF
event-file format directly: TFRecord framing (length + masked-crc32c)
around serialized Event/Summary protobuf messages (field numbers from
tensorflow/core/util/event.proto and framework/summary.proto).
TensorBoard reads the resulting `events.out.tfevents.*` files as-is.
"""
from __future__ import annotations

import os
import struct
import time

__all__ = ["SummaryWriter", "LogMetricsCallback"]

# --- crc32c (Castagnoli), table-driven -------------------------------------
_CRC_TABLE = []


def _crc_table():
    if _CRC_TABLE:
        return _CRC_TABLE
    poly = 0x82F63B78
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


# --- minimal protobuf writers ----------------------------------------------
def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _ld(field, payload):
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _f32(field, v):
    return _varint((field << 3) | 5) + struct.pack("<f", v)


def _f64(field, v):
    return _varint((field << 3) | 1) + struct.pack("<d", v)


def _vint(field, v):
    return _varint((field << 3) | 0) + _varint(v)


def _scalar_event(tag: str, value: float, step: int) -> bytes:
    # Summary.Value { tag=1, simple_value=2 }
    sval = _ld(1, tag.encode()) + _f32(2, float(value))
    summary = _ld(1, sval)              # Summary { value=1 repeated }
    # Event { wall_time=1 (double), step=2 (int64), summary=5 }
    return _f64(1, time.time()) + _vint(2, step) + _ld(5, summary)


def _file_version_event() -> bytes:
    return _f64(1, time.time()) + _ld(3, b"brain.Event:2")


class SummaryWriter:
    """Scalar-only event writer compatible with TensorBoard's loader."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{os.getpid()}.{id(self):x}.mxnet_tpu")
        self._f = open(os.path.join(logdir, fname), "wb")
        self._write_record(_file_version_event())

    def _write_record(self, payload: bytes):
        header = struct.pack("<Q", len(payload))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(payload)
        self._f.write(struct.pack("<I", _masked_crc(payload)))

    def add_scalar(self, tag, value, global_step=0):
        self._write_record(_scalar_event(tag, value, global_step))

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class LogMetricsCallback:
    """Batch-end callback pushing EvalMetric values to TensorBoard
    (parity: contrib/tensorboard.py LogMetricsCallback)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = SummaryWriter(logging_dir)

    def __call__(self, param):
        """`param` is a BatchEndParam-alike with `.eval_metric`,
        or an EvalMetric directly."""
        if hasattr(param, "eval_metric"):
            metric = param.eval_metric
        else:
            metric = param
        if metric is None or not hasattr(metric, "get"):
            return
        name_value = metric.get()
        names, values = name_value if isinstance(name_value[0],
                                                 (list, tuple)) \
            else ([name_value[0]], [name_value[1]])
        self.step += 1
        for name, value in zip(names, values):
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self.step)
        self.summary_writer.flush()

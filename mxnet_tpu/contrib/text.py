"""contrib.text — vocabulary + token embeddings (parity:
python/mxnet/contrib/text/{vocab.py,embedding.py}).

GloVe/FastText pretrained downloads need egress the deployment may not
have, so `CustomEmbedding` (load any `token<sep>v1 v2 ...` file) and
`CompositeEmbedding` are the core; `GloVe`/`FastText` accept a local
`pretrained_file_path` and parse the same format.
"""
from __future__ import annotations

import collections

import numpy as onp

__all__ = ["Vocabulary", "CustomEmbedding", "CompositeEmbedding",
           "GloVe", "FastText", "register", "create",
           "count_tokens_from_str"]

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(embedding_name, **kwargs):
    return _REGISTRY[embedding_name.lower()](**kwargs)


def count_tokens_from_str(source_str, token_delim=" ", seq_delim="\n",
                          to_lower=False, counter_to_update=None):
    """Token frequency counter (parity: text/utils.py)."""
    source_str = source_str.lower() if to_lower else source_str
    counter = counter_to_update if counter_to_update is not None \
        else collections.Counter()
    for seq in source_str.split(seq_delim):
        counter.update(t for t in seq.split(token_delim) if t)
    return counter


class Vocabulary:
    """Indexed vocabulary with reserved tokens (parity:
    text/vocab.py Vocabulary)."""

    def __init__(self, counter=None, most_freq_count=None, min_freq=1,
                 unknown_token="<unk>", reserved_tokens=None):
        if min_freq < 1:
            raise ValueError("min_freq must be >= 1")
        self.unknown_token = unknown_token
        reserved_tokens = list(reserved_tokens or [])
        if len(set(reserved_tokens)) != len(reserved_tokens) or \
                unknown_token in reserved_tokens:
            raise ValueError("reserved tokens must be unique and must "
                             "not contain the unknown token")
        self._idx_to_token = [unknown_token] + reserved_tokens
        self._reserved_tokens = reserved_tokens or None
        seen = set(self._idx_to_token)
        if counter is not None:
            pairs = sorted(counter.items(), key=lambda kv: (-kv[1],
                                                            kv[0]))
            taken = 0
            for tok, freq in pairs:
                # the cap counts NEWLY indexed tokens (reserved/unknown
                # occurrences in the corpus must not consume slots)
                if most_freq_count is not None and \
                        taken >= most_freq_count:
                    break
                if freq >= min_freq and tok not in seen:
                    seen.add(tok)
                    self._idx_to_token.append(tok)
                    taken += 1
        self._token_to_idx = {t: i for i, t
                              in enumerate(self._idx_to_token)}

    def __len__(self):
        return len(self._idx_to_token)

    @property
    def token_to_idx(self):
        return self._token_to_idx

    @property
    def idx_to_token(self):
        return self._idx_to_token

    @property
    def reserved_tokens(self):
        return self._reserved_tokens

    def to_indices(self, tokens):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        out = [self._token_to_idx.get(t, 0) for t in toks]
        return out[0] if single else out

    def to_tokens(self, indices):
        single = isinstance(indices, int)
        idxs = [indices] if single else indices
        for i in idxs:
            if not 0 <= i < len(self):
                raise ValueError(f"index {i} out of vocabulary range")
        out = [self._idx_to_token[i] for i in idxs]
        return out[0] if single else out


class _TokenEmbedding(Vocabulary):
    """Vocabulary + per-token vectors (parity:
    text/embedding.py _TokenEmbedding)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._vec_len = 0
        self._idx_to_vec = None

    @property
    def vec_len(self):
        return self._vec_len

    @property
    def idx_to_vec(self):
        return self._idx_to_vec

    def _load_embedding_file(self, path, elem_delim=" ",
                             encoding="utf8"):
        vecs = {}
        with open(path, encoding=encoding) as f:
            for line_num, line in enumerate(f, 1):
                parts = line.rstrip().split(elem_delim)
                if len(parts) < 2:
                    continue
                tok, vals = parts[0], parts[1:]
                if line_num == 1 and len(vals) == 1 and \
                        tok.isdigit() and vals[0].strip().isdigit():
                    continue  # fastText "count dim" header, not a token
                try:
                    vec = [float(v) for v in vals]
                except ValueError:
                    continue
                if self._vec_len == 0:
                    self._vec_len = len(vec)
                if len(vec) == self._vec_len and tok not in vecs:
                    vecs[tok] = vec
        return vecs

    def _build(self, vecs, vocabulary=None):
        import mxnet_tpu as mx
        if vocabulary is None:
            for tok in sorted(vecs):
                if tok not in self._token_to_idx:
                    self._token_to_idx[tok] = len(self._idx_to_token)
                    self._idx_to_token.append(tok)
        else:
            self._idx_to_token = list(vocabulary.idx_to_token)
            self._token_to_idx = dict(vocabulary.token_to_idx)
            self.unknown_token = vocabulary.unknown_token
        mat = onp.zeros((len(self), self._vec_len), onp.float32)
        for tok, vec in vecs.items():
            idx = self._token_to_idx.get(tok)
            if idx is not None:
                mat[idx] = vec
        self._idx_to_vec = mx.np.array(mat)

    def get_vecs_by_tokens(self, tokens, lower_case_backup=False):
        single = isinstance(tokens, str)
        toks = [tokens] if single else tokens
        idxs = []
        for t in toks:
            i = self._token_to_idx.get(t)
            if i is None and lower_case_backup:
                i = self._token_to_idx.get(t.lower())
            idxs.append(i if i is not None else 0)
        out = self._idx_to_vec[onp.asarray(idxs)]
        return out[0] if single else out

    def update_token_vectors(self, tokens, new_vectors):
        import mxnet_tpu as mx
        toks = [tokens] if isinstance(tokens, str) else tokens
        host = onp.array(self._idx_to_vec.asnumpy())
        nv = new_vectors.asnumpy() if hasattr(new_vectors, "asnumpy") \
            else onp.asarray(new_vectors)
        nv = nv.reshape(len(toks), -1)
        for t, v in zip(toks, nv):
            if t not in self._token_to_idx:
                raise ValueError(f"token {t!r} is unknown")
            host[self._token_to_idx[t]] = v
        self._idx_to_vec = mx.np.array(host)


@register
class CustomEmbedding(_TokenEmbedding):
    """Embedding from a user file 'token<elem_delim>v1 v2 ...'
    (parity: embedding.py CustomEmbedding)."""

    def __init__(self, pretrained_file_path, elem_delim=" ",
                 encoding="utf8", vocabulary=None, **kwargs):
        super().__init__(**kwargs)
        vecs = self._load_embedding_file(pretrained_file_path,
                                         elem_delim, encoding)
        self._build(vecs, vocabulary)


@register
class GloVe(CustomEmbedding):
    """GloVe-format file loader; pass a local pretrained_file_path
    (downloads need egress the runtime may not have)."""


@register
class FastText(CustomEmbedding):
    """FastText .vec loader (the count/dim header line is skipped)."""


@register
class CompositeEmbedding(_TokenEmbedding):
    """Concatenate several embeddings over one vocabulary (parity:
    embedding.py CompositeEmbedding)."""

    def __init__(self, vocabulary, token_embeddings, **kwargs):
        import mxnet_tpu as mx
        super().__init__(**kwargs)
        if not isinstance(token_embeddings, (list, tuple)):
            token_embeddings = [token_embeddings]
        self._idx_to_token = list(vocabulary.idx_to_token)
        self._token_to_idx = dict(vocabulary.token_to_idx)
        self.unknown_token = vocabulary.unknown_token
        parts = []
        for emb in token_embeddings:
            parts.append(emb.get_vecs_by_tokens(
                self._idx_to_token).asnumpy())
        mat = onp.concatenate(parts, axis=1)
        self._vec_len = mat.shape[1]
        self._idx_to_vec = mx.np.array(mat)


# -- reference submodule spellings (contrib/text/{embedding,vocab,
# utils}.py): expose the same names under the nested import paths so
# `from mxnet.contrib.text import embedding` ports verbatim --
import types as _types

embedding = _types.ModuleType(__name__ + ".embedding")
embedding.register = register
embedding.create = create
embedding.get_pretrained_file_names = globals().get(
    "get_pretrained_file_names",
    lambda name=None: {})
embedding.GloVe = GloVe
embedding.FastText = FastText
embedding.CustomEmbedding = CustomEmbedding
embedding.CompositeEmbedding = CompositeEmbedding

vocab = _types.ModuleType(__name__ + ".vocab")
vocab.Vocabulary = Vocabulary

utils = _types.ModuleType(__name__ + ".utils")
utils.count_tokens_from_str = count_tokens_from_str

import sys as _sys
for _m in (embedding, vocab, utils):
    _sys.modules[_m.__name__] = _m
del _types, _sys, _m

"""Minimal ONNX protobuf wire codec (no `onnx`/`protobuf` dependency).

The environment ships neither the onnx package nor its generated
protobufs, so this module encodes/decodes the protobuf wire format
directly. Message schemas and field numbers follow the public
onnx/onnx.proto (IR version 8): ModelProto, GraphProto, NodeProto,
AttributeProto, TensorProto, ValueInfoProto, TypeProto,
TensorShapeProto, OperatorSetIdProto.

Messages are represented as plain dicts; `encode_model`/`decode_model`
are the entry points used by mx2onnx (writer) and the test-time
evaluator (reader). Only the fields this exporter emits are
implemented — unknown fields are skipped on decode, so files from
other producers still parse for the subset we understand.
"""
from __future__ import annotations

import struct

import numpy as onp

# --- TensorProto.DataType enum (onnx.proto) ---
FLOAT = 1
UINT8 = 2
INT8 = 3
UINT16 = 4
INT16 = 5
INT32 = 6
INT64 = 7
STRING = 8
BOOL = 9
FLOAT16 = 10
DOUBLE = 11
UINT32 = 12
UINT64 = 13
BFLOAT16 = 16

_NP2ONNX = {
    "float32": FLOAT, "uint8": UINT8, "int8": INT8, "uint16": UINT16,
    "int16": INT16, "int32": INT32, "int64": INT64, "bool": BOOL,
    "float16": FLOAT16, "float64": DOUBLE, "uint32": UINT32,
    "uint64": UINT64, "bfloat16": BFLOAT16,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items() if k != "bfloat16"}
_ONNX2NP[BFLOAT16] = "float32"  # decoded as f32 (numpy has no bf16)

# --- AttributeProto.AttributeType enum ---
A_FLOAT = 1
A_INT = 2
A_STRING = 3
A_TENSOR = 4
A_FLOATS = 6
A_INTS = 7
A_STRINGS = 8


def np_dtype_to_onnx(dt) -> int:
    return _NP2ONNX[str(onp.dtype(dt)) if str(dt) != "bfloat16"
                    else "bfloat16"]


def onnx_dtype_to_np(code: int):
    return onp.dtype(_ONNX2NP[code])


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------
def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _ld(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _vint(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def _f32(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(value))


def _s(field: int, value) -> bytes:
    if isinstance(value, str):
        value = value.encode()
    return _ld(field, value)


# ---------------------------------------------------------------------------
# encoders (dict -> bytes)
# ---------------------------------------------------------------------------
def _enc_tensor(t: dict) -> bytes:
    out = bytearray()
    for d in t.get("dims", ()):
        out += _vint(1, d)
    out += _vint(2, t["data_type"])
    if "raw_data" in t:
        out += _s(9, t["raw_data"])
    if "name" in t:
        out += _s(8, t["name"])
    return bytes(out)


def _enc_attr(a: dict) -> bytes:
    out = bytearray()
    out += _s(1, a["name"])
    typ = a["type"]
    if typ == A_FLOAT:
        out += _f32(2, a["f"])
    elif typ == A_INT:
        out += _vint(3, a["i"])
    elif typ == A_STRING:
        out += _s(4, a["s"])
    elif typ == A_TENSOR:
        out += _ld(5, _enc_tensor(a["t"]))
    elif typ == A_FLOATS:
        for v in a["floats"]:
            out += _f32(7, v)
    elif typ == A_INTS:
        for v in a["ints"]:
            out += _vint(8, v)
    elif typ == A_STRINGS:
        for v in a["strings"]:
            out += _s(9, v)
    else:
        raise ValueError(f"unsupported attribute type {typ}")
    out += _vint(20, typ)
    return bytes(out)


def _enc_node(n: dict) -> bytes:
    out = bytearray()
    for i in n.get("input", ()):
        out += _s(1, i)
    for o in n.get("output", ()):
        out += _s(2, o)
    if n.get("name"):
        out += _s(3, n["name"])
    out += _s(4, n["op_type"])
    for a in n.get("attribute", ()):
        out += _ld(5, _enc_attr(a))
    return bytes(out)


def _enc_dim(d) -> bytes:
    if isinstance(d, int):
        return _vint(1, d)
    return _s(2, str(d))  # symbolic


def _enc_value_info(v: dict) -> bytes:
    shape = bytearray()
    for d in v["shape"]:
        shape += _ld(1, _enc_dim(d))
    tensor_type = _vint(1, v["elem_type"]) + _ld(2, bytes(shape))
    type_proto = _ld(1, tensor_type)
    return _s(1, v["name"]) + _ld(2, type_proto)


def _enc_graph(g: dict) -> bytes:
    out = bytearray()
    for n in g["node"]:
        out += _ld(1, _enc_node(n))
    out += _s(2, g.get("name", "mxnet_tpu"))
    for t in g.get("initializer", ()):
        out += _ld(5, _enc_tensor(t))
    for v in g.get("input", ()):
        out += _ld(11, _enc_value_info(v))
    for v in g.get("output", ()):
        out += _ld(12, _enc_value_info(v))
    return bytes(out)


def encode_model(graph: dict, opset_version=13, producer="mxnet_tpu",
                 ir_version=8) -> bytes:
    out = bytearray()
    out += _vint(1, ir_version)
    out += _s(2, producer)
    out += _s(3, "3.0")
    out += _ld(7, _enc_graph(graph))
    opset = _s(1, "") + _vint(2, opset_version)
    out += _ld(8, opset)
    return bytes(out)


# ---------------------------------------------------------------------------
# decoders (bytes -> dict)
# ---------------------------------------------------------------------------
def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _fields(buf):
    """Yield (field_number, wire_type, value) skipping nothing."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _read_varint(buf, pos)
        elif wire == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val


def _dec_tensor(buf) -> dict:
    t = {"dims": []}
    for f, w, v in _fields(buf):
        if f == 1:
            t["dims"].append(v)
        elif f == 2:
            t["data_type"] = v
        elif f == 8:
            t["name"] = v.decode()
        elif f == 9:
            t["raw_data"] = bytes(v)
        elif f == 4 and w == 5:  # float_data (unpacked)
            t.setdefault("float_data", []).append(
                struct.unpack("<f", v)[0])
    return t


def _dec_attr(buf) -> dict:
    a = {}
    for f, w, v in _fields(buf):
        if f == 1:
            a["name"] = v.decode()
        elif f == 2:
            a["f"] = struct.unpack("<f", v)[0]
        elif f == 3:
            a["i"] = v
        elif f == 4:
            a["s"] = bytes(v)
        elif f == 5:
            a["t"] = _dec_tensor(v)
        elif f == 7:
            a.setdefault("floats", []).append(struct.unpack("<f", v)[0])
        elif f == 8:
            a.setdefault("ints", []).append(v)
        elif f == 9:
            a.setdefault("strings", []).append(bytes(v))
        elif f == 20:
            a["type"] = v
    return a


def _dec_node(buf) -> dict:
    n = {"input": [], "output": [], "attribute": []}
    for f, w, v in _fields(buf):
        if f == 1:
            n["input"].append(v.decode())
        elif f == 2:
            n["output"].append(v.decode())
        elif f == 3:
            n["name"] = v.decode()
        elif f == 4:
            n["op_type"] = v.decode()
        elif f == 5:
            n["attribute"].append(_dec_attr(v))
    return n


def _dec_value_info(buf) -> dict:
    out = {"name": None, "elem_type": None, "shape": []}
    for f, w, v in _fields(buf):
        if f == 1:
            out["name"] = v.decode()
        elif f == 2:
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:  # tensor_type
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 1:
                            out["elem_type"] = v3
                        elif f3 == 2:
                            for f4, _w4, v4 in _fields(v3):
                                if f4 == 1:
                                    dim = {"value": None}
                                    for f5, _w5, v5 in _fields(v4):
                                        if f5 == 1:
                                            dim["value"] = v5
                                        elif f5 == 2:
                                            dim["value"] = v5.decode()
                                    out["shape"].append(dim["value"])
    return out


def _dec_graph(buf) -> dict:
    g = {"node": [], "initializer": [], "input": [], "output": []}
    for f, w, v in _fields(buf):
        if f == 1:
            g["node"].append(_dec_node(v))
        elif f == 2:
            g["name"] = v.decode()
        elif f == 5:
            g["initializer"].append(_dec_tensor(v))
        elif f == 11:
            g["input"].append(_dec_value_info(v))
        elif f == 12:
            g["output"].append(_dec_value_info(v))
    return g


def decode_model(buf: bytes) -> dict:
    m = {"opset": None, "graph": None}
    for f, w, v in _fields(buf):
        if f == 1:
            m["ir_version"] = v
        elif f == 2:
            m["producer_name"] = v.decode()
        elif f == 7:
            m["graph"] = _dec_graph(v)
        elif f == 8:
            for f2, _w2, v2 in _fields(v):
                if f2 == 2:
                    m["opset"] = v2
    return m


def tensor_to_numpy(t: dict) -> onp.ndarray:
    dt = onnx_dtype_to_np(t["data_type"])
    if "raw_data" in t:
        if t["data_type"] == BFLOAT16:
            # bf16 raw: upper 16 bits of f32
            raw = onp.frombuffer(t["raw_data"], dtype=onp.uint16)
            as32 = raw.astype(onp.uint32) << 16
            arr = as32.view(onp.float32)
        else:
            arr = onp.frombuffer(t["raw_data"], dtype=dt)
        return arr.reshape(t["dims"]).copy()
    if "float_data" in t:
        return onp.asarray(t["float_data"], dtype=onp.float32) \
            .reshape(t["dims"])
    return onp.zeros(t["dims"], dtype=dt)


def numpy_to_tensor(arr, name: str) -> dict:
    dims = list(arr.shape)  # BEFORE ascontiguousarray: it promotes
    sdt = str(arr.dtype)    # 0-d scalars to shape (1,)
    if sdt == "bfloat16":
        as32 = onp.asarray(arr, dtype=onp.float32)
        raw = (as32.view(onp.uint32) >> 16).astype(onp.uint16).tobytes()
        code = BFLOAT16
    else:
        arr = onp.ascontiguousarray(arr)
        raw = arr.tobytes()
        code = np_dtype_to_onnx(arr.dtype)
    return {"dims": dims, "data_type": code,
            "raw_data": raw, "name": name}

"""mx.contrib.onnx — ONNX export/import (parity: contrib/onnx/).

`export_model(net, input_shapes, path)` writes an opset-13 ONNX file
from the traced graph; `import_model(path)` loads one back as a
callable. No external onnx/protobuf dependency — the wire format is
encoded directly (see proto.py).
"""
from .mx2onnx import export_model  # noqa: F401
from .runtime import import_model, OnnxGraph  # noqa: F401

"""Minimal ONNX graph evaluator + importer.

Two jobs (parity: contrib/onnx/onnx2mx — the reference imports ONNX
back into its own graph IR):
- `OnnxGraph.run(feeds)` evaluates a decoded ONNX graph with
  NumPy/lax semantics reconstructed from the ONNX spec — an
  independent execution path used to validate exported files (the
  environment ships no onnxruntime).
- `import_model(path)` wraps that evaluator as a callable returning
  NDArrays, giving ONNX *import* capability.

Covers the op set mx2onnx emits (opset 13): Conv, MaxPool,
AveragePool, MatMul, elementwise/unary math, Where, comparisons,
Reshape, Expand, Transpose, Concat, Slice, Pad, Cast, Reduce*,
ArgMax, Identity.
"""
from __future__ import annotations

import numpy as onp

from . import proto


def _to_np(x):
    return onp.asarray(x)


class OnnxGraph:
    def __init__(self, model: dict):
        self.graph = model["graph"]
        self.opset = model.get("opset")
        self.initializers = {
            t["name"]: proto.tensor_to_numpy(t)
            for t in self.graph["initializer"]}
        self.input_names = [v["name"] for v in self.graph["input"]
                            if v["name"] not in self.initializers]
        self.output_names = [v["name"] for v in self.graph["output"]]

    @classmethod
    def load(cls, path):
        with open(path, "rb") as f:
            return cls(proto.decode_model(f.read()))

    # -- op semantics ---------------------------------------------------
    @staticmethod
    def _attrs(node):
        out = {}
        for a in node["attribute"]:
            t = a["type"]
            if t == proto.A_INT:
                out[a["name"]] = a["i"]
            elif t == proto.A_FLOAT:
                out[a["name"]] = a["f"]
            elif t == proto.A_INTS:
                out[a["name"]] = list(a["ints"])
            elif t == proto.A_STRING:
                out[a["name"]] = a["s"].decode()
            elif t == proto.A_TENSOR:
                out[a["name"]] = proto.tensor_to_numpy(a["t"])
        return out

    def _eval_node(self, node, env):
        import jax.numpy as jnp
        from jax import lax
        op = node["op_type"]
        ins = [env[i] for i in node["input"]]
        at = self._attrs(node)

        def conv():
            x, w = ins[0], ins[1]
            strides = at.get("strides", [1] * (x.ndim - 2))
            pads = at.get("pads", [0] * 2 * (x.ndim - 2))
            dil = at.get("dilations", [1] * (x.ndim - 2))
            g = at.get("group", 1)
            nsp = x.ndim - 2
            pad_pairs = [(pads[i], pads[i + nsp]) for i in range(nsp)]
            y = lax.conv_general_dilated(
                jnp.asarray(x, jnp.float32), jnp.asarray(w, jnp.float32),
                strides, pad_pairs, rhs_dilation=dil,
                feature_group_count=g)
            r = onp.asarray(y)
            if len(ins) == 3:
                r = r + ins[2].reshape((1, -1) + (1,) * nsp)
            return r

        def pool(kind):
            x = ins[0]
            nsp = x.ndim - 2
            k = at["kernel_shape"]
            strides = at.get("strides", [1] * nsp)
            pads = at.get("pads", [0] * 2 * nsp)
            pad_pairs = [(0, 0), (0, 0)] + \
                [(pads[i], pads[i + nsp]) for i in range(nsp)]
            window = (1, 1) + tuple(k)
            stride = (1, 1) + tuple(strides)
            if kind == "max":
                init = -onp.inf
                y = lax.reduce_window(jnp.asarray(x, jnp.float32), init,
                                      lax.max, window, stride, pad_pairs)
                return onp.asarray(y)
            y = lax.reduce_window(jnp.asarray(x, jnp.float32), 0.0,
                                  lax.add, window, stride, pad_pairs)
            if at.get("count_include_pad", 0):
                denom = float(onp.prod(k))
                return onp.asarray(y) / denom
            ones = jnp.ones_like(jnp.asarray(x, jnp.float32))
            denom = lax.reduce_window(ones, 0.0, lax.add, window,
                                      stride, pad_pairs)
            return onp.asarray(y / denom)

        table = {
            "Add": lambda: ins[0] + ins[1],
            "Sub": lambda: ins[0] - ins[1],
            "Mul": lambda: ins[0] * ins[1],
            "Div": lambda: ins[0] / ins[1],
            "Pow": lambda: onp.power(ins[0], ins[1]),
            "Max": lambda: onp.maximum(ins[0], ins[1]),
            "Min": lambda: onp.minimum(ins[0], ins[1]),
            "Mod": lambda: onp.mod(ins[0], ins[1]),
            "MatMul": lambda: onp.matmul(ins[0], ins[1]),
            "Gemm": lambda: self._gemm(ins, at),
            "Conv": conv,
            "MaxPool": lambda: pool("max"),
            "AveragePool": lambda: pool("avg"),
            "Relu": lambda: onp.maximum(ins[0], 0),
            "Sigmoid": lambda: 1.0 / (1.0 + onp.exp(-ins[0])),
            "Tanh": lambda: onp.tanh(ins[0]),
            "Exp": lambda: onp.exp(ins[0]),
            "Log": lambda: onp.log(ins[0]),
            "Sqrt": lambda: onp.sqrt(ins[0]),
            "Reciprocal": lambda: 1.0 / ins[0],
            "Neg": lambda: -ins[0],
            "Abs": lambda: onp.abs(ins[0]),
            "Sign": lambda: onp.sign(ins[0]),
            "Floor": lambda: onp.floor(ins[0]),
            "Ceil": lambda: onp.ceil(ins[0]),
            "Round": lambda: onp.round(ins[0]),
            "Erf": lambda: self._erf(ins[0]),
            "Sin": lambda: onp.sin(ins[0]),
            "Cos": lambda: onp.cos(ins[0]),
            "Tan": lambda: onp.tan(ins[0]),
            "Atan": lambda: onp.arctan(ins[0]),
            "Asin": lambda: onp.arcsin(ins[0]),
            "Acos": lambda: onp.arccos(ins[0]),
            "Sinh": lambda: onp.sinh(ins[0]),
            "Cosh": lambda: onp.cosh(ins[0]),
            "Identity": lambda: ins[0],
            "Cast": lambda: ins[0].astype(
                proto.onnx_dtype_to_np(at["to"])),
            "Reshape": lambda: ins[0].reshape(
                [int(v) for v in ins[1]]),
            "Expand": lambda: onp.broadcast_to(
                ins[0], [int(v) for v in ins[1]]).copy(),
            "Transpose": lambda: onp.transpose(ins[0], at["perm"]),
            "Concat": lambda: onp.concatenate(ins, axis=at["axis"]),
            "Where": lambda: onp.where(ins[0].astype(bool), ins[1],
                                       ins[2]),
            "Greater": lambda: ins[0] > ins[1],
            "Less": lambda: ins[0] < ins[1],
            "GreaterOrEqual": lambda: ins[0] >= ins[1],
            "LessOrEqual": lambda: ins[0] <= ins[1],
            "Equal": lambda: ins[0] == ins[1],
            "Not": lambda: ~ins[0].astype(bool),
            "IsInf": lambda: onp.isinf(ins[0]),
            "IsNaN": lambda: onp.isnan(ins[0]),
            "And": lambda: ins[0].astype(bool) & ins[1].astype(bool),
            "Or": lambda: ins[0].astype(bool) | ins[1].astype(bool),
            "Xor": lambda: ins[0].astype(bool) ^ ins[1].astype(bool),
            "ReduceSum": lambda: onp.sum(
                ins[0], axis=tuple(int(v) for v in ins[1])
                if len(ins) > 1 else None,
                keepdims=bool(at.get("keepdims", 1))),
            "ReduceMax": lambda: onp.max(
                ins[0], axis=tuple(at["axes"]),
                keepdims=bool(at.get("keepdims", 1))),
            "ReduceMin": lambda: onp.min(
                ins[0], axis=tuple(at["axes"]),
                keepdims=bool(at.get("keepdims", 1))),
            "ReduceMean": lambda: onp.mean(
                ins[0], axis=tuple(at["axes"]),
                keepdims=bool(at.get("keepdims", 1))),
            "ArgMax": lambda: onp.argmax(
                ins[0], axis=at.get("axis", 0)),
            "Softmax": lambda: self._softmax(ins[0],
                                             at.get("axis", -1)),
            "Pad": lambda: self._pad(ins),
            "Slice": lambda: self._slice(ins),
            "Flatten": lambda: ins[0].reshape(ins[0].shape[0], -1),
            "ArgMin": lambda: onp.argmin(
                ins[0], axis=at.get("axis", 0)),
            "Gather": lambda: onp.take(
                ins[0], ins[1].astype(onp.int64),
                axis=at.get("axis", 0)),
            "GatherElements": lambda: onp.take_along_axis(
                ins[0], ins[1].astype(onp.int64),
                axis=at.get("axis", 0)),
            "Unsqueeze": lambda: onp.expand_dims(
                ins[0], tuple(int(v) for v in ins[1])),
            "Squeeze": lambda: onp.squeeze(
                ins[0], tuple(int(v) for v in ins[1]))
            if len(ins) > 1 else onp.squeeze(ins[0]),
            "CumSum": lambda: self._cumsum(ins, at),
            "Split": lambda: tuple(
                onp.split(ins[0],
                          onp.cumsum([int(v) for v in ins[1]])[:-1],
                          axis=at.get("axis", 0))),
            "TopK": lambda: self._topk(ins, at),
        }
        if op not in table:
            raise NotImplementedError(f"evaluator: ONNX op {op!r}")
        return table[op]()

    @staticmethod
    def _gemm(ins, at):
        a, b = ins[0], ins[1]
        if at.get("transA", 0):
            a = a.T
        if at.get("transB", 0):
            b = b.T
        y = at.get("alpha", 1.0) * (a @ b)
        if len(ins) == 3:
            y = y + at.get("beta", 1.0) * ins[2]
        return y

    @staticmethod
    def _erf(x):
        from math import erf
        return onp.vectorize(erf)(x).astype(onp.asarray(x).dtype)

    @staticmethod
    def _softmax(x, axis):
        e = onp.exp(x - onp.max(x, axis=axis, keepdims=True))
        return e / e.sum(axis=axis, keepdims=True)

    @staticmethod
    def _pad(ins):
        x, pads = ins[0], [int(v) for v in ins[1]]
        nd = x.ndim
        pairs = [(pads[i], pads[i + nd]) for i in range(nd)]
        cval = float(ins[2]) if len(ins) > 2 else 0.0
        return onp.pad(x, pairs, constant_values=cval)

    @staticmethod
    def _cumsum(ins, at):
        ax = int(onp.asarray(ins[1]))
        if at.get("reverse"):
            flip = onp.flip(ins[0], axis=ax)
            return onp.flip(onp.cumsum(flip, axis=ax), axis=ax)
        return onp.cumsum(ins[0], axis=ax)

    @staticmethod
    def _topk(ins, at):
        x = ins[0]
        k = int(onp.asarray(ins[1]).reshape(-1)[0])
        axis = at.get("axis", -1)
        largest = at.get("largest", 1)
        order = onp.argsort(-x if largest else x, axis=axis,
                            kind="stable")
        idx = onp.take(order, range(k), axis=axis)
        vals = onp.take_along_axis(x, idx, axis=axis)
        return vals, idx.astype(onp.int64)

    @staticmethod
    def _slice(ins):
        x = ins[0]
        starts = [int(v) for v in ins[1]]
        ends = [int(v) for v in ins[2]]
        axes = [int(v) for v in ins[3]] if len(ins) > 3 \
            else list(range(len(starts)))
        steps = [int(v) for v in ins[4]] if len(ins) > 4 \
            else [1] * len(starts)
        sl = [slice(None)] * x.ndim
        for ax, s, e, st in zip(axes, starts, ends, steps):
            lo = s if s >= -x.shape[ax] else None
            hi = e if -x.shape[ax] <= e < 2 ** 31 - 1 else \
                (None if st > 0 or e < -(2 ** 30) else e)
            if st < 0 and e <= -(2 ** 30):
                hi = None
            sl[ax] = slice(lo, hi, st)
        return x[tuple(sl)]

    def run(self, feeds: dict):
        env = dict(self.initializers)
        for k, v in feeds.items():
            env[k] = _to_np(v)
        for node in self.graph["node"]:
            outs = node["output"]
            res = self._eval_node(node, env)
            if isinstance(res, tuple):
                for name, val in zip(outs, res):
                    env[name] = _to_np(val)
            else:
                env[outs[0]] = _to_np(res)
        return [env[n] for n in self.output_names]


def import_model(path):
    """Load an ONNX file as a callable over NDArrays (parity:
    contrib/onnx/onnx2mx import_model — the reference rebuilds a
    Symbol; here the decoded graph is evaluated directly)."""
    g = OnnxGraph.load(path)

    def fn(*args):
        import mxnet_tpu as mx
        feeds = {name: (a.asnumpy() if hasattr(a, "asnumpy") else a)
                 for name, a in zip(g.input_names, args)}
        outs = [mx.np.array(o) if o.dtype != onp.int64
                else mx.np.array(o.astype(onp.int32))
                for o in g.run(feeds)]
        return outs[0] if len(outs) == 1 else tuple(outs)

    fn.graph = g
    return fn

"""mx2onnx — export a HybridBlock's traced graph to ONNX.

Parity with the reference's ONNX exporter
(python/mxnet/contrib/onnx/mx2onnx/export_onnx.py MXNetGraph, which
walks the nnvm symbol graph emitting per-op translations). TPU-first
redesign: the source of truth here is the SAME traced jaxpr the
hybridize/StableHLO-export path uses — each jaxpr equation lowers to
ONNX nodes (opset 13). Decomposed ops (batch-norm as mul/add chains,
softmax as exp/sub/div) export as primitive chains, which is valid
ONNX and loads anywhere.

Constant folding: any equation whose inputs are all initializers or
literals is evaluated at export time and becomes an initializer, so
PRNG plumbing and eps-broadcast chains never reach the file.
"""
from __future__ import annotations

import numpy as onp
import jax
import jax.numpy as jnp

from . import proto

__all__ = ["export_model"]


class _Ctx:
    def __init__(self):
        self.nodes = []
        self.initializers = {}     # name -> numpy array
        self.const_vals = {}       # onnx name -> numpy value (foldable)
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def add_node(self, op_type, inputs, outputs, attrs=None, name=None):
        self.nodes.append({
            "op_type": op_type, "input": list(inputs),
            "output": list(outputs),
            "name": name or self.fresh(op_type.lower()),
            "attribute": attrs or []})

    def add_const(self, arr, hint="const"):
        arr = onp.asarray(arr)
        name = self.fresh(hint)
        self.initializers[name] = arr
        self.const_vals[name] = arr
        return name

    def name_of(self, v, env):
        """Resolve a jaxpr Var to an ONNX name in the given scope.

        Scoping matters: jax CACHES sub-jaxprs, so the same inner
        jaxpr (same Var objects) can be inlined at several call sites;
        a global Var->name map would alias the call sites' tensors
        (SSA violation). Each inlined instance gets its own env."""
        from jax._src.core import Literal
        if isinstance(v, Literal):
            return self.add_const(onp.asarray(v.val), "lit")
        if v not in env:
            env[v] = self.fresh("v")
        return env[v]


def _attr_i(name, v):
    return {"name": name, "type": proto.A_INT, "i": int(v)}


def _attr_f(name, v):
    return {"name": name, "type": proto.A_FLOAT, "f": float(v)}


def _attr_ints(name, vs):
    return {"name": name, "type": proto.A_INTS,
            "ints": [int(x) for x in vs]}


def _attr_s(name, v):
    return {"name": name, "type": proto.A_STRING, "s": v}


def _shape_const(ctx, shape):
    return ctx.add_const(onp.asarray(shape, dtype=onp.int64), "shape")


def _transpose(ctx, inp, perm, hint="tr"):
    out = ctx.fresh(hint)
    ctx.add_node("Transpose", [inp], [out], [_attr_ints("perm", perm)])
    return out


def _reshape(ctx, inp, shape, hint="rs"):
    out = ctx.fresh(hint)
    ctx.add_node("Reshape", [inp, _shape_const(ctx, shape)], [out])
    return out


_ELEMWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "rem": "Mod",
}
_UNARY = {
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "neg": "Neg", "abs": "Abs", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil", "round": "Round", "erf": "Erf",
    "sin": "Sin", "cos": "Cos", "tan": "Tan", "asin": "Asin",
    "acos": "Acos", "atan": "Atan", "sinh": "Sinh", "cosh": "Cosh",
    "not": "Not", "stop_gradient": "Identity",
    "copy": "Identity",
}
_COMPARE = {
    "gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
    "le": "LessOrEqual", "eq": "Equal", "ne": "Equal",  # ne: Equal+Not
    "and": "And", "or": "Or", "xor": "Xor",
}


def _conv_eqn(ctx, eqn, ins, outs):
    p = eqn.params
    dn = p["dimension_numbers"]
    nsp = len(p["window_strides"])
    # normalize operands to NCHW / OIHW via Transpose nodes; jax specs
    # are (batch, feature, *spatial) as axis indices into the operand
    lhs_perm = list(dn.lhs_spec)
    rhs_perm = list(dn.rhs_spec)
    out_perm = list(dn.out_spec)
    x = ins[0]
    w = ins[1]
    if lhs_perm != list(range(nsp + 2)):
        x = _transpose(ctx, x, lhs_perm, "nchw")
    if rhs_perm != list(range(nsp + 2)):
        w = _transpose(ctx, w, rhs_perm, "oihw")
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("transposed conv export not supported")
    pads = [pp[0] for pp in p["padding"]] + [pp[1] for pp in p["padding"]]
    attrs = [
        _attr_ints("strides", p["window_strides"]),
        _attr_ints("pads", pads),
        _attr_ints("dilations", p["rhs_dilation"]),
        _attr_i("group", p["feature_group_count"]),
    ]
    inv_out = [out_perm.index(i) for i in range(nsp + 2)]
    if out_perm != list(range(nsp + 2)):
        tmp = ctx.fresh("conv")
        ctx.add_node("Conv", [x, w], [tmp], attrs)
        ctx.add_node("Transpose", [tmp], [outs[0]],
                     [_attr_ints("perm", inv_out)])
    else:
        ctx.add_node("Conv", [x, w], [outs[0]], attrs)


def _dot_eqn(ctx, eqn, ins, outs, in_avals):
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    la, ra = in_avals
    lnd, rnd = len(la.shape), len(ra.shape)
    if not lb and not rb and len(lc) == 1 and len(rc) == 1:
        a, b = ins
        if lc[0] != lnd - 1:
            raise NotImplementedError("dot_general lhs contraction "
                                      f"on axis {lc[0]}")
        if rc[0] == rnd - 2:
            pass  # (…,K) x (K,N) — MatMul directly
        elif rc[0] == rnd - 1:
            b = _transpose(ctx, b, list(range(rnd - 2)) + [rnd - 1, rnd - 2],
                           "wT")
        else:
            raise NotImplementedError("dot_general rhs contraction "
                                      f"on axis {rc[0]}")
        ctx.add_node("MatMul", [a, b], [outs[0]])
        return
    # batched matmul: batch dims must be the leading dims in order
    if list(lb) == list(range(len(lb))) and list(rb) == list(range(len(rb))) \
            and len(lc) == 1 and len(rc) == 1 \
            and lc[0] == lnd - 1 and rc[0] == rnd - 2:
        ctx.add_node("MatMul", ins, [outs[0]])
        return
    raise NotImplementedError(
        f"dot_general {eqn.params['dimension_numbers']}")


def _reduce_window_eqn(ctx, eqn, ins, outs, kind):
    p = eqn.params
    dims = p["window_dimensions"]
    strides = p["window_strides"]
    padding = p["padding"]
    nd = len(dims)
    # pooling must act on trailing-or-marked spatial dims with
    # batch/channel windows of 1
    spatial = [i for i in range(nd) if dims[i] != 1 or strides[i] != 1
               or padding[i] != (0, 0)]
    if not spatial:
        spatial = [nd - 2, nd - 1]
    if any(d != 1 for i, d in enumerate(dims) if i not in spatial):
        raise NotImplementedError("pooling over non-spatial dims")
    perm = [i for i in range(nd) if i not in spatial] + spatial
    needs_perm = perm != list(range(nd))
    x = ins[0]
    if needs_perm:
        x = _transpose(ctx, x, perm, "pool_in")
    kshape = [dims[i] for i in spatial]
    kstride = [strides[i] for i in spatial]
    pads = [padding[i][0] for i in spatial] + \
        [padding[i][1] for i in spatial]
    attrs = [_attr_ints("kernel_shape", kshape),
             _attr_ints("strides", kstride),
             _attr_ints("pads", pads)]
    op = "MaxPool" if kind == "max" else "AveragePool"
    if kind == "sum":
        attrs.append(_attr_i("count_include_pad", 1))
    pooled = ctx.fresh("pool")
    ctx.add_node(op, [x], [pooled], attrs)
    if kind == "sum":
        # reduce_window-sum = AveragePool * window_size
        k = float(onp.prod(kshape))
        scaled = ctx.fresh("pool_sum")
        ctx.add_node("Mul", [pooled, ctx.add_const(
            onp.asarray(k, dtype=onp.float32))], [scaled])
        pooled = scaled
    if needs_perm:
        inv = [perm.index(i) for i in range(nd)]
        ctx.add_node("Transpose", [pooled], [outs[0]],
                     [_attr_ints("perm", inv)])
    else:
        ctx.add_node("Identity", [pooled], [outs[0]])


def _broadcast_eqn(ctx, eqn, ins, outs, in_avals, out_aval):
    bdims = eqn.params["broadcast_dimensions"]
    tgt = list(out_aval.shape)
    src = list(in_avals[0].shape)
    # reshape to rank of target with 1s, then Expand
    interim = [1] * len(tgt)
    for i, bd in enumerate(bdims):
        interim[bd] = src[i]
    x = ins[0]
    if interim != src or len(interim) != len(src):
        x = _reshape(ctx, x, interim, "bcast_rs")
    ctx.add_node("Expand", [x, _shape_const(ctx, tgt)], [outs[0]])


def _convert_eqn(ctx, eqn, ins, outs):
    tgt = proto.np_dtype_to_onnx(eqn.params["new_dtype"])
    ctx.add_node("Cast", [ins[0]], [outs[0]], [_attr_i("to", tgt)])


def _translate_eqn(ctx, eqn, env):
    prim = eqn.primitive.name
    ins = [ctx.name_of(v, env) for v in eqn.invars]
    outs = [ctx.name_of(v, env) for v in eqn.outvars]
    in_avals = [v.aval for v in eqn.invars]
    if prim in _ELEMWISE:
        ctx.add_node(_ELEMWISE[prim], ins, outs)
    elif prim in _UNARY:
        ctx.add_node(_UNARY[prim], ins, outs)
    elif prim in _COMPARE:
        if prim == "ne":
            eq = ctx.fresh("eq")
            ctx.add_node("Equal", ins, [eq])
            ctx.add_node("Not", [eq], outs)
        else:
            ctx.add_node(_COMPARE[prim], ins, outs)
    elif prim == "square":
        ctx.add_node("Mul", [ins[0], ins[0]], outs)
    elif prim == "erfc":
        e = ctx.fresh("erf")
        ctx.add_node("Erf", ins, [e])
        ctx.add_node("Sub", [ctx.add_const(
            onp.asarray(1.0, onp.float32)), e], outs)
    elif prim == "log1p":
        a = ctx.fresh("lp1")
        ctx.add_node("Add", [ins[0], ctx.add_const(
            onp.asarray(1.0, onp.float32))], [a])
        ctx.add_node("Log", [a], outs)
    elif prim == "expm1":
        e = ctx.fresh("em1")
        ctx.add_node("Exp", ins, [e])
        ctx.add_node("Sub", [e, ctx.add_const(
            onp.asarray(1.0, onp.float32))], outs)
    elif prim == "rsqrt":
        s = ctx.fresh("sqrt")
        ctx.add_node("Sqrt", ins, [s])
        ctx.add_node("Reciprocal", [s], outs)
    elif prim == "atan2":
        # atan2(y, x) = atan(y/x) + quadrant correction:
        #   x < 0 -> +pi when y >= 0, -pi when y < 0
        y, x = ins
        d = ctx.fresh("at2_div")
        ctx.add_node("Div", [y, x], [d])
        a = ctx.fresh("at2_atan")
        ctx.add_node("Atan", [d], [a])
        xneg = ctx.fresh("at2_xneg")
        ctx.add_node("Less", [x, ctx.add_const(
            onp.asarray(0.0, onp.float32))], [xneg])
        ypos = ctx.fresh("at2_ypos")
        ctx.add_node("GreaterOrEqual", [y, ctx.add_const(
            onp.asarray(0.0, onp.float32))], [ypos])
        pi = ctx.add_const(onp.asarray(onp.pi, onp.float32))
        npi = ctx.add_const(onp.asarray(-onp.pi, onp.float32))
        corr_sign = ctx.fresh("at2_corrs")
        ctx.add_node("Where", [ypos, pi, npi], [corr_sign])
        corr = ctx.fresh("at2_corr")
        ctx.add_node("Where", [xneg, corr_sign, ctx.add_const(
            onp.asarray(0.0, onp.float32))], [corr])
        ctx.add_node("Add", [a, corr], outs)
    elif prim == "is_finite":
        # Not(Or(IsInf(x), IsNaN(x)))
        isinf = ctx.fresh("isinf")
        ctx.add_node("IsInf", [ins[0]], [isinf])
        isnan = ctx.fresh("isnan")
        ctx.add_node("IsNaN", [ins[0]], [isnan])
        bad = ctx.fresh("nonfinite")
        ctx.add_node("Or", [isinf, isnan], [bad])
        ctx.add_node("Not", [bad], outs)
    elif prim == "integer_pow":
        y = eqn.params["y"]
        if y == 2:
            ctx.add_node("Mul", [ins[0], ins[0]], outs)
        else:
            ctx.add_node("Pow", [ins[0], ctx.add_const(
                onp.asarray(float(y), onp.float32))], outs)
    elif prim == "conv_general_dilated":
        _conv_eqn(ctx, eqn, ins, outs)
    elif prim == "dot_general":
        _dot_eqn(ctx, eqn, ins, outs, in_avals)
    elif prim == "reduce_window_max":
        _reduce_window_eqn(ctx, eqn, ins, outs, "max")
    elif prim == "reduce_window_sum":
        _reduce_window_eqn(ctx, eqn, ins, outs, "sum")
    elif prim == "reduce_sum":
        ctx.add_node("ReduceSum",
                     [ins[0], ctx.add_const(onp.asarray(
                         eqn.params["axes"], onp.int64), "axes")],
                     outs, [_attr_i("keepdims", 0)])
    elif prim in ("reduce_max", "reduce_min"):
        op = "ReduceMax" if prim == "reduce_max" else "ReduceMin"
        ctx.add_node(op, ins, outs,
                     [_attr_ints("axes", eqn.params["axes"]),
                      _attr_i("keepdims", 0)])
    elif prim == "broadcast_in_dim":
        _broadcast_eqn(ctx, eqn, ins, outs, in_avals,
                       eqn.outvars[0].aval)
    elif prim == "reshape":
        ctx.add_node("Reshape",
                     [ins[0], _shape_const(ctx,
                                           eqn.outvars[0].aval.shape)],
                     outs)
    elif prim == "squeeze":
        ctx.add_node("Reshape",
                     [ins[0], _shape_const(ctx,
                                           eqn.outvars[0].aval.shape)],
                     outs)
    elif prim == "expand_dims":
        ctx.add_node("Reshape",
                     [ins[0], _shape_const(ctx,
                                           eqn.outvars[0].aval.shape)],
                     outs)
    elif prim == "transpose":
        ctx.add_node("Transpose", ins, outs,
                     [_attr_ints("perm", eqn.params["permutation"])])
    elif prim == "concatenate":
        ctx.add_node("Concat", ins, outs,
                     [_attr_i("axis", eqn.params["dimension"])])
    elif prim == "slice":
        p = eqn.params
        strides = p["strides"] or [1] * len(p["start_indices"])
        ctx.add_node("Slice", [
            ins[0],
            ctx.add_const(onp.asarray(p["start_indices"], onp.int64)),
            ctx.add_const(onp.asarray(p["limit_indices"], onp.int64)),
            ctx.add_const(onp.asarray(range(len(strides)), onp.int64)),
            ctx.add_const(onp.asarray(strides, onp.int64))], outs)
    elif prim == "rev":
        # reverse via Slice with negative steps
        nd = len(in_avals[0].shape)
        dims = eqn.params["dimensions"]
        starts = [-1 if i in dims else 0 for i in range(nd)]
        ends = [-(2 ** 31) if i in dims else 2 ** 31 - 1
                for i in range(nd)]
        steps = [-1 if i in dims else 1 for i in range(nd)]
        ctx.add_node("Slice", [
            ins[0],
            ctx.add_const(onp.asarray(starts, onp.int64)),
            ctx.add_const(onp.asarray(ends, onp.int64)),
            ctx.add_const(onp.asarray(range(nd), onp.int64)),
            ctx.add_const(onp.asarray(steps, onp.int64))], outs)
    elif prim == "select_n":
        # select_n(pred, case0, case1): case1 where pred else case0
        if len(ins) != 3:
            raise NotImplementedError("select_n with >2 cases")
        ctx.add_node("Where", [ins[0], ins[2], ins[1]], outs)
    elif prim == "pad":
        p = eqn.params
        if any(i != 0 for _, _, i in p["padding_config"]):
            raise NotImplementedError("interior padding")
        lo = [c[0] for c in p["padding_config"]]
        hi = [c[1] for c in p["padding_config"]]
        ctx.add_node("Pad", [
            ins[0],
            ctx.add_const(onp.asarray(lo + hi, onp.int64)),
            ins[1]], outs)
    elif prim == "convert_element_type":
        _convert_eqn(ctx, eqn, ins, outs)
    elif prim == "argmax":
        ctx.add_node("ArgMax", ins, outs,
                     [_attr_i("axis", eqn.params["axes"][0]),
                      _attr_i("keepdims", 0)])
    elif prim == "argmin":
        ctx.add_node("ArgMin", ins, outs,
                     [_attr_i("axis", eqn.params["axes"][0]),
                      _attr_i("keepdims", 0)])
    elif prim == "clamp":
        lo, x, hi = ins
        m = ctx.fresh("clamp_lo")
        ctx.add_node("Max", [x, lo], [m])
        ctx.add_node("Min", [m, hi], outs)
    elif prim == "cumsum":
        ax = ctx.add_const(onp.asarray(eqn.params["axis"], onp.int64))
        ctx.add_node("CumSum", [ins[0], ax], outs,
                     [_attr_i("reverse",
                              1 if eqn.params.get("reverse") else 0)])
    elif prim == "split":
        ctx.add_node(
            "Split",
            [ins[0], ctx.add_const(
                onp.asarray(eqn.params["sizes"], onp.int64), "split")],
            outs, [_attr_i("axis", eqn.params["axis"])])
    elif prim == "scan":
        _scan_eqn(ctx, eqn, ins, outs, env)
    elif prim == "while":
        raise NotImplementedError(
            "lax.while_loop cannot be unrolled for ONNX (dynamic trip "
            "count); use lax.scan / fused RNN layers instead")
    elif prim == "sort":
        _sort_eqn(ctx, eqn, ins, outs, in_avals)
    elif prim == "top_k":
        _topk_eqn(ctx, eqn, ins, outs, in_avals)
    elif prim == "gather":
        _gather_eqn(ctx, eqn, ins, outs, in_avals)
    elif prim == "dynamic_slice":
        _dynamic_slice_eqn(ctx, eqn, ins, outs)
    elif prim in ("device_put", "copy_p", "sharding_constraint"):
        ctx.add_node("Identity", ins, outs)
    else:
        raise NotImplementedError(
            f"no ONNX translation for jaxpr primitive {prim!r}")


def _unsqueeze0(ctx, name, hint="us"):
    u = ctx.fresh(hint)
    ctx.add_node("Unsqueeze",
                 [name, ctx.add_const(onp.asarray([0], onp.int64))], [u])
    return u


def _scan_eqn(ctx, eqn, ins, outs, env):
    """lax.scan → unrolled body (the fused RNN/LSTM/GRU path).

    The body jaxpr is inlined `length` times with Gather-sliced xs;
    carries chain through, ys are Unsqueeze+Concat-stacked. Model size
    grows linearly with sequence length — the trade for static ONNX
    graphs (the reference exports cuDNN RNN as ONNX LSTM nodes;
    here any scanned cell body exports, not just the three stock
    cells)."""
    p = eqn.params
    T = p["length"]
    nc = p["num_consts"]
    ncar = p["num_carry"]
    closed = p["jaxpr"]
    body = closed.jaxpr
    consts_in = ins[:nc]
    carry = list(ins[nc:nc + ncar])
    xs = ins[nc + ncar:]
    n_ys = len(body.outvars) - ncar
    ys = [[] for _ in range(n_ys)]
    xs_body_vars = body.invars[nc + ncar:]
    # closure constants register ONCE — a fresh _walk per timestep
    # would duplicate them T times in the initializer list
    const_names = {cv: ctx.add_const(onp.asarray(c), "scanc")
                   for cv, c in zip(body.constvars, closed.consts)}
    order = range(T - 1, -1, -1) if p.get("reverse") else range(T)
    for t in order:
        xt = []
        for xi, bv in zip(xs, xs_body_vars):
            g = ctx.fresh("scan_x")
            ctx.add_node(
                "Gather",
                [xi, ctx.add_const(onp.asarray(t, onp.int64))], [g],
                [_attr_i("axis", 0)])
            # 0-d consts decode as shape (1,) through the proto layer,
            # leaving a stray leading axis — pin the body's static
            # per-step shape
            r = ctx.fresh("scan_xr")
            ctx.add_node("Reshape",
                         [g, _shape_const(ctx, bv.aval.shape)], [r])
            xt.append(r)
        inner_env = dict(zip(body.invars, consts_in + carry + xt))
        inner_env.update(const_names)
        _walk(ctx, body, [], inner_env)
        step_out = [ctx.name_of(ov, inner_env) for ov in body.outvars]
        carry = step_out[:ncar]
        for k, y in enumerate(step_out[ncar:]):
            ys[k].append(_unsqueeze0(ctx, y, "scan_y"))
    for i in range(ncar):
        ctx.add_node("Identity", [carry[i]], [outs[i]])
    for k in range(n_ys):
        seq = ys[k][::-1] if p.get("reverse") else ys[k]
        if len(seq) == 1:
            ctx.add_node("Identity", seq, [outs[ncar + k]])
        else:
            ctx.add_node("Concat", seq, [outs[ncar + k]],
                         [_attr_i("axis", 0)])


def _sort_eqn(ctx, eqn, ins, outs, in_avals):
    """lax.sort (jnp.sort/argsort) via full-width TopK (ascending);
    co-sorted operands follow through GatherElements. Multi-key sorts
    (jnp.lexsort) cannot map onto single-key TopK and refuse loudly
    rather than exporting a wrong permutation."""
    if eqn.params.get("num_keys", 1) > 1:
        raise NotImplementedError(
            "multi-key lax.sort (jnp.lexsort) has no ONNX translation "
            "— ONNX TopK sorts by one key")
    axis = eqn.params["dimension"]
    n = in_avals[0].shape[axis]
    vals = ctx.fresh("sort_v")
    idxs = ctx.fresh("sort_i")
    ctx.add_node("TopK",
                 [ins[0], ctx.add_const(onp.asarray([n], onp.int64))],
                 [vals, idxs],
                 [_attr_i("axis", axis), _attr_i("largest", 0),
                  _attr_i("sorted", 1)])
    ctx.add_node("Identity", [vals], [outs[0]])
    for i in range(1, len(ins)):
        ctx.add_node("GatherElements", [ins[i], idxs], [outs[i]],
                     [_attr_i("axis", axis)])


def _topk_eqn(ctx, eqn, ins, outs, in_avals):
    """lax.top_k → ONNX TopK on the last axis (+ int32 index cast)."""
    k = eqn.params["k"]
    axis = len(in_avals[0].shape) - 1
    i64 = ctx.fresh("topk_i64")
    ctx.add_node("TopK",
                 [ins[0], ctx.add_const(onp.asarray([k], onp.int64))],
                 [outs[0], i64],
                 [_attr_i("axis", axis), _attr_i("largest", 1),
                  _attr_i("sorted", 1)])
    ctx.add_node("Cast", [i64], [outs[1]],
                 [_attr_i("to", 6)])  # int32 (jax top_k index dtype)


def _gather_eqn(ctx, eqn, ins, outs, in_avals):
    """lax.gather, simple-take form (jnp.take / embedding lookup):
    one indexed axis collapsed, full slices elsewhere → ONNX Gather.
    The general strided-window form has no ONNX analogue and raises."""
    dn = eqn.params["dimension_numbers"]
    sizes = eqn.params["slice_sizes"]
    shape = in_avals[0].shape
    batching = tuple(getattr(dn, "operand_batching_dims", ()))
    one_axis = (len(dn.start_index_map) == 1 and
                tuple(dn.collapsed_slice_dims)
                == tuple(dn.start_index_map))
    axis = dn.start_index_map[0] if one_axis else None

    idx = ins[1]
    idx_shape = in_avals[1].shape
    if idx_shape and idx_shape[-1] == 1:  # drop the index-vector dim
        r = ctx.fresh("gather_idx")
        ctx.add_node("Reshape",
                     [idx, _shape_const(ctx, idx_shape[:-1])], [r])
        idx = r

    if one_axis and not batching and \
            all(sizes[d] == shape[d] for d in range(len(shape))
                if d != axis) and sizes[axis] == 1:
        # take/embedding form: one indexed axis, full slices elsewhere
        ctx.add_node("Gather", [ins[0], idx], outs,
                     [_attr_i("axis", axis)])
    elif one_axis and not dn.offset_dims and \
            all(s == 1 for s in sizes) and \
            tuple(sorted(batching + (axis,))) == tuple(
                range(len(shape))):
        # take_along_axis form: every other dim batched elementwise
        ctx.add_node("GatherElements", [ins[0], idx], outs,
                     [_attr_i("axis", axis)])
    else:
        raise NotImplementedError(
            "general lax.gather (strided/multi-axis) has no ONNX "
            "translation; only take/embedding/take_along_axis-style "
            "gathers export")


def _dynamic_slice_eqn(ctx, eqn, ins, outs):
    """lax.dynamic_slice → ONNX Slice with runtime starts, clamped to
    [0, dim - size] per jax semantics (an out-of-range start slides
    the window back instead of shortening the result)."""
    sizes = eqn.params["slice_sizes"]
    op_shape = eqn.invars[0].aval.shape
    parts = []
    for s in ins[1:]:
        u = _unsqueeze0(ctx, s, "ds_s")
        c = ctx.fresh("ds_c")
        ctx.add_node("Cast", [u], [c], [_attr_i("to", 7)])  # int64
        parts.append(c)
    raw = ctx.fresh("ds_raw")
    if len(parts) == 1:
        ctx.add_node("Identity", parts, [raw])
    else:
        ctx.add_node("Concat", parts, [raw], [_attr_i("axis", 0)])
    lo = ctx.fresh("ds_lo")
    ctx.add_node("Max", [raw, ctx.add_const(
        onp.zeros(len(sizes), onp.int64))], [lo])
    starts = ctx.fresh("ds_starts")
    ctx.add_node("Min", [lo, ctx.add_const(onp.asarray(
        [d - s for d, s in zip(op_shape, sizes)], onp.int64))],
        [starts])
    ends = ctx.fresh("ds_ends")
    ctx.add_node("Add",
                 [starts, ctx.add_const(onp.asarray(sizes, onp.int64))],
                 [ends])
    ctx.add_node("Slice", [
        ins[0], starts, ends,
        ctx.add_const(onp.asarray(range(len(sizes)), onp.int64))], outs)


def _try_fold(ctx, eqn, env):
    """Evaluate an equation at export time when every input is a known
    constant; PRNG plumbing, iota, eps chains all fold away."""
    from jax._src.core import Literal
    vals = []
    for v in eqn.invars:
        if isinstance(v, Literal):
            vals.append(v.val)
        else:
            nm = env.get(v)
            if nm is None or nm not in ctx.const_vals:
                return False
            vals.append(ctx.const_vals[nm])
    try:
        if eqn.primitive.name in ("pjit", "jit", "closed_call",
                                  "custom_jvp_call", "custom_vjp_call",
                                  "remat", "checkpoint"):
            return False  # inlined elsewhere
        out = eqn.primitive.bind(*[jnp.asarray(v) for v in vals],
                                 **eqn.params)
    except Exception:  # noqa: BLE001 — fall back to node translation
        return False
    outs = out if eqn.primitive.multiple_results else [out]
    for var, val in zip(eqn.outvars, outs):
        host = onp.asarray(val)
        name = ctx.add_const(host, "folded")
        env[var] = name
    return True


def _inline_params(eqn):
    """Return the sub-jaxpr to inline for call-like primitives."""
    prim = eqn.primitive.name
    if prim in ("pjit", "jit"):
        return eqn.params["jaxpr"]
    if prim == "closed_call":
        return eqn.params["call_jaxpr"]
    if prim == "custom_jvp_call":
        return eqn.params["call_jaxpr"]
    if prim == "custom_vjp_call":
        return eqn.params.get("call_jaxpr") or eqn.params.get("fun_jaxpr")
    if prim in ("remat", "checkpoint", "remat2"):
        from jax._src.core import ClosedJaxpr
        j = eqn.params["jaxpr"]
        return j if isinstance(j, ClosedJaxpr) else None
    return None


def _walk(ctx, jaxpr, consts, env):
    from jax._src.core import ClosedJaxpr, Literal
    for var, const in zip(jaxpr.constvars, consts):
        host = onp.asarray(const)
        env[var] = ctx.add_const(host, "c")
    for eqn in jaxpr.eqns:
        sub = _inline_params(eqn)
        if sub is not None:
            closed = sub if isinstance(sub, ClosedJaxpr) else None
            inner = closed.jaxpr if closed else sub
            inner_consts = closed.consts if closed else []
            # fresh scope per inlined instance: jax caches sub-jaxprs,
            # so the same Var objects appear at every call site
            inner_env = {}
            for iv, ov in zip(inner.invars, eqn.invars):
                if isinstance(ov, Literal):
                    inner_env[iv] = ctx.add_const(
                        onp.asarray(ov.val), "lit")
                else:
                    inner_env[iv] = ctx.name_of(ov, env)
            _walk(ctx, inner, inner_consts, inner_env)
            for ov, iv in zip(eqn.outvars, inner.outvars):
                if isinstance(iv, Literal):
                    env[ov] = ctx.add_const(
                        onp.asarray(iv.val), "lit")
                else:
                    env[ov] = ctx.name_of(iv, inner_env)
            continue
        if _try_fold(ctx, eqn, env):
            continue
        _translate_eqn(ctx, eqn, env)


def export_model(net, input_shapes, onnx_file_path="model.onnx",
                 input_type="float32", dynamic_batch=False,
                 verbose=False, opset_version=13):
    """Export a HybridBlock to an ONNX file (parity:
    contrib/onnx/mx2onnx/export_model.py:export_model).

    Traces the net in inference mode (the same traced program
    hybridize compiles), translates each jaxpr equation to ONNX nodes,
    and writes a self-contained opset-13 ModelProto.
    """
    import mxnet_tpu as mx
    from ...ndarray.ndarray import NDArray
    from ... import engine, autograd
    from ...gluon import _deferred

    if isinstance(input_shapes, tuple):
        input_shapes = [input_shapes]
    xs = [mx.np.random.uniform(size=s).astype(input_type)
          for s in input_shapes]
    with autograd.pause():
        net(*xs)  # materialize deferred params eagerly

    params = list(net.collect_params().values())
    param_names = list(net.collect_params().keys())
    param_datas = [p.data()._data for p in params]

    def fwd(param_datas, input_datas):
        saved = [p._data._data for p in params]
        in_nds = [NDArray(engine.track(d)) for d in input_datas]
        try:
            with autograd.pause(), _deferred.trace_scope():
                for p, d in zip(params, param_datas):
                    p._data._data = d
                out = net(*in_nds)
        finally:
            for p, s in zip(params, saved):
                p._data._data = s
        outs = out if isinstance(out, tuple) else (out,)
        return tuple(o._data for o in outs)

    closed = jax.make_jaxpr(fwd)([d for d in param_datas],
                                 [x._data for x in xs])
    ctx = _Ctx()
    jaxpr = closed.jaxpr
    # invars: params then inputs (flattened in pytree order)
    n_params = len(param_datas)
    flat_invars = jaxpr.invars
    assert len(flat_invars) == n_params + len(xs), \
        (len(flat_invars), n_params, len(xs))
    env = {}
    for var, pname, pdata in zip(flat_invars[:n_params], param_names,
                                 param_datas):
        host = onp.asarray(pdata.astype(jnp.float32)
                           if str(pdata.dtype) == "bfloat16" else pdata)
        env[var] = pname
        ctx.initializers[pname] = host
        # params are NOT fold-constants: keep them live initializers
    graph_inputs = []
    for i, var in enumerate(flat_invars[n_params:]):
        name = f"data{i}" if i else "data"
        env[var] = name
        shape = list(var.aval.shape)
        if dynamic_batch:
            shape[0] = "batch"
        graph_inputs.append({
            "name": name,
            "elem_type": proto.np_dtype_to_onnx(var.aval.dtype),
            "shape": shape})

    _walk(ctx, jaxpr, closed.consts, env)

    graph_outputs = []
    out_nodes = []
    from jax._src.core import Literal
    for i, var in enumerate(jaxpr.outvars):
        oname = f"output{i}" if i else "output"
        src = (ctx.add_const(onp.asarray(var.val), "lit")
               if isinstance(var, Literal) else ctx.name_of(var, env))
        out_nodes.append({"op_type": "Identity", "input": [src],
                          "output": [oname], "name": f"out_{i}",
                          "attribute": []})
        shape = list(var.aval.shape)
        if dynamic_batch:
            shape[0] = "batch"
        graph_outputs.append({
            "name": oname,
            "elem_type": proto.np_dtype_to_onnx(var.aval.dtype),
            "shape": shape})

    graph = {
        "name": type(net).__name__,
        "node": ctx.nodes + out_nodes,
        "initializer": [proto.numpy_to_tensor(arr, nm)
                        for nm, arr in ctx.initializers.items()],
        "input": graph_inputs,
        "output": graph_outputs,
    }
    blob = proto.encode_model(graph, opset_version=opset_version)
    with open(onnx_file_path, "wb") as f:
        f.write(blob)
    if verbose:
        print(f"[mx2onnx] wrote {onnx_file_path}: "
              f"{len(ctx.nodes)} nodes, "
              f"{len(ctx.initializers)} initializers, "
              f"{len(blob)} bytes")
    return onnx_file_path

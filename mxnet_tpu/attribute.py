"""Attribute scoping for symbol construction.

Parity target: ``python/mxnet/attribute.py`` (AttrScope
``attribute.py:23``). Symbols created inside a ``with AttrScope(...)``
block inherit the scope's attributes; nested scopes merge with inner
values winning — the reference contract.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current"]

_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = [AttrScope()]
    return _tls.stack


class AttrScope:
    """Holds a dict of string attributes applied to symbols created
    within the scope."""

    def __init__(self, **kwargs):
        for v in kwargs.values():
            if not isinstance(v, str):
                raise ValueError(
                    "attributes need to be strings; got "
                    f"{type(v).__name__}")
        self._attr = dict(kwargs)

    def get(self, attr=None):
        """Merge scope attributes into ``attr`` (user values win)."""
        if not self._attr:
            return attr if attr else {}
        merged = dict(self._attr)
        if attr:
            merged.update(attr)
        return merged

    def __enter__(self):
        parent = _stack()[-1]
        merged = dict(parent._attr)
        merged.update(self._attr)
        self._attr = merged
        _stack().append(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        st = _stack()
        if len(st) > 1 and st[-1] is self:
            st.pop()


def current():
    """The innermost active AttrScope."""
    return _stack()[-1]

"""Runtime feature detection (parity: python/mxnet/runtime.py over
src/libinfo.cc)."""
from __future__ import annotations

from collections import OrderedDict

import jax


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"✔ {self.name}" if self.enabled else f"✖ {self.name}"


def _detect():
    try:
        backend = jax.default_backend()
    except Exception:
        backend = "cpu"
    devices = jax.devices()
    feats = OrderedDict()
    feats["TPU"] = backend not in ("cpu",)
    feats["CUDA"] = False
    feats["CUDNN"] = False
    feats["NCCL"] = False
    feats["XLA"] = True
    feats["PJRT"] = True
    feats["PALLAS"] = True
    feats["BF16"] = True
    feats["INT64_TENSOR_SIZE"] = True
    feats["OPENMP"] = True
    feats["DIST_KVSTORE"] = True
    feats["F16C"] = True
    feats["MKLDNN"] = False
    feats["ONEDNN"] = False
    feats["TENSORRT"] = False
    feats["OPENCV"] = False
    feats["PROFILER"] = True
    feats["DEVICE_COUNT"] = len(devices) > 0
    return feats


class LibInfo:
    def features(self):
        return [Feature(k, v) for k, v in _detect().items()]


def feature_list():
    return LibInfo().features()


class Features(OrderedDict):
    instance = None

    def __init__(self):
        super().__init__([(f.name, f) for f in feature_list()])

    def __repr__(self):
        return str(list(self.values()))

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError(f"Feature '{feature_name}' is unknown, "
                               f"known features are: {list(self.keys())}")
        return self[feature_name].enabled

"""mx.library — out-of-tree extension loading (parity:
python/mxnet/library.py + include/mxnet/lib_api.h).

The reference dlopens extension libraries exposing custom ops through
a self-contained C ABI (lib_api.h's MXTensor). The TPU-native ABI here
is deliberately small and buffer-oriented:

    // exported by the extension .so
    const char* mxtpu_ext_op_list();
    //   "name:arity,name:arity,..."  (arity 1 or 2; float32 elementwise)
    void <name>(const float* a, const float* b_or_null,
                float* out, int64_t n);

`load(path)` registers every listed op into ``mx.npx`` as a host
callback: the op is jit-compatible (`jax.custom-free pure_callback`),
so extension ops work eagerly AND inside hybridized graphs — XLA
treats them as opaque host calls, the TPU analogue of the reference's
engine-pushed extension kernels.
"""
from __future__ import annotations

import ctypes
import os

import numpy as onp
import jax
import jax.numpy as jnp

__all__ = ["load", "loaded_libraries", "apply_pass", "partition",
           "graph_passes", "partitioners"]

_LOADED = {}
_PASSES = {}
_PARTITIONERS = {}


def loaded_libraries():
    return dict(_LOADED)


def graph_passes():
    """Registered out-of-tree graph passes (name → callable)."""
    return dict(_PASSES)


def partitioners():
    """Registered out-of-tree partitioners (name → callable)."""
    return dict(_PARTITIONERS)


def _make_op(cfn, name, arity):
    def host_call(*hosts):
        a = onp.ascontiguousarray(hosts[0], dtype=onp.float32)
        b = None
        if arity == 2:
            b = onp.ascontiguousarray(
                onp.broadcast_to(hosts[1], a.shape), dtype=onp.float32)
        out = onp.empty_like(a)
        cfn(a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            b.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
            if b is not None else None,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(a.size))
        return out

    def op(*args, **kwargs):
        from .ops import apply_op
        from .ndarray.ndarray import NDArray
        from . import engine

        nds = [a if isinstance(a, NDArray)
               else NDArray(engine.track(jnp.asarray(a, jnp.float32)))
               for a in args[:arity]]

        def fn(*datas):
            shape_dtype = jax.ShapeDtypeStruct(datas[0].shape,
                                               jnp.float32)
            return jax.pure_callback(
                host_call, shape_dtype,
                *[d.astype(jnp.float32) for d in datas],
                vmap_method="sequential")

        return apply_op(fn, *nds, name=f"ext_{name}")

    op.__name__ = name
    op.__doc__ = (f"Extension op '{name}' (arity {arity}) loaded via "
                  "mx.library.load — runs as a host callback, usable "
                  "eagerly and under hybridize.")
    return op


def load(path, verbose=True):
    """dlopen an extension library and register its ops into mx.npx
    (parity: mx.library.load → MXLoadLib)."""
    from . import numpy_extension as npx

    path = os.path.abspath(path)
    lib = ctypes.CDLL(path)
    try:
        lib.mxtpu_ext_op_list.restype = ctypes.c_char_p
        listing = lib.mxtpu_ext_op_list().decode()
    except AttributeError:
        raise RuntimeError(
            f"{path} does not export mxtpu_ext_op_list(); not a "
            "mxnet_tpu extension library")
    registered = []
    for entry in listing.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, arity_s = entry.partition(":")
        arity = int(arity_s or "1")
        if arity not in (1, 2):
            raise RuntimeError(f"op {name!r}: unsupported arity {arity}")
        cfn = getattr(lib, name)
        cfn.restype = None
        cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        setattr(npx, name, _make_op(cfn, name, arity))
        registered.append(name)

    # optional: graph passes and partitioners (parity:
    # include/mxnet/lib_api.h REGISTER_PASS / REGISTER_PARTITIONER —
    # the reference feeds extensions the nnvm JSON graph; here they
    # receive the mx.sym serialized DAG JSON. Returned pointers stay
    # valid until the next call into the library, so copy eagerly.)
    def _c_json_fn(sym_name):
        cjf = getattr(lib, sym_name)
        cjf.restype = ctypes.c_char_p
        cjf.argtypes = [ctypes.c_char_p]

        def call(graph_json: str) -> str:
            out = cjf(graph_json.encode())
            if out is None:
                raise RuntimeError(
                    f"extension {sym_name!r} returned NULL")
            return out.decode()
        call.__name__ = sym_name
        return call

    passes, parts = [], []
    for lister, registry, out in (
            ("mxtpu_ext_pass_list", _PASSES, passes),
            ("mxtpu_ext_partitioner_list", _PARTITIONERS, parts)):
        try:
            fn = getattr(lib, lister)
        except AttributeError:
            continue
        fn.restype = ctypes.c_char_p
        for name in fn().decode().split(","):
            name = name.strip()
            if name:
                registry[name] = _c_json_fn(name)
                out.append(name)

    _LOADED[path] = registered + passes + parts
    if verbose:
        print(f"[mx.library] loaded {len(registered)} op(s), "
              f"{len(passes)} pass(es), {len(parts)} partitioner(s) "
              f"from {path}")
    return _LOADED[path]


def apply_pass(symbol, name):
    """Run a loaded extension graph pass over a Symbol: the pass sees
    the serialized DAG JSON and returns a rewritten graph (parity:
    HybridBlock.optimize_for with a lib_api graph pass)."""
    from .symbol import load_json
    if name not in _PASSES:
        raise ValueError(f"no loaded graph pass {name!r}; loaded: "
                         f"{sorted(_PASSES)}")
    return load_json(_PASSES[name](symbol.tojson()))


def partition(symbol, name):
    """Run a loaded extension partitioner: it returns groups of node
    names; each group folds into ONE `_subgraph` node whose attr
    embeds the sub-DAG (parity: SubgraphProperty-based partitioning,
    src/operator/subgraph/build_subgraph.cc)."""
    import json as _json
    if name not in _PARTITIONERS:
        raise ValueError(f"no loaded partitioner {name!r}; loaded: "
                         f"{sorted(_PARTITIONERS)}")
    groups = _json.loads(_PARTITIONERS[name](symbol.tojson()))
    out = symbol
    for group in groups:
        if group:
            out = _fold_group(out, group)
    return out


def _fold_group(sym, names):
    """Fold the named nodes of `sym` into one `_subgraph` node.

    Constraints (v1, matching the reference's single-output subgraph
    ops): the group must have exactly one output entry consumed
    outside the group; groups violating this are skipped with a
    warning."""
    import warnings
    from .symbol.symbol import Symbol, _Node

    nodes = sym._nodes
    name_to_id = {n.name: i for i, n in enumerate(nodes)}
    gids = {name_to_id[n] for n in names if n in name_to_id}
    gids = {i for i in gids if nodes[i].op != "null"}
    if not gids:
        return sym

    consumed = set()
    for i, n in enumerate(nodes):
        if i in gids:
            continue
        for (j, idx) in n.inputs:
            if j in gids:
                consumed.add((j, idx))
    for (j, idx) in sym._outputs:
        if j in gids:
            consumed.add((j, idx))
    if len(consumed) != 1:
        warnings.warn(
            f"partitioner group {sorted(names)} has "
            f"{len(consumed)} external outputs; only single-output "
            "groups fold — skipped")
        return sym
    out_entry = next(iter(consumed))

    # ordered external inputs of the group
    ext_in = []
    for i in sorted(gids):
        for (j, idx) in nodes[i].inputs:
            if j not in gids and (j, idx) not in ext_in:
                ext_in.append((j, idx))

    # build the embedded subgraph (vars __sg_in_k for external inputs)
    sub_nodes, id_map = [], {}
    for k, (j, idx) in enumerate(ext_in):
        id_map[("ext", j, idx)] = len(sub_nodes)
        sub_nodes.append(_Node("null", f"__sg_in_{k}", [], {}))
    for i in sorted(gids):
        new_inputs = []
        for (j, idx) in nodes[i].inputs:
            if j in gids:
                new_inputs.append((id_map[("g", j)], idx))
            else:
                new_inputs.append((id_map[("ext", j, idx)], 0))
        id_map[("g", i)] = len(sub_nodes)
        sub_nodes.append(_Node(nodes[i].op, nodes[i].name, new_inputs,
                               nodes[i].attrs))
    sub_sym = Symbol(sub_nodes,
                     [(id_map[("g", out_entry[0])], out_entry[1])])
    sub_json = sub_sym.tojson()

    # rebuild the outer graph: group nodes out, one _subgraph node in
    new_nodes, remap = [], {}
    insert_after = max(gids)
    sg_id = None
    sg_name = f"subgraph_{min(gids)}"
    for i, n in enumerate(nodes):
        if i in gids:
            pass
        else:
            remap[i] = len(new_nodes)
            new_nodes.append(n)  # inputs fixed in a second pass
        if i == insert_after:
            sg_id = len(new_nodes)
            new_nodes.append(_Node(
                "_subgraph", sg_name, list(ext_in),  # remapped below
                {"json": sub_json}))

    def map_entry(j, idx):
        if j in gids:
            return (sg_id, 0) if (j, idx) == out_entry else None
        return (remap[j], idx)

    fixed = []
    for pos, n in enumerate(new_nodes):
        if pos == sg_id:
            fixed.append(_Node(n.op, n.name,
                               [(remap[j], idx) for (j, idx) in n.inputs],
                               n.attrs))
        else:
            fixed.append(_Node(n.op, n.name,
                               [map_entry(j, idx) for (j, idx)
                                in n.inputs], n.attrs))
    new_outputs = [map_entry(j, idx) for (j, idx) in sym._outputs]
    return Symbol(fixed, new_outputs)

"""mx.library — out-of-tree extension loading (parity:
python/mxnet/library.py + include/mxnet/lib_api.h).

The reference dlopens extension libraries exposing custom ops through
a self-contained C ABI (lib_api.h's MXTensor). The TPU-native ABI here
is deliberately small and buffer-oriented:

    // exported by the extension .so
    const char* mxtpu_ext_op_list();
    //   "name:arity,name:arity,..."  (arity 1 or 2; float32 elementwise)
    void <name>(const float* a, const float* b_or_null,
                float* out, int64_t n);

`load(path)` registers every listed op into ``mx.npx`` as a host
callback: the op is jit-compatible (`jax.custom-free pure_callback`),
so extension ops work eagerly AND inside hybridized graphs — XLA
treats them as opaque host calls, the TPU analogue of the reference's
engine-pushed extension kernels.
"""
from __future__ import annotations

import ctypes
import os

import numpy as onp
import jax
import jax.numpy as jnp

__all__ = ["load", "loaded_libraries"]

_LOADED = {}


def loaded_libraries():
    return dict(_LOADED)


def _make_op(cfn, name, arity):
    def host_call(*hosts):
        a = onp.ascontiguousarray(hosts[0], dtype=onp.float32)
        b = None
        if arity == 2:
            b = onp.ascontiguousarray(
                onp.broadcast_to(hosts[1], a.shape), dtype=onp.float32)
        out = onp.empty_like(a)
        cfn(a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            b.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
            if b is not None else None,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            ctypes.c_int64(a.size))
        return out

    def op(*args, **kwargs):
        from .ops import apply_op
        from .ndarray.ndarray import NDArray
        from . import engine

        nds = [a if isinstance(a, NDArray)
               else NDArray(engine.track(jnp.asarray(a, jnp.float32)))
               for a in args[:arity]]

        def fn(*datas):
            shape_dtype = jax.ShapeDtypeStruct(datas[0].shape,
                                               jnp.float32)
            return jax.pure_callback(
                host_call, shape_dtype,
                *[d.astype(jnp.float32) for d in datas],
                vmap_method="sequential")

        return apply_op(fn, *nds, name=f"ext_{name}")

    op.__name__ = name
    op.__doc__ = (f"Extension op '{name}' (arity {arity}) loaded via "
                  "mx.library.load — runs as a host callback, usable "
                  "eagerly and under hybridize.")
    return op


def load(path, verbose=True):
    """dlopen an extension library and register its ops into mx.npx
    (parity: mx.library.load → MXLoadLib)."""
    from . import numpy_extension as npx

    path = os.path.abspath(path)
    lib = ctypes.CDLL(path)
    try:
        lib.mxtpu_ext_op_list.restype = ctypes.c_char_p
        listing = lib.mxtpu_ext_op_list().decode()
    except AttributeError:
        raise RuntimeError(
            f"{path} does not export mxtpu_ext_op_list(); not a "
            "mxnet_tpu extension library")
    registered = []
    for entry in listing.split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, _, arity_s = entry.partition(":")
        arity = int(arity_s or "1")
        if arity not in (1, 2):
            raise RuntimeError(f"op {name!r}: unsupported arity {arity}")
        cfn = getattr(lib, name)
        cfn.restype = None
        cfn.argtypes = [ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float),
                        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        setattr(npx, name, _make_op(cfn, name, arity))
        registered.append(name)
    _LOADED[path] = registered
    if verbose:
        print(f"[mx.library] loaded {len(registered)} op(s) from "
              f"{path}: {registered}")
    return registered
